"""Fused optimizer update: one flat program instead of per-leaf tree_maps.

The per-leaf updater path runs one optax ``update``/``apply_updates`` chain
per layer, which lowers to hundreds of tiny elementwise XLA ops on real
models — each a separate fusion with its own launch and layout overhead.
Here every group of layers that shares an updater config and dtype is
raveled into ONE flat vector, the optax transform runs once over it, and
the results are sliced back into the per-layer pytrees. Because every
shipped updater (nn/updaters.py) plus ``optax.clip`` /
``add_decayed_weights`` is purely elementwise, the fused math is
**bitwise identical** to the per-leaf path — concatenation commutes with
elementwise ops. Cross-leaf reductions (``clip_by_global_norm``) would
not commute; callers mark those members non-fusable via a ``None`` group
key and they keep the legacy per-member math.

The stored opt-state layout is untouched: states stay per-layer (so
checkpoints, the model serializer, and the executor's co-sharding specs
all see the exact structures they saw before) and are flattened/rebuilt
*inside* the traced update via slot-walking:

- the "template" is ``transform.init`` evaluated on the flat vector
  (``jax.eval_shape`` — no compute). Its leaves enumerate the state
  slots in DFS order: a leaf shaped ``(total,)`` is a *param slot* (mu,
  nu, trace, ...), anything else is a *scalar slot* (count, ...).
- each member's stored state flattens in the SAME slot order, with each
  param slot contributing that member's k_i param leaves contiguously
  (DFS keeps embedded param subtrees contiguous). So a single cursor
  walk converts per-member states <-> the flat state exactly.
- scalar slots (step counts) are taken from the first member: within a
  group every member is created by the same ``init`` and stepped by the
  same calls, so the counts are equal by construction.

``FusedUpdate.apply`` is pure — it is traced inside the existing train
steps AND inside the standalone donated update program the model
containers register (see ``_apply_updates_jitted``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import optax

_OVERRIDE: Optional[bool] = None


def fused_update_enabled() -> bool:
    """Fused updates are on by default; ``DL4JTPU_FUSED_UPDATE=0`` (env)
    or ``set_fused_update(False)`` forces the legacy per-leaf path. Read
    at optimizer-build time — call ``_build_optimizer()`` after toggling."""
    if _OVERRIDE is not None:
        return _OVERRIDE
    return os.environ.get("DL4JTPU_FUSED_UPDATE", "1").lower() not in (
        "0", "false", "off", "no")


def set_fused_update(flag: Optional[bool]) -> None:
    """Process-wide override (None restores the env default). Used by the
    bench fused-vs-per-leaf sub-row and tests; rebuild optimizers after."""
    global _OVERRIDE
    _OVERRIDE = flag


def _metrics():
    from deeplearning4j_tpu.monitor.metrics import get_registry
    reg = get_registry()
    return reg.gauge(
        "dl4jtpu_train_fused_groups",
        "Fused updater groups in the most recently built optimizer "
        "(0 = per-leaf path)")


@dataclass
class _Group:
    """Members fused into one flat transform (same updater config+dtype)."""
    transform: Any                       # optax GradientTransformation
    members: List[Any]                   # item keys, in build order
    dtype: Any


@dataclass
class FusedUpdate:
    """Grouped update plan for one model's (params, opt_state, grads).

    ``apply`` takes/returns dicts keyed like the build-time dicts; the
    containers adapt their list/dict layouts around it.
    """
    groups: List[_Group]
    fallback: List[Any]                  # keys updated with per-member math
    passthrough: List[Any]               # empty-params keys (copied as-is)
    transforms: Dict[Any, Any]
    constraints: Dict[Any, Callable]

    @property
    def fused_keys(self) -> List[Any]:
        return [k for g in self.groups for k in g.members]

    def apply(self, params: Dict, opt_state: Dict, grads: Dict
              ) -> Tuple[Dict, Dict]:
        new_params: Dict[Any, Any] = {}
        new_opt: Dict[Any, Any] = {}
        for k in self.passthrough:
            new_params[k], new_opt[k] = params[k], opt_state[k]
        for k in self.fallback:
            u, o = self.transforms[k].update(grads[k], opt_state[k],
                                             params[k])
            p = optax.apply_updates(params[k], u)
            new_params[k] = self.constraints[k](p)
            new_opt[k] = o
        for g in self.groups:
            self._apply_group(g, params, opt_state, grads,
                              new_params, new_opt)
        return new_params, new_opt

    # ------------------------------------------------------------ fused core
    def _apply_group(self, g, params, opt_state, grads, new_params, new_opt):
        # ravel every member's param/grad leaves into one flat vector
        metas = []            # (key, treedef, [(shape, dtype), ...])
        pf_parts, gf_parts = [], []
        for k in g.members:
            leaves, treedef = jtu.tree_flatten(params[k])
            gleaves = jtu.tree_flatten(grads[k])[0]
            metas.append((k, treedef, [(l.shape, l.dtype) for l in leaves]))
            pf_parts += [l.ravel() for l in leaves]
            gf_parts += [gl.ravel() for gl in gleaves]
        pf = jnp.concatenate(pf_parts) if len(pf_parts) > 1 else pf_parts[0]
        gf = jnp.concatenate(gf_parts) if len(gf_parts) > 1 else gf_parts[0]
        total = pf.size

        # slot-walk the stored per-member states into the flat state
        tmpl_leaves, tmpl_def = jtu.tree_flatten(
            jax.eval_shape(g.transform.init, jax.ShapeDtypeStruct(
                pf.shape, pf.dtype)))
        mstates = [jtu.tree_flatten(opt_state[k]) for k in g.members]
        cursors = [0] * len(g.members)
        flat_state_leaves = []
        for t in tmpl_leaves:
            if tuple(t.shape) == (int(total),):
                parts = []
                for mi, (_, _, shapes) in enumerate(metas):
                    kk = len(shapes)
                    run = mstates[mi][0][cursors[mi]:cursors[mi] + kk]
                    cursors[mi] += kk
                    parts += [r.ravel() for r in run]
                flat_state_leaves.append(
                    jnp.concatenate(parts) if len(parts) > 1 else parts[0])
            else:
                # scalar slot (e.g. step count): equal across members
                flat_state_leaves.append(mstates[0][0][cursors[0]])
                for mi in range(len(g.members)):
                    cursors[mi] += 1
        flat_state = jtu.tree_unflatten(tmpl_def, flat_state_leaves)

        # one update over the whole group
        u, new_flat = g.transform.update(gf, flat_state, pf)
        new_pf = optax.apply_updates(pf, u)

        # slice params back out and re-apply per-layer constraints
        off = 0
        for k, treedef, shapes in metas:
            lvs = []
            for shp, _dt in shapes:
                n = int(np.prod(shp)) if shp else 1
                lvs.append(new_pf[off:off + n].reshape(shp))
                off += n
            p = jtu.tree_unflatten(treedef, lvs)
            new_params[k] = self.constraints[k](p)

        # slot-walk the new flat state back into per-member states
        new_flat_leaves = jtu.tree_flatten(new_flat)[0]
        member_leaves: List[List[Any]] = [[] for _ in g.members]
        for t, s in zip(tmpl_leaves, new_flat_leaves):
            if tuple(t.shape) == (int(total),):
                off = 0
                for mi, (_, _, shapes) in enumerate(metas):
                    for shp, _dt in shapes:
                        n = int(np.prod(shp)) if shp else 1
                        member_leaves[mi].append(
                            s[off:off + n].reshape(shp))
                        off += n
            else:
                for mi in range(len(g.members)):
                    member_leaves[mi].append(s)
        for mi, (k, _, _) in enumerate(metas):
            new_opt[k] = jtu.tree_unflatten(mstates[mi][1],
                                            member_leaves[mi])


def _identity(p):
    return p


def build_fused_update(params: Dict, transforms: Dict,
                       group_keys: Dict, constraints: Optional[Dict] = None
                       ) -> FusedUpdate:
    """Group items by (group key, dtype) into a :class:`FusedUpdate`.

    ``params`` / ``transforms`` / ``group_keys`` are dicts over the same
    keys. ``group_keys[k]`` is any hashable describing the updater config
    (the containers use the updater's sorted-JSON dict) — members fuse
    only when BOTH the key and every param leaf's dtype match. ``None``
    marks a member non-fusable (frozen layers, cross-leaf clipping);
    empty param trees pass through untouched.
    """
    constraints = constraints or {}
    groups: Dict[Tuple, _Group] = {}
    fallback: List[Any] = []
    passthrough: List[Any] = []
    for k, p in params.items():
        leaves = jtu.tree_leaves(p)
        if not leaves:
            passthrough.append(k)
            continue
        gk = group_keys.get(k)
        dtypes = {l.dtype for l in leaves}
        if gk is None or len(dtypes) != 1:
            fallback.append(k)
            continue
        bucket = (gk, next(iter(dtypes)))
        if bucket not in groups:
            groups[bucket] = _Group(transform=transforms[k], members=[],
                                    dtype=bucket[1])
        groups[bucket].members.append(k)
    fu = FusedUpdate(groups=list(groups.values()), fallback=fallback,
                     passthrough=passthrough, transforms=dict(transforms),
                     constraints={k: constraints.get(k, _identity)
                                  for k in params})
    try:
        _metrics().set(len(fu.groups))
    except Exception:
        pass
    return fu
