"""Weight initialization schemes.

Parity surface: the reference's ``WeightInit`` enum (20 schemes,
deeplearning4j-nn/.../nn/weights/WeightInit.java:68) and ``WeightInitUtil``.
Implemented as pure functions of a jax PRNG key — fully deterministic and
reproducible across hosts, unlike the reference's shared java.util.Random.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _fans(shape, fan_in=None, fan_out=None):
    """fan_in/fan_out for a weight shape. Dense: (in, out). Conv (our NHWC
    HWIO layout): (h, w, in, out) → fan_in = h*w*in, fan_out = h*w*out —
    matches reference WeightInitUtil conventions."""
    if fan_in is not None and fan_out is not None:
        return float(fan_in), float(fan_out)
    if len(shape) == 1:
        return float(shape[0]), float(shape[0])
    if len(shape) == 2:
        return float(shape[0]), float(shape[1])
    receptive = 1
    for s in shape[:-2]:
        receptive *= s
    return float(receptive * shape[-2]), float(receptive * shape[-1])


def init_weights(rng, shape, scheme="xavier", distribution=None, dtype=jnp.float32,
                 fan_in=None, fan_out=None):
    """Initialize a weight array.

    scheme: one of the reference's WeightInit scheme names (case-insensitive).
    distribution: (kind, *args) used when scheme == 'distribution',
        e.g. ("normal", mean, std) or ("uniform", lo, hi).
    """
    scheme = str(scheme).lower()
    fi, fo = _fans(shape, fan_in, fan_out)
    n = fi + fo

    if scheme == "zero":
        return jnp.zeros(shape, dtype)
    if scheme == "ones":
        return jnp.ones(shape, dtype)
    if scheme == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY weight init requires a square 2d shape")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == "normal":
        # reference NORMAL: N(0, 1/sqrt(fan_in))
        return jax.random.normal(rng, shape, dtype) / jnp.sqrt(fi)
    if scheme == "lecun_normal":
        return jax.random.normal(rng, shape, dtype) * jnp.sqrt(1.0 / fi)
    if scheme == "lecun_uniform":
        # reference WeightInitUtil.java:88: U[-b,b], b = 3/sqrt(fanIn)
        # (NOT Keras's sqrt(3/fanIn) — parity follows the reference code)
        b = 3.0 / jnp.sqrt(fi)
        return jax.random.uniform(rng, shape, dtype, -b, b)
    if scheme == "uniform":
        a = jnp.sqrt(1.0 / fi)
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if scheme == "xavier":
        return jax.random.normal(rng, shape, dtype) * jnp.sqrt(2.0 / n)
    if scheme == "xavier_uniform":
        b = jnp.sqrt(6.0 / n)
        return jax.random.uniform(rng, shape, dtype, -b, b)
    if scheme == "xavier_fan_in":
        return jax.random.normal(rng, shape, dtype) / jnp.sqrt(fi)
    if scheme == "xavier_legacy":
        # reference WeightInitUtil.java:106: randn / sqrt(shape[0]+shape[1])
        # — in its OIHW layout those are the out/in CHANNEL dims, so for
        # our HWIO kernels the equivalent dims are the trailing two
        return jax.random.normal(rng, shape, dtype) / jnp.sqrt(
            shape[-2] + shape[-1])
    if scheme == "relu":
        return jax.random.normal(rng, shape, dtype) * jnp.sqrt(2.0 / fi)
    if scheme == "relu_uniform":
        b = jnp.sqrt(6.0 / fi)
        return jax.random.uniform(rng, shape, dtype, -b, b)
    if scheme == "sigmoid_uniform":
        b = 4.0 * jnp.sqrt(6.0 / n)
        return jax.random.uniform(rng, shape, dtype, -b, b)
    if scheme in ("var_scaling_normal_fan_in", "varscalingnormalfanin"):
        return jax.random.normal(rng, shape, dtype) * jnp.sqrt(1.0 / fi)
    if scheme in ("var_scaling_normal_fan_out", "varscalingnormalfanout"):
        return jax.random.normal(rng, shape, dtype) * jnp.sqrt(1.0 / fo)
    if scheme in ("var_scaling_normal_fan_avg", "varscalingnormalfanavg"):
        return jax.random.normal(rng, shape, dtype) * jnp.sqrt(2.0 / n)
    # VAR_SCALING_UNIFORM_*: reference WeightInitUtil.java:136-147 uses
    # bound 3/sqrt(fan) (not Keras's sqrt(3/fan)); parity follows the code
    if scheme in ("var_scaling_uniform_fan_in", "varscalinguniformfanin"):
        b = 3.0 / jnp.sqrt(fi)
        return jax.random.uniform(rng, shape, dtype, -b, b)
    if scheme in ("var_scaling_uniform_fan_out", "varscalinguniformfanout"):
        b = 3.0 / jnp.sqrt(fo)
        return jax.random.uniform(rng, shape, dtype, -b, b)
    if scheme in ("var_scaling_uniform_fan_avg", "varscalinguniformfanavg"):
        b = 3.0 / jnp.sqrt(n / 2.0)
        return jax.random.uniform(rng, shape, dtype, -b, b)
    if scheme == "distribution":
        if distribution is None:
            raise ValueError("scheme='distribution' requires a distribution tuple")
        kind = str(distribution[0]).lower()
        args = distribution[1:]
        if kind == "normal" or kind == "gaussian":
            mean, std = (args + (0.0, 1.0))[:2] if args else (0.0, 1.0)
            return mean + std * jax.random.normal(rng, shape, dtype)
        if kind == "uniform":
            lo, hi = args if len(args) == 2 else (-1.0, 1.0)
            return jax.random.uniform(rng, shape, dtype, lo, hi)
        if kind == "constant":
            return jnp.full(shape, args[0], dtype)
        if kind == "truncated_normal":
            mean, std = (args + (0.0, 1.0))[:2] if args else (0.0, 1.0)
            return mean + std * jax.random.truncated_normal(rng, -2.0, 2.0, shape, dtype)
        raise ValueError(f"Unknown distribution kind '{kind}'")
    raise ValueError(f"Unknown weight init scheme '{scheme}'")
