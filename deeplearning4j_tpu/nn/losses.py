"""Loss functions.

Parity surface: the reference's ``ILossFunction`` set (nd4j lossfunctions,
selected in output-layer configs, e.g. reference
deeplearning4j-nn/.../conf/layers/OutputLayer.java and
LossFunctions.LossFunction enum). Every loss takes ``(labels, preoutput,
activation_fn, mask)`` and returns a per-example score plus supports autodiff;
the reference's hand-written ``computeGradient`` is unnecessary under jax.

All losses reduce with mean-over-batch, sum-over-output-dims — matching the
reference's score convention (BaseOptimizer divides by minibatch size,
optimize/solvers/BaseOptimizer.java:314 path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation

_EPS = 1e-7


def _apply_mask(per_elem, mask):
    """Broadcast a per-timestep/per-example mask over a per-element loss."""
    if mask is None:
        return per_elem, None
    while mask.ndim < per_elem.ndim:
        mask = mask[..., None]
    return per_elem * mask, mask


def _reduce(per_elem, mask):
    """Sum over feature dims, mean over examples (mask-aware)."""
    per_ex = per_elem.reshape(per_elem.shape[0], -1).sum(axis=-1)
    if mask is not None:
        # mean over unmasked examples/timesteps
        denom = jnp.maximum(mask.reshape(mask.shape[0], -1).max(axis=-1).sum(), 1.0)
        # For RNN losses (B, T, C) the mask sums timesteps; handled upstream by
        # flattening time into batch before calling the loss.
        return per_ex.sum() / denom
    return per_ex.mean()


def l2(labels, preout, activation="identity", mask=None):
    # reference L2 = per-example SUM of squared errors
    out = get_activation(activation)(preout)
    per = (labels - out) ** 2
    per, m = _apply_mask(per, mask)
    return _reduce(per, mask)


def mse(labels, preout, activation="identity", mask=None):
    # reference MSE = L2 / nOut (LossMSE extends LossL2 with /nOut scaling)
    n_out = preout.shape[-1]
    return l2(labels, preout, activation, mask) / n_out


def l1(labels, preout, activation="identity", mask=None):
    out = get_activation(activation)(preout)
    per = jnp.abs(labels - out)
    per, m = _apply_mask(per, mask)
    return _reduce(per, mask)


def mae(labels, preout, activation="identity", mask=None):
    # reference MAE = L1 / nOut
    return l1(labels, preout, activation, mask) / preout.shape[-1]


def mcxent(labels, preout, activation="softmax", mask=None):
    """Multi-class cross entropy. With softmax activation, computed fused as
    log_softmax for numerical stability (XLA fuses this into one kernel)."""
    act_name = activation if isinstance(activation, str) else "softmax"
    if str(act_name).lower() == "softmax":
        logp = jax.nn.log_softmax(preout, axis=-1)
    else:
        out = get_activation(activation)(preout)
        logp = jnp.log(jnp.clip(out, _EPS, 1.0))
    per = -labels * logp
    per, _ = _apply_mask(per, mask)
    return _reduce(per, mask)


def negativeloglikelihood(labels, preout, activation="softmax", mask=None):
    return mcxent(labels, preout, activation, mask)


def xent(labels, preout, activation="sigmoid", mask=None):
    """Binary cross entropy. With sigmoid activation uses the logits-stable
    form."""
    if str(activation).lower() == "sigmoid":
        # stable: max(x,0) - x*z + log(1+exp(-|x|))
        x = preout
        per = jnp.maximum(x, 0) - x * labels + jnp.log1p(jnp.exp(-jnp.abs(x)))
    else:
        out = jnp.clip(get_activation(activation)(preout), _EPS, 1 - _EPS)
        per = -(labels * jnp.log(out) + (1 - labels) * jnp.log(1 - out))
    per, _ = _apply_mask(per, mask)
    return _reduce(per, mask)


def hinge(labels, preout, activation="identity", mask=None):
    out = get_activation(activation)(preout)
    per = jnp.maximum(0.0, 1.0 - labels * out)
    per, _ = _apply_mask(per, mask)
    return _reduce(per, mask)


def squared_hinge(labels, preout, activation="identity", mask=None):
    out = get_activation(activation)(preout)
    per = jnp.maximum(0.0, 1.0 - labels * out) ** 2
    per, _ = _apply_mask(per, mask)
    return _reduce(per, mask)


def kl_divergence(labels, preout, activation="softmax", mask=None):
    out = jnp.clip(get_activation(activation)(preout), _EPS, 1.0)
    lab = jnp.clip(labels, _EPS, 1.0)
    per = lab * (jnp.log(lab) - jnp.log(out))
    per, _ = _apply_mask(per, mask)
    return _reduce(per, mask)


def poisson(labels, preout, activation="identity", mask=None):
    out = get_activation(activation)(preout)
    per = out - labels * jnp.log(jnp.clip(out, _EPS, None))
    per, _ = _apply_mask(per, mask)
    return _reduce(per, mask)


def mape(labels, preout, activation="identity", mask=None):
    out = get_activation(activation)(preout)
    per = 100.0 * jnp.abs((labels - out) / jnp.clip(jnp.abs(labels), _EPS, None))
    per, _ = _apply_mask(per, mask)
    return _reduce(per, mask)


def msle(labels, preout, activation="identity", mask=None):
    out = get_activation(activation)(preout)
    per = (jnp.log1p(jnp.clip(out, 0, None)) - jnp.log1p(jnp.clip(labels, 0, None))) ** 2
    per, _ = _apply_mask(per, mask)
    return _reduce(per, mask)


def cosine_proximity(labels, preout, activation="identity", mask=None):
    out = get_activation(activation)(preout)
    ln = jnp.linalg.norm(labels, axis=-1, keepdims=True)
    on = jnp.linalg.norm(out, axis=-1, keepdims=True)
    cos = (labels * out) / jnp.clip(ln * on, _EPS, None)
    per = -cos
    per, _ = _apply_mask(per, mask)
    return _reduce(per, mask)


def wasserstein(labels, preout, activation="identity", mask=None):
    out = get_activation(activation)(preout)
    per = labels * out
    per, _ = _apply_mask(per, mask)
    return _reduce(per, mask)


LOSSES = {
    "mse": mse,
    "l1": l1,
    "l2": l2,
    "mae": mae,
    "mcxent": mcxent,
    "negativeloglikelihood": negativeloglikelihood,
    "xent": xent,
    "hinge": hinge,
    "squaredhinge": squared_hinge,
    "kldivergence": kl_divergence,
    "kl_divergence": kl_divergence,
    "poisson": poisson,
    "meanabsolutepercentageerror": mape,
    "mape": mape,
    "meansquaredlogarithmicerror": msle,
    "msle": msle,
    "cosineproximity": cosine_proximity,
    "cosine_proximity": cosine_proximity,
    "wasserstein": wasserstein,
}


def get_loss(name):
    if callable(name):
        return name
    key = str(name).lower().replace("_", "")
    key2 = str(name).lower()
    if key in LOSSES:
        return LOSSES[key]
    if key2 in LOSSES:
        return LOSSES[key2]
    raise ValueError(f"Unknown loss '{name}'. Available: {sorted(set(LOSSES))}")
