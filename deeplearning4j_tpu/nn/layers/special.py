"""Special layers: global pooling, autoencoders, VAE, center loss, YOLO, frozen.

Parity: reference nn/conf/layers/GlobalPoolingLayer.java,
nn/layers/variational/VariationalAutoencoder.java:51 (1,163 LoC),
nn/conf/layers/CenterLossOutputLayer.java,
nn/conf/layers/objdetect/Yolo2OutputLayer.java (721 LoC impl),
nn/layers/FrozenLayer.java.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer
from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.losses import get_loss
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.core import DenseLayer, OutputLayer


@register_layer
@dataclass
class GlobalPoolingLayer(Layer):
    """Pool over time (B,T,C)→(B,C) or space (B,H,W,C)→(B,C). Mask-aware for
    variable-length sequences (parity: GlobalPoolingLayer.java)."""
    pooling_type: str = "max"   # max | avg | sum | pnorm
    pnorm: int = 2
    collapse_dimensions: bool = True

    def has_params(self):
        return False

    def output_type(self, input_type):
        if input_type.kind == "rnn":
            return InputType.feed_forward(input_type.size)
        if input_type.kind == "cnn":
            return InputType.feed_forward(input_type.channels)
        return input_type

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        if x.ndim == 3:
            axes = (1,)
        elif x.ndim == 4:
            axes = (1, 2)
        else:
            return x, state
        if mask is not None and x.ndim == 3:
            m = mask[..., None]
            if self.pooling_type == "max":
                y = jnp.where(m > 0, x, -jnp.inf).max(axis=1)
            elif self.pooling_type == "sum":
                y = (x * m).sum(axis=1)
            elif self.pooling_type == "avg":
                y = (x * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
            else:
                p = float(self.pnorm)
                y = ((jnp.abs(x) ** p * m).sum(axis=1)) ** (1.0 / p)
            return y, state
        if self.pooling_type == "max":
            y = x.max(axis=axes)
        elif self.pooling_type == "sum":
            y = x.sum(axis=axes)
        elif self.pooling_type == "avg":
            y = x.mean(axis=axes)
        else:
            p = float(self.pnorm)
            y = (jnp.abs(x) ** p).sum(axis=axes) ** (1.0 / p)
        return y, state


@register_layer
@dataclass
class AutoEncoder(Layer):
    """Denoising autoencoder (parity: nn/conf/layers/AutoEncoder.java,
    nn/layers/feedforward/autoencoder/AutoEncoder.java). ``apply`` returns the
    encoding; ``compute_score`` adds corruption + reconstruction loss for
    layerwise pretraining."""
    n_in: int = 0
    n_out: int = 0
    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    def init(self, rng, dtype=jnp.float32):
        r1, r2 = jax.random.split(rng)
        return {
            "W": init_weights(r1, (self.n_in, self.n_out),
                              self.weight_init or "xavier", self.dist, dtype),
            "b": jnp.zeros((self.n_out,), dtype),
            "vb": jnp.zeros((self.n_in,), dtype),  # visible bias for decode
        }

    def _encode(self, params, x):
        return get_activation(self.activation or "sigmoid")(x @ params["W"] + params["b"])

    def _decode(self, params, h):
        return get_activation(self.activation or "sigmoid")(h @ params["W"].T + params["vb"])

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        return self._encode(params, x), state

    def compute_score(self, params, x, labels=None, mask=None, *, train=False, rng=None):
        if train and rng is not None and self.corruption_level > 0:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            xc = jnp.where(keep, x, 0.0)
        else:
            xc = x
        recon = self._decode(params, self._encode(params, xc))
        return get_loss(self.loss)(x, recon, "identity", mask)


@register_layer
@dataclass
class VariationalAutoencoder(Layer):
    """VAE (parity: nn/layers/variational/VariationalAutoencoder.java:51).
    Gaussian q(z|x); pluggable reconstruction distribution via ``recon``:
    'gaussian' | 'bernoulli' | 'mse'. ``apply`` returns the latent mean
    (matches reference activate() semantics); ``compute_score`` = -ELBO."""
    n_in: int = 0
    n_out: int = 0                        # latent size nZ
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    recon: str = "bernoulli"
    pzx_activation: str = "identity"
    num_samples: int = 1

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    def init(self, rng, dtype=jnp.float32):
        act_in = self.n_in
        p = {"enc": [], "dec": []}
        keys = jax.random.split(rng, len(self.encoder_layer_sizes) +
                                len(self.decoder_layer_sizes) + 4)
        ki = 0
        for h in self.encoder_layer_sizes:
            p["enc"].append({
                "W": init_weights(keys[ki], (act_in, h),
                                  self.weight_init or "xavier", self.dist, dtype),
                "b": jnp.zeros((h,), dtype)})
            act_in = h
            ki += 1
        p["zW_mean"] = init_weights(keys[ki], (act_in, self.n_out),
                                    self.weight_init or "xavier", self.dist, dtype)
        p["zb_mean"] = jnp.zeros((self.n_out,), dtype)
        ki += 1
        p["zW_logvar"] = init_weights(keys[ki], (act_in, self.n_out),
                                      self.weight_init or "xavier", self.dist, dtype)
        p["zb_logvar"] = jnp.zeros((self.n_out,), dtype)
        ki += 1
        act_in = self.n_out
        for h in self.decoder_layer_sizes:
            p["dec"].append({
                "W": init_weights(keys[ki], (act_in, h),
                                  self.weight_init or "xavier", self.dist, dtype),
                "b": jnp.zeros((h,), dtype)})
            act_in = h
            ki += 1
        p["xW"] = init_weights(keys[ki], (act_in, self.n_in),
                               self.weight_init or "xavier", self.dist, dtype)
        p["xb"] = jnp.zeros((self.n_in,), dtype)
        return p

    def _encode(self, params, x):
        act = get_activation(self.activation or "tanh")
        h = x
        for lp in params["enc"]:
            h = act(h @ lp["W"] + lp["b"])
        mean = get_activation(self.pzx_activation)(h @ params["zW_mean"] + params["zb_mean"])
        logvar = h @ params["zW_logvar"] + params["zb_logvar"]
        return mean, logvar

    def _decode(self, params, z):
        act = get_activation(self.activation or "tanh")
        h = z
        for lp in params["dec"]:
            h = act(h @ lp["W"] + lp["b"])
        return h @ params["xW"] + params["xb"]

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        mean, _ = self._encode(params, x)
        return mean, state

    def reconstruct(self, params, x):
        mean, _ = self._encode(params, x)
        logits = self._decode(params, mean)
        if self.recon == "bernoulli":
            return jax.nn.sigmoid(logits)
        return logits

    def generate(self, params, z):
        logits = self._decode(params, z)
        if self.recon == "bernoulli":
            return jax.nn.sigmoid(logits)
        return logits

    def compute_score(self, params, x, labels=None, mask=None, *, train=False, rng=None):
        mean, logvar = self._encode(params, x)
        if rng is not None and train:
            eps = jax.random.normal(rng, mean.shape, mean.dtype)
        else:
            eps = jnp.zeros_like(mean)
        z = mean + jnp.exp(0.5 * logvar) * eps
        logits = self._decode(params, z)
        if self.recon == "bernoulli":
            xcl = jnp.clip(x, 0.0, 1.0)
            rec = jnp.maximum(logits, 0) - logits * xcl + jnp.log1p(jnp.exp(-jnp.abs(logits)))
            rec = rec.sum(axis=-1)
        else:  # gaussian / mse
            rec = 0.5 * ((x - logits) ** 2).sum(axis=-1)
        kl = -0.5 * (1 + logvar - mean ** 2 - jnp.exp(logvar)).sum(axis=-1)
        per_ex = rec + kl
        if mask is not None:
            m = mask.reshape(per_ex.shape[0])
            return (per_ex * m).sum() / jnp.maximum(m.sum(), 1.0)
        return per_ex.mean()


@register_layer
@dataclass
class CenterLossOutputLayer(OutputLayer):
    """Softmax + center loss (parity: nn/conf/layers/CenterLossOutputLayer).
    Class centers are trainable params pulled toward features; total loss =
    primary + lambda * centerloss."""
    alpha: float = 0.05
    lambda_: float = 2e-4

    def init(self, rng, dtype=jnp.float32):
        p = super().init(rng, dtype)
        p["centers"] = jnp.zeros((self.n_out, self.n_in), dtype)
        return p

    def compute_score(self, params, x, labels, mask=None, *, train=False, rng=None):
        base = super().compute_score(
            {k: v for k, v in params.items() if k != "centers"},
            x, labels, mask, train=train, rng=rng)
        cls = jnp.argmax(labels, axis=-1)
        centers = params["centers"][cls]
        per_ex = 0.5 * ((x - centers) ** 2).sum(axis=-1)
        if mask is not None:
            m = mask.reshape(per_ex.shape[0])
            cl = (per_ex * m).sum() / jnp.maximum(m.sum(), 1.0)
        else:
            cl = per_ex.mean()
        return base + self.lambda_ * cl


@register_layer
@dataclass
class Yolo2OutputLayer(Layer):
    """YOLOv2 detection loss (parity: nn/conf/layers/objdetect/
    Yolo2OutputLayer + nn/layers/objdetect/Yolo2OutputLayer.java, 721 LoC).

    Input: (B, H, W, A*(5+C)) raw activations (NHWC; A = #anchors).
    Labels: (B, H, W, A*(5+C)) with the same layout: per anchor
    [tx, ty, tw, th, obj, class-one-hot]. Cells with obj=0 contribute only
    no-object confidence loss.
    """
    anchors: Tuple[Tuple[float, float], ...] = ((1.0, 1.0),)
    lambda_coord: float = 5.0
    lambda_no_obj: float = 0.5
    n_classes: int = 0

    def __post_init__(self):
        # JSON round-trips deliver lists; canonicalize so serde is stable
        self.anchors = tuple(tuple(float(v) for v in a) for a in self.anchors)

    def has_params(self):
        return False

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        return x, state

    def _split(self, x):
        A = len(self.anchors)
        B, H, W, _ = x.shape
        x = x.reshape(B, H, W, A, 5 + self.n_classes)
        xy = jax.nn.sigmoid(x[..., 0:2])
        wh = x[..., 2:4]
        obj = jax.nn.sigmoid(x[..., 4])
        cls = x[..., 5:]
        return xy, wh, obj, cls

    def compute_score(self, params, x, labels, mask=None, *, train=False, rng=None):
        pxy, pwh, pobj, pcls = self._split(x)
        A = len(self.anchors)
        B, H, W, _ = labels.shape
        lab = labels.reshape(B, H, W, A, 5 + self.n_classes)
        txy, twh, tobj, tcls = lab[..., 0:2], lab[..., 2:4], lab[..., 4], lab[..., 5:]
        coord = ((pxy - txy) ** 2).sum(-1) + ((pwh - twh) ** 2).sum(-1)
        # per-example terms (B,), then mask-weighted mean over examples
        coord = (coord * tobj).sum((1, 2, 3))
        obj_loss = (tobj * (pobj - 1.0) ** 2).sum((1, 2, 3))
        noobj_loss = ((1 - tobj) * pobj ** 2).sum((1, 2, 3))
        logp = jax.nn.log_softmax(pcls, axis=-1)
        cls_loss = ((-(tcls * logp).sum(-1)) * tobj).sum((1, 2, 3))
        per_ex = (self.lambda_coord * coord + obj_loss +
                  self.lambda_no_obj * noobj_loss + cls_loss)
        if mask is not None:
            m = mask.reshape(B)
            return (per_ex * m).sum() / jnp.maximum(m.sum(), 1.0)
        return per_ex.sum() / B


@register_layer
@dataclass
class FrozenLayer(Layer):
    """Wrapper freezing inner params (parity: nn/layers/FrozenLayer.java;
    used by transfer learning). Gradient is cut with stop_gradient and the
    container also excludes these params from the updater."""
    inner: Optional[Layer] = None

    def set_n_in(self, input_type):
        self.inner.set_n_in(input_type)

    def apply_defaults(self, defaults):
        if self.inner is not None:
            self.inner.apply_defaults(defaults)

    def output_type(self, input_type):
        return self.inner.output_type(input_type)

    def init(self, rng, dtype=jnp.float32):
        return self.inner.init(rng, dtype)

    def init_state(self, dtype=None):
        import jax.numpy as jnp
        return self.inner.init_state(dtype or jnp.float32)

    def has_params(self):
        return self.inner.has_params()

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        # frozen layers run in inference mode (no dropout, fixed BN stats)
        y, _ = self.inner.apply(frozen, x, state, train=False, rng=rng, mask=mask)
        return y, state

    def compute_score(self, params, x, labels, mask=None, *, train=False, rng=None):
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, params)
        return self.inner.compute_score(frozen, x, labels, mask, train=False, rng=rng)
