"""Unsupervised layerwise pretraining: RBM + the pretrain protocol.

Parity: reference nn/layers/feedforward/rbm/RBM.java (legacy CD-k
restricted Boltzmann machine), nn/conf/layers/RBM.java, and
MultiLayerNetwork.pretrain (MultiLayerNetwork.java:1172 — greedy layerwise
pretraining of RBM/AutoEncoder/VAE layers before supervised backprop).

Protocol: a layer is pretrainable if it defines ``pretrain_step(params, x,
rng, lr) -> (new_params, loss)``. RBM implements contrastive divergence
directly (CD is not the gradient of a tractable loss); AutoEncoder gets a
generic gradient step on its reconstruction ``compute_score``. The whole
CD-k chain is one jit'd function — Gibbs steps are a ``lax.fori_loop``.

Param keys follow the reference's PretrainParamInitializer: ``W`` (n_in,
n_out), ``b`` hidden bias, ``vb`` visible bias."""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer, require_dims
from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.nn.conf.inputs import InputType


@register_layer
@dataclass
class RBM(Layer):
    """Bernoulli-Bernoulli RBM (parity: RBM.java, hidden/visible unit types
    BINARY; GAUSSIAN visible supported via ``visible_unit='gaussian'``).
    As a feedforward layer, ``apply`` is propup: sigmoid(x W + b)."""
    n_in: int = 0
    n_out: int = 0
    k: int = 1                      # CD-k Gibbs steps
    visible_unit: str = "binary"    # binary | gaussian

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    def init(self, rng, dtype=jnp.float32):
        require_dims(self, n_in=self.n_in, n_out=self.n_out)
        return {
            "W": init_weights(rng, (self.n_in, self.n_out),
                              self.weight_init or "xavier", self.dist, dtype),
            "b": jnp.zeros((self.n_out,), dtype),
            "vb": jnp.zeros((self.n_in,), dtype),
        }

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        act = get_activation(self.activation or "sigmoid")
        return act(x @ params["W"] + params["b"]), state

    # --------------------------------------------------------- pretraining
    def _prop_up(self, params, v):
        return jax.nn.sigmoid(v @ params["W"] + params["b"])

    def _prop_down(self, params, h):
        pre = h @ params["W"].T + params["vb"]
        if self.visible_unit == "gaussian":
            return pre
        return jax.nn.sigmoid(pre)

    def pretrain_step(self, params, x, rng, lr):
        """One CD-k update on a minibatch. Returns (params, recon_error)."""
        B = x.shape[0]
        h0 = self._prop_up(params, x)

        def gibbs(i, carry):
            h, key = carry
            key, k1, k2 = jax.random.split(key, 3)
            h_samp = jax.random.bernoulli(k1, h).astype(x.dtype)
            v = self._prop_down(params, h_samp)
            if self.visible_unit == "binary":
                v = jax.random.bernoulli(k2, v).astype(x.dtype)
            return self._prop_up(params, v), key

        # k-1 full Gibbs steps; the final sample/down/up below is the k-th,
        # so CD-k runs exactly k steps (parity: RBM.java CD-k)
        hk, _ = lax.fori_loop(0, self.k - 1, gibbs, (h0, rng))
        key = jax.random.fold_in(rng, 7)
        h_samp = jax.random.bernoulli(key, hk).astype(x.dtype)
        vk = self._prop_down(params, h_samp)
        hk2 = self._prop_up(params, vk)

        dW = (x.T @ h0 - vk.T @ hk2) / B
        dvb = (x - vk).mean(axis=0)
        dhb = (h0 - hk2).mean(axis=0)
        new_params = {
            "W": params["W"] + lr * dW,
            "b": params["b"] + lr * dhb,
            "vb": params["vb"] + lr * dvb,
        }
        recon = jnp.mean((x - self._prop_down(params, h0)) ** 2)
        return new_params, recon


def make_gradient_pretrain_step(layer):
    """Generic pretrain step for layers with a self-supervised
    ``compute_score`` (AutoEncoder, VariationalAutoencoder): plain SGD on
    the layer's own reconstruction/ELBO loss."""

    def step(params, x, rng, lr):
        def loss_fn(p):
            return layer.compute_score(p, x, None, None, train=True, rng=rng)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    return step


def get_pretrain_step(layer):
    """Resolve the pretrain function for a layer, or None."""
    if hasattr(layer, "pretrain_step"):
        return layer.pretrain_step
    if type(layer).__name__ in ("AutoEncoder", "VariationalAutoencoder"):
        return make_gradient_pretrain_step(layer)
    return None
