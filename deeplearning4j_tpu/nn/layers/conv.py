"""Convolutional / pooling / normalization layers.

Parity: reference nn/conf/layers/ConvolutionLayer.java:1-566,
SubsamplingLayer.java, Upsampling*.java, ZeroPaddingLayer.java,
BatchNormalization.java, LocalResponseNormalization.java and their
nn/layers/convolution|normalization impls, plus the cuDNN helper seam
(deeplearning4j-cuda CudnnConvolutionHelper.java etc.).

TPU design: internal layout is NHWC with HWIO kernels — the layout XLA tiles
best onto the MXU; convs lower to ``lax.conv_general_dilated`` (one fused XLA
conv per layer, replacing the reference's im2col+GEMM pipeline,
ConvolutionLayer.java:279 preOutput). There is no algo-selection/workspace
machinery to port: XLA owns scheduling and memory.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer, as_pair, require_dims
from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.nn.conf.inputs import InputType, conv_output_size


def _padding_config(mode, kernel, stride, padding, dilation):
    """lax padding config for ConvolutionMode parity ('same'|'truncate')."""
    if mode == "same":
        return "SAME"
    return [(p, p) for p in padding]


@register_layer
@dataclass
class ConvolutionLayer(Layer):
    """2D convolution. Input/weights: NHWC / HWIO."""
    n_in: int = 0                  # input channels
    n_out: int = 0                 # output channels
    kernel_size: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"   # 'truncate' | 'same'
    has_bias: bool = True

    def __post_init__(self):
        self.kernel_size = as_pair(self.kernel_size)
        self.stride = as_pair(self.stride)
        self.padding = as_pair(self.padding)
        self.dilation = as_pair(self.dilation)

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.channels

    def output_type(self, input_type):
        h = conv_output_size(input_type.height, self.kernel_size[0], self.stride[0],
                             self.padding[0], self.dilation[0], self.convolution_mode)
        w = conv_output_size(input_type.width, self.kernel_size[1], self.stride[1],
                             self.padding[1], self.dilation[1], self.convolution_mode)
        return InputType.convolutional(h, w, self.n_out)

    def init(self, rng, dtype=jnp.float32):
        require_dims(self, n_in=self.n_in, n_out=self.n_out)
        kh, kw = self.kernel_size
        p = {"W": init_weights(rng, (kh, kw, self.n_in, self.n_out),
                               self.weight_init or "xavier", self.dist, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init or 0.0, dtype)
        return p

    def _conv(self, x, w):
        y = lax.conv_general_dilated(
            x, w, window_strides=self.stride,
            padding=_padding_config(self.convolution_mode, self.kernel_size,
                                    self.stride, self.padding, self.dilation),
            rhs_dilation=self.dilation,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        # named for selective rematerialization (GlobalConf.remat =
        # 'save_convs', alias 'selective': keep conv outputs, recompute
        # BN/activations); identity outside a remat context
        return checkpoint_name(y, "conv_out")

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        y = self._conv(x, params["W"])
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation or "identity")(y), state


@register_layer
@dataclass
class Convolution1DLayer(Layer):
    """1D (temporal) convolution over (B, T, C)
    (parity: nn/conf/layers/Convolution1DLayer.java)."""
    n_in: int = 0
    n_out: int = 0
    kernel_size: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolution_mode: str = "truncate"
    has_bias: bool = True

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.size

    def output_type(self, input_type):
        t = input_type.timeseries_length
        if t > 0:
            t = conv_output_size(t, self.kernel_size, self.stride, self.padding,
                                 self.dilation, self.convolution_mode)
        return InputType.recurrent(self.n_out, t)

    def init(self, rng, dtype=jnp.float32):
        p = {"W": init_weights(rng, (self.kernel_size, self.n_in, self.n_out),
                               self.weight_init or "xavier", self.dist, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init or 0.0, dtype)
        return p

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        pad = "SAME" if self.convolution_mode == "same" else [(self.padding, self.padding)]
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=(self.stride,), padding=pad,
            rhs_dilation=(self.dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation or "identity")(y), state


@register_layer
@dataclass
class SeparableConvolution2D(ConvolutionLayer):
    """Depthwise + pointwise conv
    (parity: nn/conf/layers/SeparableConvolution2D.java)."""
    depth_multiplier: int = 1

    def init(self, rng, dtype=jnp.float32):
        kh, kw = self.kernel_size
        r1, r2 = jax.random.split(rng)
        p = {"dW": init_weights(r1, (kh, kw, 1, self.n_in * self.depth_multiplier),
                                self.weight_init or "xavier", self.dist, dtype,
                                fan_in=kh * kw, fan_out=kh * kw * self.depth_multiplier),
             "pW": init_weights(r2, (1, 1, self.n_in * self.depth_multiplier, self.n_out),
                                self.weight_init or "xavier", self.dist, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init or 0.0, dtype)
        return p

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        pad = _padding_config(self.convolution_mode, self.kernel_size, self.stride,
                              self.padding, self.dilation)
        y = lax.conv_general_dilated(
            x, params["dW"], window_strides=self.stride, padding=pad,
            rhs_dilation=self.dilation, feature_group_count=self.n_in,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = lax.conv_general_dilated(
            y, params["pW"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation or "identity")(y), state


@register_layer
@dataclass
class DepthwiseConvolution2D(ConvolutionLayer):
    depth_multiplier: int = 1

    def output_type(self, input_type):
        ot = super().output_type(input_type)
        return InputType.convolutional(ot.height, ot.width,
                                       self.n_in * self.depth_multiplier)

    def init(self, rng, dtype=jnp.float32):
        kh, kw = self.kernel_size
        p = {"W": init_weights(rng, (kh, kw, 1, self.n_in * self.depth_multiplier),
                               self.weight_init or "xavier", self.dist, dtype,
                               fan_in=kh * kw, fan_out=kh * kw * self.depth_multiplier)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_in * self.depth_multiplier,),
                              self.bias_init or 0.0, dtype)
        return p

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        pad = _padding_config(self.convolution_mode, self.kernel_size, self.stride,
                              self.padding, self.dilation)
        y = lax.conv_general_dilated(
            x, params["W"], window_strides=self.stride, padding=pad,
            rhs_dilation=self.dilation, feature_group_count=self.n_in,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation or "identity")(y), state


@register_layer
@dataclass
class Deconvolution2D(ConvolutionLayer):
    """Transposed convolution (parity: nn/conf/layers/Deconvolution2D)."""

    def output_type(self, input_type):
        kh, kw = self.kernel_size
        sh, sw = self.stride
        if self.convolution_mode == "same":
            h, w = input_type.height * sh, input_type.width * sw
        else:
            h = sh * (input_type.height - 1) + kh - 2 * self.padding[0]
            w = sw * (input_type.width - 1) + kw - 2 * self.padding[1]
        return InputType.convolutional(h, w, self.n_out)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            kh, kw = self.kernel_size
            ph, pw = self.padding
            pad = [(kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)]
        y = lax.conv_transpose(x, params["W"], strides=self.stride, padding=pad,
                               dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation or "identity")(y), state


@register_layer
@dataclass
class SubsamplingLayer(Layer):
    """Pooling (parity: nn/conf/layers/SubsamplingLayer.java; cuDNN seam
    CudnnSubsamplingHelper). Lowered to ``lax.reduce_window``."""
    pooling_type: str = "max"       # 'max' | 'avg' | 'pnorm' | 'sum'
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2
    # avg divisor at padded edges: True = kernel size (reference/dl4j
    # semantics), False = only real positions (Keras/TF semantics — set by
    # the Keras importer so imported AveragePooling matches Keras output)
    avg_count_includes_padding: bool = True

    def __post_init__(self):
        self.kernel_size = as_pair(self.kernel_size)
        self.stride = as_pair(self.stride)
        self.padding = as_pair(self.padding)

    def has_params(self):
        return False

    def output_type(self, input_type):
        h = conv_output_size(input_type.height, self.kernel_size[0], self.stride[0],
                             self.padding[0], 1, self.convolution_mode)
        w = conv_output_size(input_type.width, self.kernel_size[1], self.stride[1],
                             self.padding[1], 1, self.convolution_mode)
        return InputType.convolutional(h, w, input_type.channels)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        kh, kw = self.kernel_size
        sh, sw = self.stride
        dims = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        if self.convolution_mode == "same":
            pad = "SAME"
        else:
            ph, pw = self.padding
            pad = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        if self.pooling_type == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        elif self.pooling_type in ("avg", "sum"):
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            if self.pooling_type == "avg":
                if self.avg_count_includes_padding:
                    y = y / (kh * kw)
                else:
                    ones = jnp.ones_like(x)
                    cnt = lax.reduce_window(ones, 0.0, lax.add, dims,
                                            strides, pad)
                    y = y / cnt
        elif self.pooling_type == "pnorm":
            p = float(self.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, dims, strides, pad)
            y = y ** (1.0 / p)
        else:
            raise ValueError(self.pooling_type)
        return y, state


@register_layer
@dataclass
class Subsampling1DLayer(Layer):
    pooling_type: str = "max"
    kernel_size: int = 2
    stride: int = 2
    padding: int = 0
    convolution_mode: str = "truncate"
    avg_count_includes_padding: bool = True   # False = Keras/TF semantics

    def has_params(self):
        return False

    def output_type(self, input_type):
        t = input_type.timeseries_length
        if t > 0:
            t = conv_output_size(t, self.kernel_size, self.stride, self.padding,
                                 1, self.convolution_mode)
        return InputType.recurrent(input_type.size, t)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        dims, strides = (1, self.kernel_size, 1), (1, self.stride, 1)
        pad = "SAME" if self.convolution_mode == "same" else \
            ((0, 0), (self.padding, self.padding), (0, 0))
        if self.pooling_type == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pad)
        else:
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pad)
            if self.pooling_type == "avg":
                if self.avg_count_includes_padding:
                    y = y / self.kernel_size
                else:
                    cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add,
                                            dims, strides, pad)
                    y = y / cnt
        return y, state


@register_layer
@dataclass
class Upsampling2D(Layer):
    size: Tuple[int, int] = (2, 2)

    def __post_init__(self):
        self.size = as_pair(self.size)

    def has_params(self):
        return False

    def output_type(self, input_type):
        return InputType.convolutional(input_type.height * self.size[0],
                                       input_type.width * self.size[1],
                                       input_type.channels)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        y = jnp.repeat(jnp.repeat(x, self.size[0], axis=1), self.size[1], axis=2)
        return y, state


@register_layer
@dataclass
class Upsampling1D(Layer):
    size: int = 2

    def has_params(self):
        return False

    def output_type(self, input_type):
        t = input_type.timeseries_length
        return InputType.recurrent(input_type.size, t * self.size if t > 0 else t)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        return jnp.repeat(x, self.size, axis=1), state


@register_layer
@dataclass
class ZeroPaddingLayer(Layer):
    padding: Tuple[int, int, int, int] = (0, 0, 0, 0)  # top,bottom,left,right

    def __post_init__(self):
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        elif len(p) == 2:
            p = (p[0], p[0], p[1], p[1])
        self.padding = tuple(p)

    def has_params(self):
        return False

    def output_type(self, input_type):
        t, b, l, r = self.padding
        return InputType.convolutional(input_type.height + t + b,
                                       input_type.width + l + r,
                                       input_type.channels)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        t, b, l, r = self.padding
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0))), state


@register_layer
@dataclass
class ZeroPadding1DLayer(Layer):
    padding: Tuple[int, int] = (0, 0)

    def __post_init__(self):
        self.padding = as_pair(self.padding)

    def has_params(self):
        return False

    def output_type(self, input_type):
        t = input_type.timeseries_length
        extra = self.padding[0] + self.padding[1]
        return InputType.recurrent(input_type.size, t + extra if t > 0 else t)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        l, r = self.padding
        return jnp.pad(x, ((0, 0), (l, r), (0, 0))), state


@register_layer
@dataclass
class Cropping2D(Layer):
    cropping: Tuple[int, int, int, int] = (0, 0, 0, 0)

    def __post_init__(self):
        c = self.cropping
        if isinstance(c, int):
            c = (c, c, c, c)
        elif len(c) == 2:
            c = (c[0], c[0], c[1], c[1])
        self.cropping = tuple(c)

    def has_params(self):
        return False

    def output_type(self, input_type):
        t, b, l, r = self.cropping
        return InputType.convolutional(input_type.height - t - b,
                                       input_type.width - l - r,
                                       input_type.channels)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        t, b, l, r = self.cropping
        H, W = x.shape[1], x.shape[2]
        return x[:, t:H - b if b else H, l:W - r if r else W, :], state


@register_layer
@dataclass
class BatchNormalization(Layer):
    """Batch norm with running stats carried as functional state
    (parity: nn/conf/layers/BatchNormalization.java + cuDNN seam
    CudnnBatchNormalizationHelper; running stats = the reference's
    globalMean/globalVar params, here non-trainable state updated in the
    train step and returned — no mutation)."""
    n_in: int = 0
    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.channels if input_type.kind == "cnn" \
                else input_type.flat_size() if input_type.kind != "rnn" \
                else input_type.size

    def init(self, rng, dtype=jnp.float32):
        require_dims(self, n_in=self.n_in)
        if self.lock_gamma_beta:
            return {}
        return {"gamma": jnp.ones((self.n_in,), dtype),
                "beta": jnp.zeros((self.n_in,), dtype)}

    def init_state(self, dtype=jnp.float32):
        return {"mean": jnp.zeros((self.n_in,), dtype),
                "var": jnp.ones((self.n_in,), dtype)}

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        axes = tuple(range(x.ndim - 1))
        if train:
            mean = x.mean(axis=axes)
            var = x.var(axis=axes)
            new_state = {
                "mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                "var": self.decay * state["var"] + (1 - self.decay) * var,
            }
        else:
            # running stats are stored f32 (dtype-stable state contract);
            # cast to the activation dtype or a bf16 forward would promote
            # to f32 and crash the next conv on mixed dtypes
            mean = state["mean"].astype(x.dtype)
            var = state["var"].astype(x.dtype)
            new_state = state
        xn = (x - mean) * lax.rsqrt(var + self.eps)
        if not self.lock_gamma_beta:
            xn = xn * params["gamma"] + params["beta"]
        return get_activation(self.activation or "identity")(xn), new_state


@register_layer
@dataclass
class LocalResponseNormalization(Layer):
    """LRN across channels (parity: nn/conf/layers/
    LocalResponseNormalization.java; cuDNN seam CudnnLocalResponseNormalizationHelper).
    Implemented as an avg-pool over the channel axis — one fused XLA window op."""
    k: float = 2.0
    alpha: float = 1e-4
    beta: float = 0.75
    n: int = 5

    def has_params(self):
        return False

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        half = self.n // 2
        sq = x ** 2
        win = lax.reduce_window(sq, 0.0, lax.add, (1, 1, 1, self.n), (1, 1, 1, 1),
                                ((0, 0), (0, 0), (0, 0), (half, half)))
        denom = (self.k + self.alpha * win) ** self.beta
        return x / denom, state


@register_layer
@dataclass
class SpaceToDepthLayer(Layer):
    block_size: int = 2

    def has_params(self):
        return False

    def output_type(self, input_type):
        b = self.block_size
        return InputType.convolutional(input_type.height // b, input_type.width // b,
                                       input_type.channels * b * b)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        B, H, W, C = x.shape
        b = self.block_size
        y = x.reshape(B, H // b, b, W // b, b, C)
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // b, W // b, b * b * C)
        return y, state


@register_layer
@dataclass
class SpaceToBatchLayer(Layer):
    block_size: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)

    def __post_init__(self):
        self.block_size = as_pair(self.block_size)

    def has_params(self):
        return False

    def output_type(self, input_type):
        bh, bw = self.block_size
        return InputType.convolutional(input_type.height // bh,
                                       input_type.width // bw, input_type.channels)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        B, H, W, C = x.shape
        bh, bw = self.block_size
        y = x.reshape(B, H // bh, bh, W // bw, bw, C)
        y = y.transpose(2, 4, 0, 1, 3, 5).reshape(B * bh * bw, H // bh, W // bw, C)
        return y, state
