"""Layer configs + implementations (config IS the layer; pure-function apply).

Parity surface: reference nn/conf/layers/* (declarative configs) fused with
nn/layers/** (imperative impls). In this framework a layer is one dataclass:
hyperparameters are fields, ``init`` builds a params pytree, ``apply`` is a
pure function, and the backward pass is ``jax.grad`` of the container loss.
"""

from deeplearning4j_tpu.nn.layers.base import Layer, LAYER_REGISTRY, layer_from_dict
from deeplearning4j_tpu.nn.layers.core import (
    DenseLayer, OutputLayer, LossLayer, ActivationLayer, DropoutLayer,
    EmbeddingLayer, EmbeddingSequenceLayer, PReLULayer,
    ElementWiseMultiplicationLayer, ReshapeLayer, FlattenLayer,
)
from deeplearning4j_tpu.nn.layers.conv import (
    ConvolutionLayer, Convolution1DLayer, SeparableConvolution2D,
    DepthwiseConvolution2D, Deconvolution2D, SubsamplingLayer,
    Subsampling1DLayer, Upsampling1D, Upsampling2D, ZeroPaddingLayer,
    ZeroPadding1DLayer, Cropping2D, BatchNormalization,
    LocalResponseNormalization, SpaceToDepthLayer, SpaceToBatchLayer,
)
from deeplearning4j_tpu.nn.layers.rnn import (
    LSTM, GravesLSTM, GravesBidirectionalLSTM, SimpleRnn, Bidirectional,
    RnnOutputLayer, RnnLossLayer, LastTimeStep,
)
from deeplearning4j_tpu.nn.layers.special import (
    GlobalPoolingLayer, AutoEncoder, VariationalAutoencoder,
    CenterLossOutputLayer, Yolo2OutputLayer, FrozenLayer,
)
from deeplearning4j_tpu.nn.layers.attention import (
    MultiHeadAttention, LayerNormalization, PositionalEmbedding,
)
from deeplearning4j_tpu.nn.layers.pretrain import RBM

__all__ = [
    "Layer", "LAYER_REGISTRY", "layer_from_dict",
    "DenseLayer", "OutputLayer", "LossLayer", "ActivationLayer", "DropoutLayer",
    "EmbeddingLayer", "EmbeddingSequenceLayer", "PReLULayer",
    "ElementWiseMultiplicationLayer", "ReshapeLayer", "FlattenLayer",
    "ConvolutionLayer", "Convolution1DLayer", "SeparableConvolution2D",
    "DepthwiseConvolution2D", "Deconvolution2D", "SubsamplingLayer",
    "Subsampling1DLayer", "Upsampling1D", "Upsampling2D", "ZeroPaddingLayer",
    "ZeroPadding1DLayer", "Cropping2D", "BatchNormalization",
    "LocalResponseNormalization", "SpaceToDepthLayer", "SpaceToBatchLayer",
    "LSTM", "GravesLSTM", "GravesBidirectionalLSTM", "SimpleRnn", "Bidirectional",
    "RnnOutputLayer", "RnnLossLayer", "LastTimeStep",
    "GlobalPoolingLayer", "AutoEncoder", "VariationalAutoencoder",
    "CenterLossOutputLayer", "Yolo2OutputLayer", "FrozenLayer",
    "MultiHeadAttention", "LayerNormalization", "PositionalEmbedding", "RBM",
]
