"""Attention layers.

The reference (DL4J 0.9.2) has NO attention layer — long sequences are
handled only by truncated BPTT (SURVEY.md §5 'long-context'). This module is
the TPU-first extension the build plan calls for: scaled-dot-product
multi-head attention that slots into the Layer protocol, with a
sequence-parallel ring-attention path (parallel/sequence_parallel.py) for
contexts longer than one chip's HBM.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer, require_dims
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.nn.conf.inputs import InputType


def scaled_dot_product_attention(q, k, v, *, causal=False, mask=None,
                                 q_offset=0, k_offset=0, train=False):
    """q/k/v: (B, T, H, Dh). mask: (B, Tk) key padding mask. Offsets give
    global positions for causal masking of sequence blocks. ``train``
    feeds the route decision: the flash kernel is a custom-vjp pair, so a
    training call commits BOTH its forward and backward — routing asks
    for both phases (exec/routing.py flash_attn_route)."""
    from deeplearning4j_tpu import ops
    if (mask is None and q_offset == 0 and k_offset == 0
            and q.shape == k.shape and v.shape == q.shape
            and ops.helpers_enabled()):
        from deeplearning4j_tpu.ops.flash_attention import (
            supported, MIN_SEQ_FOR_AUTO_ROUTE)
        from deeplearning4j_tpu.exec.routing import flash_attn_route
        B, T, H, Dh = q.shape
        # interpreter mode (CPU tests) exercises the kernel at any length;
        # compiled mode routes per (shape, backend) measurement with the
        # long-sequence crossover as the no-data fallback — the SAME
        # decision for the training and inference forward
        interp = ops.interpret_mode()
        min_t = 0 if interp else MIN_SEQ_FOR_AUTO_ROUTE
        backend = None if interp else jax.default_backend()
        if (supported(T, Dh, min_t=0)
                and flash_attn_route(B * H, T, Dh, causal, train=train,
                                     backend=backend,
                                     min_t=min_t) == "pallas"):
            dt = q.dtype
            fold = lambda a: (a.transpose(0, 2, 1, 3)
                              .reshape(B * H, T, Dh).astype(jnp.float32))
            o = ops.flash_attention(fold(q), fold(k), fold(v), causal,
                                    interp)
            return (o.reshape(B, H, T, Dh).transpose(0, 2, 1, 3).astype(dt))
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = jnp.arange(q.shape[1]) + q_offset
        kpos = jnp.arange(k.shape[1]) + k_offset
        s = jnp.where(qpos[:, None] >= kpos[None, :], s, -jnp.inf)
    if mask is not None:
        s = jnp.where(mask[:, None, None, :] > 0, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@register_layer
@dataclass
class MultiHeadAttention(Layer):
    """Self-attention over (B, T, C) with n_heads heads. Param keys:
    Wq/Wk/Wv/Wo (+ biases). Projections are single fused GEMMs on the MXU."""
    n_in: int = 0
    n_out: int = 0          # model dim (defaults to n_in)
    n_heads: int = 4
    causal: bool = False
    has_bias: bool = True

    # KV caches are POSITIONAL decode state: rows are indexed by token
    # position and guarded by the causal mask, so speculative rewind
    # (serving/spec/) never snapshots them — rejected positions are
    # simply overwritten before any read can reach them.
    positional_state_keys = ("k", "v", "pk", "pv")

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.size or input_type.flat_size()
        if self.n_out == 0:
            self.n_out = self.n_in

    def output_type(self, input_type):
        return InputType.recurrent(self.n_out or self.n_in,
                                   input_type.timeseries_length)

    def init(self, rng, dtype=jnp.float32):
        require_dims(self, n_in=self.n_in, n_out=self.n_out or self.n_in)
        if self.n_out == 0:
            self.n_out = self.n_in
        if self.n_out % self.n_heads != 0:
            raise ValueError(f"n_out={self.n_out} not divisible by "
                             f"n_heads={self.n_heads}")
        keys = jax.random.split(rng, 4)
        wi = self.weight_init or "xavier"
        p = {
            "Wq": init_weights(keys[0], (self.n_in, self.n_out), wi, self.dist, dtype),
            "Wk": init_weights(keys[1], (self.n_in, self.n_out), wi, self.dist, dtype),
            "Wv": init_weights(keys[2], (self.n_in, self.n_out), wi, self.dist, dtype),
            "Wo": init_weights(keys[3], (self.n_out, self.n_out), wi, self.dist, dtype),
        }
        if self.has_bias:
            p["bq"] = jnp.zeros((self.n_out,), dtype)
            p["bk"] = jnp.zeros((self.n_out,), dtype)
            p["bv"] = jnp.zeros((self.n_out,), dtype)
            p["bo"] = jnp.zeros((self.n_out,), dtype)
        return p

    def _project(self, params, x):
        B, T, _ = x.shape
        H = self.n_heads
        Dh = self.n_out // H
        q = x @ params["Wq"]
        k = x @ params["Wk"]
        v = x @ params["Wv"]
        if self.has_bias:
            q, k, v = q + params["bq"], k + params["bk"], v + params["bv"]
        return (q.reshape(B, T, H, Dh), k.reshape(B, T, H, Dh),
                v.reshape(B, T, H, Dh))

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        B, T, _ = x.shape
        q, k, v = self._project(params, x)
        o = scaled_dot_product_attention(q, k, v, causal=self.causal,
                                         mask=mask, train=train)
        o = o.reshape(B, T, self.n_out) @ params["Wo"]
        if self.has_bias:
            o = o + params["bo"]
        return o, state

    # ---- incremental decode ----------------------------------------------
    def init_decode_state(self, params, batch, max_len, dtype=jnp.float32):
        """Fixed-capacity KV cache: (B, max_len, H, Dh) per tensor. Capacity
        equals the full-forward sequence length, so the decode softmax runs
        over the same-length axis as teacher forcing (masked positions are
        -inf → exp 0) and stays bitwise-equal to it."""
        H = self.n_heads
        Dh = (self.n_out or self.n_in) // H
        # two distinct buffers — sharing one array would make the engine's
        # donated step donate the same buffer twice
        return {"k": jnp.zeros((batch, max_len, H, Dh), dtype),
                "v": jnp.zeros((batch, max_len, H, Dh), dtype)}

    def _finish_step(self, params, q, kc, vc, pos):
        """Shared decode-step attention math over a gathered/dense cache
        ``kc``/``vc`` (B, C, H, Dh) — the ONE copy of the parity-oracle
        path, so the paged gather stays byte-identical to the dense slot
        step by construction."""
        B = q.shape[0]
        C = kc.shape[1]
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kc) * scale     # (B, H, 1, C)
        valid = jnp.arange(C)[None, :] <= pos[:, None]       # (B, C)
        s = jnp.where(valid[:, None, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        # Bitwise parity trick: XLA:CPU lowers the q-length-1 contraction as
        # a gemv whose accumulation order differs from the full forward's
        # gemm rows in the last ulp. Broadcasting the single query row to 2
        # rows forces the gemm path (rows are independent, so row 0 equals
        # the teacher-forced row exactly); the duplicate row is one extra
        # (C, Dh) dot per head — noise next to the step's dispatch cost.
        p2 = jnp.broadcast_to(p, (B, p.shape[1], 2, C))
        o = jnp.einsum("bhqk,bkhd->bqhd", p2, vc)[:, :1]
        o = o.reshape(B, 1, self.n_out) @ params["Wo"]
        if self.has_bias:
            o = o + params["bo"]
        return o

    def _project_out(self, params, o, B, T, dt):
        o = o.reshape(B, T, self.n_out).astype(dt) @ params["Wo"]
        if self.has_bias:
            o = o + params["bo"]
        return o

    def decode_step(self, params, dstate, x, pos, state=None):
        if not self.causal:
            raise ValueError(
                "only causal attention can decode incrementally (non-causal "
                "heads attend to future tokens)")
        B = x.shape[0]
        q, k, v = self._project(params, x)              # (B, 1, H, Dh)
        pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
        rows = jnp.arange(B)
        kc = dstate["k"].at[rows, pos].set(k[:, 0])
        vc = dstate["v"].at[rows, pos].set(v[:, 0])
        C = kc.shape[1]
        from deeplearning4j_tpu import ops
        if ops.helpers_enabled():
            from deeplearning4j_tpu.exec import decode_attn_route
            from deeplearning4j_tpu.ops import flash_decode
            Dh = q.shape[-1]
            # interpret mode exercises the kernel on any backend (tests);
            # compiled mode asks routing with the real platform
            backend = None if ops.interpret_mode() else jax.default_backend()
            if (flash_decode.supported(C, Dh)
                    and decode_attn_route(C, Dh, backend=backend)
                    == "pallas"):
                # flash decode-step: reads only pos+1 of the C cached rows
                o = ops.flash_decode_step(q[:, 0], kc, vc, pos,
                                          interpret=ops.interpret_mode())
                return (self._project_out(params, o, B, 1, q.dtype),
                        {"k": kc, "v": vc})
        return self._finish_step(params, q, kc, vc, pos), {"k": kc, "v": vc}

    # ---- paged decode (serving/kv/) --------------------------------------
    def init_paged_decode_state(self, params, batch, max_len, num_blocks,
                                block_size, dtype=jnp.float32):
        """KV block pool (kv/pool.py layout): (num_blocks, block_size, H,
        Dh) per tensor, shared by every slot and addressed through the
        engine's page tables. Keys 'pk'/'pv' (kv.POOL_KEYS) mark the
        leaves the engine's per-slot wipe/freeze masks must skip."""
        H = self.n_heads
        Dh = (self.n_out or self.n_in) // H
        return {"pk": jnp.zeros((num_blocks, block_size, H, Dh), dtype),
                "pv": jnp.zeros((num_blocks, block_size, H, Dh), dtype)}

    def decode_step_paged(self, params, dstate, x, pos, block_tables,
                          state=None):
        """Decode step against the block pool: scatter this position's KV
        into its ``pos → (block, offset)`` pool row, then either run the
        paged flash kernel (table-indexed DMA inside the kernel loop) or
        gather the logical cache and run the byte-identical dense math —
        the parity oracle the bitwise tests pin. Inactive slots carry
        all-zero tables, so their writes land in the reserved scratch
        block; the softmax position mask keeps scratch rows out of every
        real slot's attention."""
        if not self.causal:
            raise ValueError(
                "only causal attention can decode incrementally (non-causal "
                "heads attend to future tokens)")
        B = x.shape[0]
        q, k, v = self._project(params, x)              # (B, 1, H, Dh)
        pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
        bs = dstate["pk"].shape[1]
        MB = block_tables.shape[1]
        rows = jnp.arange(B)
        phys = block_tables[rows, pos // bs]            # (B,) pool block
        off = pos % bs
        pk = dstate["pk"].at[phys, off].set(k[:, 0])
        pv = dstate["pv"].at[phys, off].set(v[:, 0])
        C = MB * bs
        from deeplearning4j_tpu import ops
        if ops.helpers_enabled():
            from deeplearning4j_tpu.exec import decode_attn_route
            from deeplearning4j_tpu.ops import flash_decode
            Dh = q.shape[-1]
            backend = None if ops.interpret_mode() else jax.default_backend()
            if (flash_decode.supported_paged(bs, Dh)
                    and decode_attn_route(C, Dh, backend=backend,
                                          paged=True) == "pallas"):
                o = ops.flash_decode_step_paged(
                    q[:, 0], pk, pv, pos, block_tables,
                    interpret=ops.interpret_mode())
                return (self._project_out(params, o, B, 1, q.dtype),
                        {"pk": pk, "pv": pv})
        kc = pk[block_tables].reshape(B, C, *pk.shape[2:])
        vc = pv[block_tables].reshape(B, C, *pv.shape[2:])
        return (self._finish_step(params, q, kc, vc, pos),
                {"pk": pk, "pv": pv})

    def prefill_chunk(self, params, dstate, x, start, n, state=None,
                      block_tables=None, carry_stack=False):
        """Chunked prefill: scatter the chunk's K rows of KV into their
        cache positions, gather the logical cache, and run the same
        causal-masked softmax/gemm the full forward runs — bitwise-equal
        to teacher forcing row-for-row (the (K, C) gemm's rows are
        independent, like the decode trick's 2-row gemm).

        Paged (``"pk"`` in dstate): rows past a slot's ``n`` scatter into
        the scratch block and produce garbage activations the engine
        discards. Dense: the cache is updated with a position-aligned
        gather+where instead of a scatter, so padding rows (whose clipped
        positions could collide with real writes) are masked out
        deterministically. ``carry_stack`` always returns a None stack —
        KV state is positional, never snapshotted (see Layer)."""
        if dstate is None:
            return super().prefill_chunk(params, dstate, x, start, n,
                                         state=state,
                                         block_tables=block_tables,
                                         carry_stack=carry_stack)
        B, K, _ = x.shape
        q, k, v = self._project(params, x)              # (B, K, H, Dh)
        poss = start[:, None] + jnp.arange(K)[None, :]  # (B, K) positions
        valid = jnp.arange(K)[None, :] < n[:, None]
        rows = jnp.arange(B)
        if "pk" in dstate:
            bs = dstate["pk"].shape[1]
            MB = block_tables.shape[1]
            C = MB * bs
            bidx = jnp.clip(poss // bs, 0, MB - 1)
            phys = jnp.where(valid, block_tables[rows[:, None], bidx], 0)
            off = poss % bs
            pk = dstate["pk"].at[phys, off].set(k)
            pv = dstate["pv"].at[phys, off].set(v)
            # gather AFTER the scatter: chunk rows attend causally to rows
            # written in this same chunk, exactly like teacher forcing
            kc = pk[block_tables].reshape(B, C, *pk.shape[2:])
            vc = pv[block_tables].reshape(B, C, *pv.shape[2:])
            nd = {"pk": pk, "pv": pv}
        else:
            C = dstate["k"].shape[1]
            # position-aligned update: cache position c takes chunk row
            # c - start when that row is valid, else keeps its old value
            coff = jnp.arange(C)[None, :] - start[:, None]       # (B, C)
            wr = (coff >= 0) & (coff < jnp.minimum(n, K)[:, None])
            tidx = jnp.broadcast_to(
                jnp.clip(coff, 0, K - 1)[:, :, None, None],
                (B, C) + k.shape[2:])

            def upd(cache, new):
                g = jnp.take_along_axis(new, tidx, axis=1)
                return jnp.where(wr[:, :, None, None], g, cache)

            kc = upd(dstate["k"], k)
            vc = upd(dstate["v"], v)
            nd = {"k": kc, "v": vc}
        scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kc) * scale   # (B, H, K, C)
        causal = jnp.arange(C)[None, None, :] <= poss[:, :, None]
        s = jnp.where(causal[:, None, :, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        if K == 1:   # single-row chunk: same gemv hazard as the decode step
            p = jnp.broadcast_to(p, (B, p.shape[1], 2, C))
            o = jnp.einsum("bhqk,bkhd->bqhd", p, vc)[:, :1]
        else:
            o = jnp.einsum("bhqk,bkhd->bqhd", p, vc)
        o = o.reshape(B, K, self.n_out) @ params["Wo"]
        if self.has_bias:
            o = o + params["bo"]
        return (o, nd, None) if carry_stack else (o, nd)

    # ---- tree speculation (serving/spec/tree.py) -------------------------
    def tree_chunk(self, params, dstate, x, pos0, tree, n, state=None,
                   block_tables=None):
        """Ancestry-masked attention over N tree nodes WITHOUT touching
        the cache. Sibling nodes share stream positions, so scattering
        the window's KV before acceptance (what ``prefill_chunk`` does
        for a linear window) would collide; instead each node attends to
        its EFFECTIVE cache — the real cache with positions
        ``pos0 .. pos0+depth(n)`` replaced by the node's own root-path
        K/V (``tree.anc_at_depth`` row n). That cache is element-for-
        element the cache the plain engine would hold after feeding that
        path, and the math is ``_finish_step`` itself over B*N rows, so
        every node's output is bitwise the non-speculative step's output
        for its prefix — the lossless-acceptance bar. The winning path's
        rows commit in ``tree_commit``; rejected nodes never existed as
        far as the cache is concerned."""
        if dstate is None:
            return super().tree_chunk(params, dstate, x, pos0, tree, n,
                                      state=state,
                                      block_tables=block_tables)
        B, N, _ = x.shape
        q, k, v = self._project(params, x)              # (B, N, H, Dh)
        H, Dh = k.shape[2], k.shape[3]
        if "pk" in dstate:
            bs = dstate["pk"].shape[1]
            C = block_tables.shape[1] * bs
            kc = dstate["pk"][block_tables].reshape(B, C, H, Dh)
            vc = dstate["pv"][block_tables].reshape(B, C, H, Dh)
        else:
            kc, vc = dstate["k"], dstate["v"]
            C = kc.shape[1]
        depth = jnp.asarray(tree.depth, jnp.int32)       # (N,)
        aad = jnp.asarray(tree.anc_at_depth, jnp.int32)  # (N, D+1)
        Dp1 = aad.shape[1]
        coff = jnp.arange(C)[None, :] - pos0[:, None]    # (B, C)
        # cache position pos0+dd holds the node's depth-dd ancestor
        on_path = ((coff[:, None, :] >= 0)
                   & (coff[:, None, :] <= depth[None, :, None]))  # (B,N,C)
        didx = jnp.broadcast_to(
            jnp.clip(coff, 0, Dp1 - 1)[:, None, :, None, None],
            (B, N, C, H, Dh))

        def effective(cache, win):
            path = win[:, aad]                           # (B, N, D+1, H, Dh)
            g = jnp.take_along_axis(path, didx, axis=2)  # (B, N, C, H, Dh)
            return jnp.where(on_path[..., None, None], g,
                             cache[:, None])

        effk = effective(kc, k)
        effv = effective(vc, v)
        posn = pos0[:, None] + depth[None, :]            # (B, N)
        o = self._finish_step(params,
                              q.reshape(B * N, 1, H, Dh),
                              effk.reshape(B * N, C, H, Dh),
                              effv.reshape(B * N, C, H, Dh),
                              posn.reshape(B * N))
        return (o.reshape(B, N, self.n_out), dstate, None,
                {"k": k, "v": v})

    def tree_commit(self, params, dstate, kv_window, path, pos0, commit_n,
                    block_tables=None):
        """Scatter the accepted root-path's K/V into the cache at
        positions ``pos0 + d`` for ``d < commit_n`` — the only tree
        writes that ever reach the cache. Paged rows outside the commit
        mask land in the scratch block (the inert-row discipline of
        ``prefill_chunk``); dense rows use a gather-old/where update so
        masked depths rewrite their current value bit-for-bit."""
        B, Dp1 = path.shape
        rows = jnp.arange(B)
        poss = pos0[:, None] + jnp.arange(Dp1)[None, :]  # (B, D+1)
        valid = jnp.arange(Dp1)[None, :] < commit_n[:, None]
        nidx = jnp.broadcast_to(path[:, :, None, None],
                                (B, Dp1) + kv_window["k"].shape[2:])
        kg = jnp.take_along_axis(kv_window["k"], nidx, axis=1)
        vg = jnp.take_along_axis(kv_window["v"], nidx, axis=1)
        if "pk" in dstate:
            bs = dstate["pk"].shape[1]
            MB = block_tables.shape[1]
            bidx = jnp.clip(poss // bs, 0, MB - 1)
            phys = jnp.where(valid, block_tables[rows[:, None], bidx], 0)
            off = poss % bs
            return {"pk": dstate["pk"].at[phys, off].set(kg),
                    "pv": dstate["pv"].at[phys, off].set(vg)}
        C = dstate["k"].shape[1]
        cpos = jnp.clip(poss, 0, C - 1)
        gidx = jnp.broadcast_to(cpos[:, :, None, None],
                                (B, Dp1) + kg.shape[2:])

        def upd(cache, new):
            old = jnp.take_along_axis(cache, gidx, axis=1)
            val = jnp.where(valid[:, :, None, None], new, old)
            return cache.at[rows[:, None], cpos].set(val)

        return {"k": upd(dstate["k"], kg), "v": upd(dstate["v"], vg)}


@register_layer
@dataclass
class LayerNormalization(Layer):
    """Layer norm over the feature axis (companion to attention stacks)."""
    n_in: int = 0
    eps: float = 1e-5

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.size or input_type.flat_size()

    def init(self, rng, dtype=jnp.float32):
        require_dims(self, n_in=self.n_in)
        return {"gamma": jnp.ones((self.n_in,), dtype),
                "beta": jnp.zeros((self.n_in,), dtype)}

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        xn = (x - mean) * jax.lax.rsqrt(var + self.eps)
        return xn * params["gamma"] + params["beta"], state


@register_layer
@dataclass
class PositionalEmbedding(Layer):
    """Learned absolute positional embedding added to (B, T, C) inputs —
    attention is permutation-invariant over a position's prefix, so a
    transformer stack needs this (or rotary) to see token order. Companion
    to MultiHeadAttention; no reference equivalent (the reference has no
    attention at all)."""
    n_in: int = 0
    max_len: int = 512

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.size or input_type.flat_size()

    def init(self, rng, dtype=jnp.float32):
        require_dims(self, n_in=self.n_in)
        return {"P": jax.random.normal(rng, (self.max_len, self.n_in),
                                       dtype) * 0.02}

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        T = x.shape[1]
        if T > self.max_len:
            raise ValueError(f"sequence length {T} exceeds "
                             f"max_len={self.max_len}")
        return x + params["P"][:T], state

    def decode_step(self, params, dstate, x, pos, state=None):
        B = x.shape[0]
        pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
        return x + params["P"][pos][:, None, :], dstate

    def prefill_chunk(self, params, dstate, x, start, n, state=None,
                      block_tables=None, carry_stack=False):
        """Chunk rows sit at global positions ``start + t``, not ``t`` —
        the stateless default's ``apply`` would add P[0:K]."""
        K = x.shape[1]
        poss = start[:, None] + jnp.arange(K)[None, :]   # (B, K)
        poss = jnp.clip(poss, 0, self.max_len - 1)
        y = x + params["P"][poss]
        return (y, dstate, None) if carry_stack else (y, dstate)

    def tree_chunk(self, params, dstate, x, pos0, tree, n, state=None,
                   block_tables=None):
        """Tree node n sits at stream position ``pos0 + depth(n)`` — the
        stateless default's ``apply`` would add P[0:N] by node index."""
        poss = pos0[:, None] + jnp.asarray(tree.depth, jnp.int32)[None, :]
        poss = jnp.clip(poss, 0, self.max_len - 1)
        return x + params["P"][poss], dstate, None, None
