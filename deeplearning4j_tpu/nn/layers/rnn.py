"""Recurrent layers.

Parity: reference nn/conf/layers/GravesLSTM.java, LSTM, GravesBidirectionalLSTM,
nn/layers/recurrent/LSTMHelpers.java:68,392 (shared fwd/bwd math) and the
fused cuDNN RNN path (deeplearning4j-cuda CudnnLSTMHelper.java:588).

TPU design: the input-to-gate projection for the WHOLE sequence is one large
(B*T, C)×(C, 4H) GEMM done outside the time loop (MXU-friendly); only the
recurrent h→gates GEMM lives inside ``lax.scan``. Backward through time is
autodiff through scan — no hand-written BPTT. Param keys follow the reference
(``W`` input weights, ``RW`` recurrent weights, ``b`` bias,
nn/params/LSTMParamInitializer.java).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer, require_dims
from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.losses import get_loss
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.core import OutputLayer


@register_layer
@dataclass
class LSTM(Layer):
    """Standard LSTM (no peepholes). Gate order: [i, f, o, g] — matches the
    reference's IFOG layout (LSTMParamInitializer)."""
    n_in: int = 0
    n_out: int = 0
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.size or input_type.flat_size()

    def output_type(self, input_type):
        return InputType.recurrent(self.n_out, input_type.timeseries_length)

    def init(self, rng, dtype=jnp.float32):
        require_dims(self, n_in=self.n_in, n_out=self.n_out)
        r1, r2 = jax.random.split(rng)
        H = self.n_out
        b = jnp.zeros((4 * H,), dtype)
        b = b.at[H:2 * H].set(self.forget_gate_bias_init)
        return {
            "W": init_weights(r1, (self.n_in, 4 * H), self.weight_init or "xavier",
                              self.dist, dtype, fan_in=self.n_in, fan_out=H),
            "RW": init_weights(r2, (H, 4 * H), self.weight_init or "xavier",
                               self.dist, dtype, fan_in=H, fan_out=H),
            "b": b,
        }

    def _gates(self, params):
        return params["W"], params["RW"], params["b"]

    def _cell(self, params, gate_in_t, h, c, mask_t):
        """One step. gate_in_t: (B, 4H) precomputed x@W + b."""
        H = self.n_out
        act = get_activation(self.activation or "tanh")
        gact = get_activation(self.gate_activation)
        z = gate_in_t + h @ params["RW"]
        i = gact(z[:, 0 * H:1 * H])
        f = gact(z[:, 1 * H:2 * H])
        o = gact(z[:, 2 * H:3 * H])
        g = act(z[:, 3 * H:4 * H])
        c_new = f * c + i * g
        h_new = o * act(c_new)
        if mask_t is not None:
            m = mask_t[:, None]
            h_new = m * h_new + (1 - m) * h
            c_new = m * c_new + (1 - m) * c
        # pin the carry dtype (after the mask blend — masks arrive f32): the
        # TPU dot lowering can return f32 from a bf16 h @ RW, which would
        # otherwise break the scan carry contract
        return h_new.astype(h.dtype), c_new.astype(c.dtype)

    def _fused_supported(self, mask, b, t, dt):
        """cuDNN-parity support check (CudnnLSTMHelper supports plain LSTM,
        sigmoid gates, tanh cell, no masking; everything else falls back to
        the built-in path). Shapes and dtype are screened too so the
        compiled kernel never sees tiles Mosaic can't lay out — the kernel
        runs f32 or bf16 streams natively (f64 gradient checks use the
        built-in path)."""
        from deeplearning4j_tpu import ops
        from deeplearning4j_tpu.ops.lstm_pallas import supported
        return (ops.helpers_enabled() and mask is None
                and type(self) is LSTM
                and self.gate_activation == "sigmoid"
                and (self.activation or "tanh") == "tanh"
                and dt in (jnp.float32, jnp.bfloat16)
                and supported(b, t, self.n_out, jnp.dtype(dt).itemsize,
                              ops.interpret_mode()))

    def _scan(self, params, x, mask, h0, c0):
        B, T, _ = x.shape
        gate_in = x.reshape(B * T, -1) @ params["W"] + params["b"]
        gate_in = gate_in.reshape(B, T, -1).transpose(1, 0, 2)  # (T, B, 4H)
        # compute dtype = the carry dtype apply() derived from (x, W) — NOT
        # gate_in.dtype: the TPU dot lowering promotes bf16@bf16 to f32,
        # which would silently upgrade the whole bf16 path
        dt = h0.dtype
        if self._fused_supported(mask, B, T, dt):
            from deeplearning4j_tpu import ops
            hs, c_last = ops.fused_lstm_sequence(
                gate_in.astype(dt), params["RW"].astype(dt),
                h0.astype(dt), c0.astype(dt), ops.interpret_mode())
            return (hs.transpose(1, 0, 2),
                    (hs[-1], c_last))
        mask_t = None if mask is None else mask.transpose(1, 0)

        def step(carry, inp):
            h, c = carry
            if mask is None:
                g = inp
                h, c = self._cell(params, g, h, c, None)
            else:
                g, m = inp
                h, c = self._cell(params, g, h, c, m)
            return (h, c), h

        xs = gate_in if mask is None else (gate_in, mask_t)
        (hT, cT), hs = lax.scan(step, (h0, c0), xs)
        return hs.transpose(1, 0, 2), (hT, cT)  # (B, T, H)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        B = x.shape[0]
        # carry dtype must match the promoted gate dtype (x64 gradient checks
        # feed f64 params with f32 activations), or the scan carry mismatches
        dt = jnp.result_type(x.dtype, params["W"].dtype)
        h0 = jnp.zeros((B, self.n_out), dt)
        c0 = jnp.zeros((B, self.n_out), dt)
        y, _ = self._scan(params, x, mask, h0, c0)
        return y, state

    def apply_with_carry(self, params, x, carry=None, mask=None):
        """Stateful-inference step (parity: rnnTimeStep,
        MultiLayerNetwork.java:2209 rnnActivateUsingStoredState)."""
        B = x.shape[0]
        if carry is None:
            dt = jnp.result_type(x.dtype, params["W"].dtype)
            carry = (jnp.zeros((B, self.n_out), dt),
                     jnp.zeros((B, self.n_out), dt))
        y, new_carry = self._scan(params, x, mask, carry[0], carry[1])
        return y, new_carry

    # ---- incremental decode ----------------------------------------------
    def init_decode_state(self, params, batch, max_len, dtype=jnp.float32):
        # ``dtype`` is the container's COMPUTE dtype (params are cast to it
        # inside decode_step), matching the carry dtype apply() derives
        return (jnp.zeros((batch, self.n_out), dtype),
                jnp.zeros((batch, self.n_out), dtype))

    def decode_step(self, params, dstate, x, pos, state=None):
        # Same math as one _scan iteration: the input-to-gate GEMM runs on
        # the (B, C) slice instead of (B*T, C); _cell is shared, so
        # GravesLSTM peepholes ride through the override automatically.
        # The cell runs inside a trip-count-2 lax.scan on purpose: XLA:CPU
        # fuses a while-loop body differently from straight-line code (the
        # gate sigmoids recompute the z-add inside per-gate loop fusions),
        # and inlines only trip-count-1 loops — so a plain call to _cell
        # here would differ from the full forward's scan in the last ulp.
        # Two identical iterations keep the loop (and its fusion) intact;
        # we read iteration 0. Cost: one duplicated elementwise cell per
        # step, noise next to the step's dispatch latency.
        h, c = dstate
        gate_in = x[:, 0, :] @ params["W"] + params["b"]

        def body(carry, g):
            hh, cc = self._cell(params, g, carry[0], carry[1], None)
            return (hh, cc), (hh, cc)

        _, (hs, cs) = lax.scan(body, (h, c),
                               jnp.stack([gate_in, gate_in]))
        return hs[0][:, None, :], (hs[0], cs[0])


def lstm_pair_fusable(l1, l2, p1, p2, x, mask):
    """True when two consecutive LSTM layers can run as ONE wavefront
    stacked kernel (ops.fused_lstm2_sequence — the cuDNN numLayers=2
    fused-RNN schedule). Each layer must pass its OWN fused-support
    envelope (``_fused_supported`` — so future envelope changes apply here
    automatically) with the true promoted dtype; the pair additionally
    needs equal hidden sizes (the wavefront batches h1 @ [RW1|W2]), no
    inter-layer dropout/weight-noise (they would need an elementwise op
    between the layers), and the stacked kernel's own VMEM screen."""
    from deeplearning4j_tpu import ops
    from deeplearning4j_tpu.ops.lstm_pallas import (supported2,
                                                    use_pallas_fwd)
    if not (type(l1) is LSTM and type(l2) is LSTM
            and l1.n_out == l2.n_out and l2.n_in == l1.n_out
            and not l2.dropout       # None or 0.0; IDropout objects block
            and l1.weight_noise is None and l2.weight_noise is None):
        return False
    B, T = x.shape[0], x.shape[1]
    # the dtype apply_lstm_pair will actually promote with (same rule as
    # LSTM.apply's carry dtype — f64 gradient checks must fall back)
    dt = jnp.result_type(x.dtype, p1["W"].dtype, p2["W"].dtype)
    if not (l1._fused_supported(mask, B, T, dt)
            and l2._fused_supported(mask, B, T, dt)):
        return False
    interp = ops.interpret_mode()
    return supported2(B, T, l1.n_out, jnp.dtype(dt).itemsize, interp) and \
        (interp or use_pallas_fwd(B, l1.n_out, t=T, dtype=jnp.dtype(dt)))


def apply_lstm_pair(l1, l2, p1, p2, x, *, train, rng):
    """Run two fusable stacked LSTMs through the wavefront kernel.
    Layer-1 dropout applies to x (its own semantics); returns the layer-2
    hidden sequence (B, T, H)."""
    from deeplearning4j_tpu import ops
    x = l1.maybe_dropout(x, train=train, rng=rng)
    B, T, _ = x.shape
    dt = jnp.result_type(x.dtype, p1["W"].dtype, p2["W"].dtype)
    gate_in1 = (x.reshape(B * T, -1) @ p1["W"] + p1["b"])
    gate_in1 = gate_in1.reshape(B, T, -1).transpose(1, 0, 2).astype(dt)
    z = jnp.zeros((B, l1.n_out), dt)
    hs2, _, _, _ = ops.fused_lstm2_sequence(
        gate_in1, p1["RW"].astype(dt), p2["W"].astype(dt),
        p2["b"].astype(dt), p2["RW"].astype(dt), z, z, z, z,
        ops.interpret_mode())
    return hs2.transpose(1, 0, 2)


@register_layer
@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (Graves 2013 variant — parity:
    nn/conf/layers/GravesLSTM.java). Peephole weights key: 'pW' (3H,)."""

    def init(self, rng, dtype=jnp.float32):
        p = super().init(rng, dtype)
        p["pW"] = jnp.zeros((3 * self.n_out,), dtype)
        return p

    def _cell(self, params, gate_in_t, h, c, mask_t):
        H = self.n_out
        act = get_activation(self.activation or "tanh")
        gact = get_activation(self.gate_activation)
        pw = params["pW"]
        z = gate_in_t + h @ params["RW"]
        i = gact(z[:, 0 * H:1 * H] + c * pw[0 * H:1 * H])
        f = gact(z[:, 1 * H:2 * H] + c * pw[1 * H:2 * H])
        g = act(z[:, 3 * H:4 * H])
        c_new = f * c + i * g
        o = gact(z[:, 2 * H:3 * H] + c_new * pw[2 * H:3 * H])
        h_new = o * act(c_new)
        if mask_t is not None:
            m = mask_t[:, None]
            h_new = m * h_new + (1 - m) * h
            c_new = m * c_new + (1 - m) * c
        return h_new.astype(h.dtype), c_new.astype(c.dtype)


@register_layer
@dataclass
class SimpleRnn(Layer):
    """Vanilla RNN: h_t = act(x_t W + h_{t-1} RW + b)."""
    n_in: int = 0
    n_out: int = 0

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.size or input_type.flat_size()

    def output_type(self, input_type):
        return InputType.recurrent(self.n_out, input_type.timeseries_length)

    def init(self, rng, dtype=jnp.float32):
        r1, r2 = jax.random.split(rng)
        return {
            "W": init_weights(r1, (self.n_in, self.n_out),
                              self.weight_init or "xavier", self.dist, dtype),
            "RW": init_weights(r2, (self.n_out, self.n_out),
                               self.weight_init or "xavier", self.dist, dtype),
            "b": jnp.zeros((self.n_out,), dtype),
        }

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        act = get_activation(self.activation or "tanh")
        B, T, _ = x.shape
        gate_in = (x.reshape(B * T, -1) @ params["W"] + params["b"]).reshape(B, T, -1)
        gate_in = gate_in.transpose(1, 0, 2)
        mask_t = None if mask is None else mask.transpose(1, 0)

        def step(h, inp):
            if mask is None:
                g = inp
                h_new = act(g + h @ params["RW"])
            else:
                g, m = inp
                h_new = act(g + h @ params["RW"])
                h_new = m[:, None] * h_new + (1 - m[:, None]) * h
            h_new = h_new.astype(h.dtype)
            return h_new, h_new

        xs = gate_in if mask is None else (gate_in, mask_t)
        h0 = jnp.zeros((B, self.n_out),
                       jnp.result_type(x.dtype, params["W"].dtype))
        _, hs = lax.scan(step, h0, xs)
        return hs.transpose(1, 0, 2), state

    # ---- incremental decode ----------------------------------------------
    def init_decode_state(self, params, batch, max_len, dtype=jnp.float32):
        return jnp.zeros((batch, self.n_out), dtype)

    def decode_step(self, params, dstate, x, pos, state=None):
        # trip-count-2 scan for the same loop-body-fusion reason as
        # LSTM.decode_step (see comment there)
        act = get_activation(self.activation or "tanh")
        gate_in = x[:, 0, :] @ params["W"] + params["b"]

        def body(h, g):
            h_new = act(g + h @ params["RW"]).astype(h.dtype)
            return h_new, h_new

        _, hs = lax.scan(body, dstate, jnp.stack([gate_in, gate_in]))
        return hs[0][:, None, :], hs[0]


@register_layer
@dataclass
class Bidirectional(Layer):
    """Bidirectional wrapper (parity: nn/conf/layers/recurrent/Bidirectional).
    mode: concat | add | mul | ave."""
    fwd: Optional[Layer] = None
    mode: str = "concat"

    def set_n_in(self, input_type):
        self.fwd.set_n_in(input_type)

    def apply_defaults(self, defaults):
        super().apply_defaults(defaults)
        if self.fwd is not None:
            self.fwd.apply_defaults(defaults)

    def output_type(self, input_type):
        ot = self.fwd.output_type(input_type)
        if self.mode == "concat":
            return InputType.recurrent(ot.size * 2, ot.timeseries_length)
        return ot

    def init(self, rng, dtype=jnp.float32):
        r1, r2 = jax.random.split(rng)
        return {"fwd": self.fwd.init(r1, dtype), "bwd": self.fwd.init(r2, dtype)}

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        yf, _ = self.fwd.apply(params["fwd"], x, None, train=train, rng=rng, mask=mask)
        xr = jnp.flip(x, axis=1)
        mr = None if mask is None else jnp.flip(mask, axis=1)
        yb, _ = self.fwd.apply(params["bwd"], xr, None, train=train, rng=rng, mask=mr)
        yb = jnp.flip(yb, axis=1)
        if self.mode == "concat":
            return jnp.concatenate([yf, yb], axis=-1), state
        if self.mode == "add":
            return yf + yb, state
        if self.mode == "mul":
            return yf * yb, state
        if self.mode == "ave":
            return 0.5 * (yf + yb), state
        raise ValueError(self.mode)

    def decode_step(self, params, dstate, x, pos, state=None):
        raise ValueError(
            "Bidirectional layers consume the whole sequence (the backward "
            "direction reads future tokens) and cannot decode incrementally")


@register_layer
@dataclass
class GravesBidirectionalLSTM(Layer):
    """Legacy fused bidirectional Graves LSTM
    (parity: nn/conf/layers/GravesBidirectionalLSTM.java)."""
    n_in: int = 0
    n_out: int = 0

    def __post_init__(self):
        self._bi = None

    def _build(self):
        if self._bi is None:
            inner = GravesLSTM(n_in=self.n_in, n_out=self.n_out,
                               activation=self.activation,
                               weight_init=self.weight_init, dist=self.dist)
            self._bi = Bidirectional(fwd=inner, mode="add")
        return self._bi

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.size or input_type.flat_size()

    def output_type(self, input_type):
        return InputType.recurrent(self.n_out, input_type.timeseries_length)

    def init(self, rng, dtype=jnp.float32):
        return self._build().init(rng, dtype)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        return self._build().apply(params, x, state, train=train, rng=rng, mask=mask)

    def decode_step(self, params, dstate, x, pos, state=None):
        return self._build().decode_step(params, dstate, x, pos, state=state)


@register_layer
@dataclass
class LastTimeStep(Layer):
    """Wrapper: run inner RNN layer, keep only the last (unmasked) step."""
    fwd: Optional[Layer] = None

    def set_n_in(self, input_type):
        self.fwd.set_n_in(input_type)

    def apply_defaults(self, defaults):
        super().apply_defaults(defaults)
        if self.fwd is not None:
            self.fwd.apply_defaults(defaults)

    def output_type(self, input_type):
        ot = self.fwd.output_type(input_type)
        return InputType.feed_forward(ot.size)

    def init(self, rng, dtype=jnp.float32):
        return self.fwd.init(rng, dtype)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        y, _ = self.fwd.apply(params, x, None, train=train, rng=rng, mask=mask)
        if mask is None:
            return y[:, -1, :], state
        # last SET step, robust to gapped masks (see LastTimeStepVertex)
        T = mask.shape[1]
        idx = T - 1 - jnp.argmax(mask[:, ::-1] > 0, axis=1).astype(jnp.int32)
        idx = jnp.where(jnp.any(mask > 0, axis=1), idx, 0)
        return y[jnp.arange(y.shape[0]), idx, :], state

    def decode_step(self, params, dstate, x, pos, state=None):
        raise ValueError(
            "LastTimeStep collapses the time axis; it has no per-token "
            "incremental form")


@register_layer
@dataclass
class RnnOutputLayer(OutputLayer):
    """Time-distributed output layer over (B,T,C)
    (parity: nn/conf/layers/RnnOutputLayer.java)."""

    def output_type(self, input_type):
        return InputType.recurrent(self.n_out, input_type.timeseries_length)

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation or "softmax")(y), state


@register_layer
@dataclass
class RnnLossLayer(Layer):
    """Parameterless time-distributed loss."""
    loss: str = "mcxent"

    def has_params(self):
        return False

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        return get_activation(self.activation or "identity")(x), state

    def compute_score(self, params, x, labels, mask=None, *, train=False, rng=None):
        B, T = x.shape[0], x.shape[1]
        xf = x.reshape(B * T, -1)
        lf = labels.reshape(B * T, -1)
        mf = None if mask is None else mask.reshape(B * T)
        return get_loss(self.loss)(lf, xf, self.activation or "identity", mf)
