"""Core feed-forward layers.

Parity: reference nn/conf/layers/DenseLayer.java, OutputLayer.java,
LossLayer.java, ActivationLayer.java, DropoutLayer.java, EmbeddingLayer.java,
ElementWiseMultiplicationLayer + nn/layers/feedforward/** impls. Param keys
match the reference ("W", "b") for import compatibility
(nn/params/DefaultParamInitializer.java).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.layers.base import Layer, register_layer, require_dims
from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.losses import get_loss
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.nn.conf.inputs import InputType


@register_layer
@dataclass
class DenseLayer(Layer):
    """Fully connected layer: y = act(x @ W + b). On 3d (B,T,C) input the
    matmul is applied per timestep — one big (B*T, C) GEMM on the MXU
    (the reference inserts an RnnToFeedForwardPreProcessor instead)."""
    n_in: int = 0
    n_out: int = 0
    has_bias: bool = True

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.flat_size() if input_type.kind != "rnn" \
                else input_type.size

    def output_type(self, input_type):
        if input_type.kind == "rnn":
            return InputType.recurrent(self.n_out, input_type.timeseries_length)
        return InputType.feed_forward(self.n_out)

    def init(self, rng, dtype=jnp.float32):
        require_dims(self, n_in=self.n_in, n_out=self.n_out)
        p = {"W": init_weights(rng, (self.n_in, self.n_out),
                               self.weight_init or "xavier", self.dist, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init or 0.0, dtype)
        return p

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        if x.ndim >= 4 or (x.ndim == 3 and x.shape[-1] != self.n_in):
            x = x.reshape(x.shape[0], -1)  # implicit CNN→FF flatten
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation or "identity")(y), state


@register_layer
@dataclass
class OutputLayer(DenseLayer):
    """Dense + loss head (parity: nn/conf/layers/OutputLayer.java). The
    container calls ``compute_score`` with labels during training."""
    loss: str = "mcxent"

    def compute_score(self, params, x, labels, mask=None, *, train=False, rng=None):
        x = self.maybe_dropout(x, train=train, rng=rng)
        if x.ndim >= 4 or (x.ndim == 3 and x.shape[-1] != self.n_in):
            x = x.reshape(x.shape[0], -1)
        pre = x @ params["W"]
        if self.has_bias:
            pre = pre + params["b"]
        if pre.ndim == 3:  # (B,T,C) time-distributed loss
            B, T, C = pre.shape
            pre = pre.reshape(B * T, C)
            labels = labels.reshape(B * T, -1)
            if mask is not None:
                mask = mask.reshape(B * T)
        return get_loss(self.loss)(labels, pre, self.activation or "softmax", mask)


@register_layer
@dataclass
class LossLayer(Layer):
    """Loss-only head, no params (parity: nn/conf/layers/LossLayer.java)."""
    loss: str = "mcxent"

    def has_params(self):
        return False

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        return get_activation(self.activation or "identity")(x), state

    def compute_score(self, params, x, labels, mask=None, *, train=False, rng=None):
        return get_loss(self.loss)(labels, x, self.activation or "identity", mask)


@register_layer
@dataclass
class ActivationLayer(Layer):
    def has_params(self):
        return False

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        return get_activation(self.activation or "relu")(x), state


@register_layer
@dataclass
class DropoutLayer(Layer):
    def has_params(self):
        return False

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        return self.maybe_dropout(x, train=train, rng=rng), state


@register_layer
@dataclass
class FlattenLayer(Layer):
    """Flatten all non-batch dims to (B, N). Needed for Keras-import parity
    where a Flatten precedes a Dense over a SEQUENCE input — our DenseLayer
    is time-distributed on (B, T, C), not flattening (for CNN inputs it
    flattens natively, core.py:30)."""

    def has_params(self):
        return False

    def output_type(self, input_type):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        if input_type.kind == "rnn":
            t = input_type.timeseries_length
            if t is None or t <= 0:
                raise ValueError(
                    "FlattenLayer over a sequence input needs a static "
                    "timeseries length (flat width = size * T)")
            return InputType.feed_forward(input_type.size * t)
        return InputType.feed_forward(input_type.flat_size())

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        return x.reshape(x.shape[0], -1), state


@register_layer
@dataclass
class ReshapeLayer(Layer):
    """Reshape activations to ``target_shape`` (excluding the batch dim).
    Parity role: the reference's ReshapeVertex / KerasReshape
    (modelimport/keras/layers/core/KerasReshape.java) as a sequential layer.
    Rank decides the output kind: 1 → feed-forward, 2 → recurrent (T, C),
    3 → convolutional (H, W, C) — this build's native layouts. One ``-1``
    wildcard dim is resolved from the input's flat size (Keras Reshape
    semantics)."""
    target_shape: tuple = ()

    def __post_init__(self):
        self.target_shape = tuple(int(d) for d in self.target_shape)
        if sum(1 for d in self.target_shape if d == -1) > 1:
            raise ValueError(
                f"Reshape target {self.target_shape} has more than one -1")

    def has_params(self):
        return False

    def _resolved(self, flat: int) -> tuple:
        s = self.target_shape
        if -1 not in s:
            return s
        known = 1
        for d in s:
            if d != -1:
                known *= d
        if known <= 0 or flat % known != 0:
            raise ValueError(
                f"Cannot infer -1 in reshape target {s} from flat size {flat}")
        return tuple(flat // known if d == -1 else d for d in s)

    def output_type(self, input_type):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        s = self.target_shape
        if -1 in s:
            flat = (input_type.size * input_type.timeseries_length
                    if input_type.kind == "rnn"
                    and input_type.timeseries_length > 0
                    else input_type.flat_size())
            s = self._resolved(flat)
        if len(s) == 1:
            return InputType.feed_forward(s[0])
        if len(s) == 2:
            return InputType.recurrent(s[1], s[0])
        if len(s) == 3:
            return InputType.convolutional(s[0], s[1], s[2])
        raise ValueError(f"Unsupported reshape target {s}")

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        return x.reshape((x.shape[0],) + self.target_shape), state


@register_layer
@dataclass
class EmbeddingLayer(Layer):
    """Index → vector lookup (parity: nn/conf/layers/EmbeddingLayer.java).
    Input: (B,) or (B,1) int indices. A gather, not a one-hot matmul —
    XLA lowers this to a dynamic-slice, cheap on TPU."""
    n_in: int = 0   # vocab size
    n_out: int = 0
    has_bias: bool = True

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.flat_size()

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)

    def init(self, rng, dtype=jnp.float32):
        p = {"W": init_weights(rng, (self.n_in, self.n_out),
                               self.weight_init or "xavier", self.dist, dtype)}
        if self.has_bias:
            p["b"] = jnp.full((self.n_out,), self.bias_init or 0.0, dtype)
        return p

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[:, 0]
        y = params["W"][idx]
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation or "identity")(y), state


@register_layer
@dataclass
class EmbeddingSequenceLayer(Layer):
    """Sequence of indices → sequence of vectors: (B,T) → (B,T,E)."""
    n_in: int = 0
    n_out: int = 0
    has_bias: bool = False

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.size or input_type.flat_size()

    def output_type(self, input_type):
        t = input_type.timeseries_length if input_type.kind == "rnn" else -1
        return InputType.recurrent(self.n_out, t)

    def init(self, rng, dtype=jnp.float32):
        p = {"W": init_weights(rng, (self.n_in, self.n_out),
                               self.weight_init or "xavier", self.dist, dtype)}
        if self.has_bias:
            p["b"] = jnp.zeros((self.n_out,), dtype)
        return p

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        y = params["W"][idx]
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation or "identity")(y), state


@register_layer
@dataclass
class PReLULayer(Layer):
    """Learned leaky-relu slope (parity: nn/conf/layers/PReLULayer later refs;
    alpha shared per-feature)."""
    n_in: int = 0

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.flat_size()

    def init(self, rng, dtype=jnp.float32):
        return {"alpha": jnp.zeros((self.n_in,), dtype)}

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        a = params["alpha"]
        shape = [1] * (x.ndim - 1) + [a.shape[0]]
        a = a.reshape(shape)
        return jnp.where(x >= 0, x, a * x), state


@register_layer
@dataclass
class ElementWiseMultiplicationLayer(Layer):
    """y = act(x * w + b), elementwise learned scaling
    (parity: nn/conf/layers/misc/ElementWiseMultiplicationLayer)."""
    n_in: int = 0
    n_out: int = 0

    def set_n_in(self, input_type):
        if self.n_in == 0:
            self.n_in = input_type.flat_size()
        self.n_out = self.n_in

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out or self.n_in)

    def init(self, rng, dtype=jnp.float32):
        return {"W": jnp.ones((self.n_in,), dtype),
                "b": jnp.zeros((self.n_in,), dtype)}

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        y = x * params["W"] + params["b"]
        return get_activation(self.activation or "identity")(y), state
