"""Layer base protocol + registry + JSON serde.

Replaces the reference's two-sided design (declarative nn/conf/layers/*.java
config POJOs + imperative nn/layers/** Layer impls with hand-written
``backpropGradient``, nn/api/Layer.java:38): here a layer is ONE dataclass
whose ``apply`` is a pure traced function; autodiff provides the backward.

Protocol:
- ``set_n_in(input_type)``  — infer input width (parity:
  MultiLayerConfiguration.setInputType nIn inference).
- ``output_type(input_type)`` — shape inference.
- ``init(rng, dtype)`` — params pytree ({} if parameterless).
- ``init_state()`` — non-trainable state pytree ({} if stateless; batchnorm
  running stats live here, carried functionally through the train step).
- ``apply(params, x, state=…, train=…, rng=…, mask=…)`` →
  ``(y, new_state)``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Any, Dict

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.updaters import Updater
from deeplearning4j_tpu.nn.conf.inputs import InputType

LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(cls):
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


# fields every layer may inherit from the global NeuralNetConfiguration
INHERITABLE = ("activation", "weight_init", "updater", "l1", "l2", "dropout",
               "bias_init", "dist", "weight_noise")


@dataclass
class Layer:
    """Base layer config. ``None`` hyperparameters inherit the network-level
    defaults at build time (parity: NeuralNetConfiguration.Builder global
    defaults, NeuralNetConfiguration.java:570)."""
    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    dist: Optional[tuple] = None            # for weight_init='distribution'
    bias_init: Optional[float] = None
    updater: Optional[Updater] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None          # drop probability (NOT dl4j retain-prob)
    weight_noise: Optional[object] = None    # IWeightNoise (DropConnect/...)
    constraints: Optional[tuple] = None      # e.g. ('maxnorm', 2.0)

    # ---- config protocol -------------------------------------------------
    def apply_defaults(self, defaults: Dict[str, Any]):
        for f in INHERITABLE:
            if hasattr(self, f) and getattr(self, f) is None and f in defaults:
                setattr(self, f, defaults[f])

    def validate(self) -> None:
        """Fail fast on unknown activation/loss names at config-build time
        (parity: the reference's enums make these unrepresentable)."""
        from deeplearning4j_tpu.nn.activations import get_activation
        if getattr(self, "activation", None) is not None:
            get_activation(self.activation)
        if getattr(self, "loss", None) is not None:
            from deeplearning4j_tpu.nn.losses import get_loss
            get_loss(self.loss)

    def set_n_in(self, input_type: InputType) -> None:
        pass

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    # ---- runtime protocol ------------------------------------------------
    def init(self, rng, dtype=jnp.float32) -> Dict[str, Any]:
        return {}

    def init_state(self, dtype=jnp.float32) -> Dict[str, Any]:
        return {}

    def apply(self, params, x, state=None, *, train=False, rng=None, mask=None):
        raise NotImplementedError

    # ---- incremental decode protocol (serving/decode.py) -----------------
    # Autoregressive serving feeds ONE token per call; layers that carry
    # sequence context expose it as explicit decode state so the whole
    # stack becomes a fixed-shape (B, 1, F) → (B, 1, F) step the containers
    # can jit exactly once. Stateless layers (dense, norm, activations)
    # inherit these defaults: no state, apply() on the length-1 slice.

    # Decode-state dict keys that are POSITIONAL: written at an explicit
    # position index (attention KV caches), so speculative rewind
    # (serving/spec/) can leave over-written positions in place and rely
    # on the causal position mask — only NON-positional leaves (recurrent
    # carries) need snapshot/rollback. Plain class attribute, not a
    # dataclass field.
    positional_state_keys = ()

    def init_decode_state(self, params, batch: int, max_len: int,
                          dtype=jnp.float32):
        """Per-slot decode state for a batch of ``batch`` concurrent
        streams (None = stateless). RNNs return the (h, c) carry; attention
        returns a fixed-capacity KV cache of ``max_len`` positions."""
        return None

    def decode_step(self, params, dstate, x, pos, state=None):
        """One incremental token step. ``x``: (B, 1, F) activations for the
        current position; ``pos``: (B,) int32 global position of that token
        per stream. Returns ``(y, new_dstate)`` with y (B, 1, F_out).
        Must be bitwise-equal to the same position of a full-sequence
        ``apply`` (decode correctness bar — see docs/DECODING.md)."""
        y, _ = self.apply(params, x, state, train=False, rng=None)
        return y, dstate

    # ---- paged decode protocol (serving/kv/) -----------------------------
    # The paged engine stores attention KV in a shared block pool indexed
    # by per-slot page tables instead of per-slot dense strips. Layers
    # WITHOUT a KV cache keep per-slot state exactly as in the dense
    # protocol, so the defaults delegate; MultiHeadAttention overrides all
    # three (pool-shaped state, table-gather step, chunk prefill).
    def init_paged_decode_state(self, params, batch: int, max_len: int,
                                num_blocks: int, block_size: int,
                                dtype=jnp.float32):
        """Decode state under paged KV: attention returns pool arrays
        ((num_blocks, block_size, H, Dh) — keys in kv.POOL_KEYS); every
        other layer returns its dense per-slot state unchanged."""
        return self.init_decode_state(params, batch, max_len, dtype)

    def decode_step_paged(self, params, dstate, x, pos, block_tables,
                          state=None):
        """``decode_step`` with a (B, max_blocks) int32 page table mapping
        each stream's logical blocks to pool blocks. Layers without a KV
        cache ignore the table."""
        return self.decode_step(params, dstate, x, pos, state=state)

    def prefill_chunk(self, params, dstate, x, start, n, state=None,
                      block_tables=None, carry_stack=False):
        """Advance a chunk of prefill positions in one call. ``x``:
        (B, K, F) activations for positions ``start .. start+K-1`` per
        stream; ``n``: (B,) int32 valid rows (rows t >= n[b] are padding —
        their state writes are masked and their outputs garbage the caller
        discards). Returns ``(y, new_dstate)`` with y (B, K, F_out).

        Default: stateless layers apply() the whole chunk (timestep-wise
        ops make this the full-forward math); stateful layers advance
        their carry by scanning ``decode_step`` with a per-row valid mask
        — bitwise the same trajectory a token-at-a-time prefill walks.

        ``carry_stack=True`` returns ``(y, new_dstate, snapshots)`` where
        ``snapshots`` stacks the carry after EVERY chunk position along a
        leading (K, ...) axis (None for stateless layers and layers whose
        state is positional — ``positional_state_keys``). The speculative
        verify program (serving/spec/verify.py) rewinds a slot to the
        carry after its accepted prefix by selecting into this stack."""
        if dstate is None:
            y, _ = self.apply(params, x, state, train=False, rng=None)
            return (y, dstate, None) if carry_stack else (y, dstate)
        B, K = x.shape[0], x.shape[1]
        xs = jnp.moveaxis(x, 1, 0)[:, :, None, :]       # (K, B, 1, F)

        def step(d, xt_t):
            xt, t = xt_t
            y, nd = self.decode_step(params, d, xt, start + t, state=state)
            v = t < n                                   # (B,) row validity

            def keep(a, b):
                return jnp.where(v.reshape((B,) + (1,) * (a.ndim - 1)), a, b)

            nd = jax.tree_util.tree_map(keep, nd, d)
            return nd, ((y, nd) if carry_stack else y)

        if carry_stack:
            d, (ys, snaps) = jax.lax.scan(step, dstate, (xs, jnp.arange(K)))
            return jnp.moveaxis(ys[:, :, 0, :], 0, 1), d, snaps
        d, ys = jax.lax.scan(step, dstate, (xs, jnp.arange(K)))
        return jnp.moveaxis(ys[:, :, 0, :], 0, 1), d

    # ---- tree-speculation protocol (serving/spec/tree.py) ----------------
    # Tree verification feeds N tree NODES as extra window positions:
    # node n sits at stream position ``pos0 + tree.depth[n]`` and may only
    # see its own root-path (ancestry, not linearity). Stateless layers
    # are position-free and just apply(); carry layers scan the nodes with
    # a node-indexed snapshot stack so every node resumes its PARENT's
    # carry; attention overrides with an ancestry-masked cache read that
    # writes NOTHING (siblings share stream positions, so committing
    # before acceptance would collide) — the winning path's KV lands in
    # ``tree_commit`` afterwards, inside the same verify program.
    def tree_chunk(self, params, dstate, x, pos0, tree, n, state=None,
                   block_tables=None):
        """Score all N tree nodes in one call. ``x``: (B, N, F) node
        activations in tree order; ``pos0``: (B,) root stream position;
        ``tree``: the static ``serving.spec.tree.TreeSpec``; ``n``: (B,)
        emit budget (0 = inert row, its state must stay bitwise).

        Returns ``(y, new_dstate, carry_stack, kv_window)``:

        - ``y`` (B, N, F_out) per-node outputs,
        - ``new_dstate`` — positional leaves unchanged (nothing is
          committed here), carry leaves unchanged (the verifier selects
          the final carry out of the stack),
        - ``carry_stack`` — carries stacked along a leading NODE axis
          (N, B, ...): entry n is the carry after node n's root-path,
          so rewind is ``stack[path_node, rows]`` (None when the layer
          keeps no carry),
        - ``kv_window`` — the N nodes' fresh K/V rows for
          ``tree_commit`` (attention only, else None)."""
        if dstate is None:
            y, _ = self.apply(params, x, state, train=False, rng=None)
            return y, dstate, None, None
        B, N = x.shape[0], x.shape[1]
        xs = jnp.moveaxis(x, 1, 0)[:, :, None, :]       # (N, B, 1, F)
        parent = jnp.asarray(tree.parent, jnp.int32)
        depth = jnp.asarray(tree.depth, jnp.int32)
        tmap = jax.tree_util.tree_map
        stack0 = tmap(lambda a: jnp.zeros((N,) + a.shape, a.dtype), dstate)

        def step(stack, xt_t):
            xt, t = xt_t
            par = parent[t]
            # resume the PARENT's carry: the root (par < 0) resumes the
            # slot's incoming carry, every other node its parent snapshot
            d_in = tmap(
                lambda s, base: jnp.where(par < 0, base,
                                          s[jnp.clip(par, 0, N - 1)]),
                stack, dstate)
            y, nd = self.decode_step(params, d_in, xt, pos0 + depth[t],
                                     state=state)
            stack = tmap(lambda s, a: s.at[t].set(a), stack, nd)
            return stack, y

        stack, ys = jax.lax.scan(step, stack0, (xs, jnp.arange(N)))
        return jnp.moveaxis(ys[:, :, 0, :], 0, 1), dstate, stack, None

    def tree_commit(self, params, dstate, kv_window, path, pos0, commit_n,
                    block_tables=None):
        """Write the accepted root-path's positional state. ``path``:
        (B, D+1) accepted node index per depth (saturated past the
        accepted depth); ``commit_n``: (B,) number of depths to commit
        (= emitted tokens; 0 = inert row, state bitwise untouched).
        Only layers with positional state override; the default is a
        no-op because carry layers roll back through the snapshot stack
        instead (serving/spec/rewind.py)."""
        return dstate

    def has_params(self) -> bool:
        return True

    # dropout on the INPUT activations, matching the reference convention
    # (BaseLayer.applyDropOutIfNecessary before preOutput). ``dropout`` is a
    # float drop-probability (standard dropout) or an IDropout object
    # (AlphaDropout/GaussianDropout/GaussianNoise — nn/conf/dropout parity)
    def maybe_dropout(self, x, *, train, rng):
        d = self.dropout
        if not train or d is None or rng is None:
            return x
        from deeplearning4j_tpu.nn.dropout import IDropout
        if isinstance(d, IDropout):
            return d.apply(x, rng)
        if d <= 0.0:
            return x
        keep = 1.0 - d
        m = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(m, x / keep, 0.0)

    # ---- regularization: container sums these into the loss --------------
    def reg_loss(self, params):
        l1 = self.l1 or 0.0
        l2 = self.l2 or 0.0
        if (l1 == 0.0 and l2 == 0.0) or not params:
            return 0.0
        total = 0.0
        for k, v in params.items():
            if k.startswith("b") or k in ("beta", "gamma", "mean", "var"):
                continue  # no l1/l2 on biases or norm params, like the reference
            for vv in jax.tree_util.tree_leaves(v):
                total = total + l1 * jnp.abs(vv).sum() + 0.5 * l2 * (vv ** 2).sum()
        return total

    def apply_constraints(self, params):
        """Post-update parameter constraints (parity: nn/conf/constraint/*)."""
        if not self.constraints or not params:
            return params
        kind = self.constraints[0]
        arg = self.constraints[1] if len(self.constraints) > 1 else 1.0
        out = dict(params)
        for k, v in params.items():
            if k.startswith("b") or isinstance(v, dict):
                continue
            if kind == "maxnorm":
                axes = tuple(range(v.ndim - 1))
                n = jnp.sqrt((v ** 2).sum(axis=axes, keepdims=True))
                out[k] = v * jnp.clip(n, 0, arg) / jnp.maximum(n, 1e-8)
            elif kind == "unitnorm":
                axes = tuple(range(v.ndim - 1))
                n = jnp.sqrt((v ** 2).sum(axis=axes, keepdims=True))
                out[k] = v / jnp.maximum(n, 1e-8)
            elif kind == "nonneg":
                out[k] = jnp.maximum(v, 0.0)
            elif kind == "minmaxnorm":
                lo, hi = self.constraints[1], self.constraints[2]
                axes = tuple(range(v.ndim - 1))
                n = jnp.sqrt((v ** 2).sum(axis=axes, keepdims=True))
                out[k] = v * jnp.clip(n, lo, hi) / jnp.maximum(n, 1e-8)
        return out

    # ---- serde -----------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        from deeplearning4j_tpu.nn.weightnoise import IWeightNoise
        from deeplearning4j_tpu.nn.dropout import IDropout
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, Updater):
                v = v.to_dict()
            elif isinstance(v, (IWeightNoise, IDropout)):
                v = v.to_dict()
            elif isinstance(v, Layer):  # wrappers (Bidirectional, Frozen)
                v = v.to_dict()
            elif isinstance(v, tuple):
                v = list(v)
            d[f.name] = v
        d["@type"] = type(self).__name__
        return d

    @classmethod
    def _from_dict_fields(cls, d):
        d = dict(d)
        d.pop("@type", None)
        fields = {f.name: f for f in dataclasses.fields(cls)}
        kwargs = {}
        for k, v in d.items():
            if k not in fields:
                continue
            if k == "updater" and isinstance(v, dict):
                v = Updater.from_dict(v)
            elif isinstance(v, dict) and "@noise" in v:
                from deeplearning4j_tpu.nn.weightnoise import IWeightNoise
                v = IWeightNoise.from_dict(v)
            elif isinstance(v, dict) and "@dropout" in v:
                from deeplearning4j_tpu.nn.dropout import IDropout
                v = IDropout.from_dict(v)
            elif isinstance(v, dict) and "@type" in v:
                v = layer_from_dict(v)
            elif isinstance(v, list):
                v = tuple(v)
            kwargs[k] = v
        return cls(**kwargs)


def layer_from_dict(d: Dict[str, Any]) -> Layer:
    cls = LAYER_REGISTRY[d["@type"]]
    return cls._from_dict_fields(d)


def require_dims(layer, **dims):
    """Validate that inferred/declared dims are set before init — catches
    building a net without set_input_type and without explicit n_in."""
    for k, v in dims.items():
        if not v or v <= 0:
            raise ValueError(
                f"{type(layer).__name__}: {k}={v} is not set. Provide "
                f"set_input_type(...) on the ListBuilder/GraphBuilder or set "
                f"{k} explicitly on the layer.")


def as_pair(v):
    """Normalize an int-or-pair hyperparameter to a 2-tuple."""
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v, v)
