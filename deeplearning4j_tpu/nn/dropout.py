"""Dropout family — standard, alpha, gaussian-multiplicative, gaussian-add.

Parity surface: reference nn/conf/dropout/ — IDropout.java (applyDropout on
input activations at forward time), Dropout.java, AlphaDropout.java
(SELU-self-normalization-preserving, Klambauer et al. 2017 §A),
GaussianDropout.java (multiplicative N(1, rate/(1-rate)) noise) and
GaussianNoise.java (additive N(0, stddev)). A layer's ``dropout`` field
takes either a plain float drop-probability (standard dropout, the common
case) or one of these objects; the containers draw a fresh fold of the
iteration-seeded PRNG per layer per step, so noise is i.i.d. across steps
but reproducible given the seed.

NOTE: this build uses DROP probability p everywhere (keep = 1-p), unlike
dl4j's retain-probability convention — documented on Layer.dropout.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp

_DROPOUT_REGISTRY = {}


def _register(cls):
    _DROPOUT_REGISTRY[cls.__name__] = cls
    return cls


@dataclass
class IDropout:
    """Base: apply(x, rng) -> noised activations (train-time only; the
    containers skip the call at inference, matching the reference's
    inverted-dropout convention of no test-time rescaling)."""

    def apply(self, x, rng):
        raise NotImplementedError

    def to_dict(self):
        return {"@dropout": type(self).__name__, **dataclasses.asdict(self)}

    @staticmethod
    def from_dict(d):
        d = dict(d)
        cls = _DROPOUT_REGISTRY[d.pop("@dropout")]
        return cls(**d)


@_register
@dataclass
class Dropout(IDropout):
    """Standard inverted dropout (parity: nn/conf/dropout/Dropout.java)."""
    p: float = 0.5

    def apply(self, x, rng):
        keep = 1.0 - self.p
        m = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(m, x / keep, jnp.zeros((), x.dtype))


@_register
@dataclass
class AlphaDropout(IDropout):
    """Dropout that preserves the self-normalizing property of SELU nets
    (parity: nn/conf/dropout/AlphaDropout.java): dropped units are set to
    alpha' = -scale*alpha and the result is affine-corrected so mean/variance
    are unchanged."""
    p: float = 0.05

    _ALPHA = 1.6732632423543772
    _SCALE = 1.0507009873554805

    def apply(self, x, rng):
        keep = 1.0 - self.p
        ap = -self._SCALE * self._ALPHA                      # alpha'
        a = (keep + ap * ap * keep * (1.0 - keep)) ** -0.5
        b = -a * ap * (1.0 - keep)
        m = jax.random.bernoulli(rng, keep, x.shape)
        return (a * jnp.where(m, x, jnp.asarray(ap, x.dtype)) + b).astype(
            x.dtype)


@_register
@dataclass
class GaussianDropout(IDropout):
    """Multiplicative gaussian noise ~ N(1, rate/(1-rate))
    (parity: nn/conf/dropout/GaussianDropout.java)."""
    rate: float = 0.5

    def apply(self, x, rng):
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        return x * (1.0 + std * jax.random.normal(rng, x.shape, x.dtype))


@_register
@dataclass
class GaussianNoise(IDropout):
    """Additive gaussian noise N(0, stddev)
    (parity: nn/conf/dropout/GaussianNoise.java)."""
    stddev: float = 0.1

    def apply(self, x, rng):
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype)
