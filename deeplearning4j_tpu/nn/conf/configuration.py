"""Network configuration DSL.

Parity surface: reference NeuralNetConfiguration.Builder
(nn/conf/NeuralNetConfiguration.java:570), MultiLayerConfiguration,
ComputationGraphConfiguration (nn/conf/ComputationGraphConfiguration.java) and
their JSON serde (nn/conf/serde/). The builder carries global hyperparameter
defaults that unset layer fields inherit — same semantics as the reference's
``Builder.layer(...)`` cascade.

Usage:
    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(Adam(1e-3))
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
"""

from __future__ import annotations

import json
import copy
import dataclasses
from dataclasses import dataclass, field as dc_field
from typing import Optional, List, Dict, Any, Tuple

from deeplearning4j_tpu.nn.updaters import Updater, Sgd
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, layer_from_dict


@dataclass
class GlobalConf:
    """Network-level defaults + training semantics."""
    seed: int = 12345
    activation: str = "sigmoid"          # reference default
    weight_init: str = "xavier"
    dist: Optional[tuple] = None
    bias_init: float = 0.0
    updater: Updater = dc_field(default_factory=lambda: Sgd(1e-3))
    l1: float = 0.0
    l2: float = 0.0
    dropout: object = 0.0                # float drop-prob or IDropout object
    optimization_algo: str = "sgd"       # sgd | lbfgs | line_gradient_descent
    max_num_line_search_iterations: int = 5
    minimize: bool = True
    mini_batch: bool = True
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    dtype: str = "float32"               # param dtype
    compute_dtype: Optional[str] = None  # e.g. 'bfloat16' for MXU-friendly fwd/bwd
    # rematerialize activations in the backward pass (jax.checkpoint over
    # the loss). True/'full' recomputes everything; 'save_convs' (alias
    # 'selective') keeps conv outputs and recomputes only BN/activations.
    # On TPU the conv-net backward is HBM-bound on stored activations: full
    # remat measures up to 5x faster at CIFAR shapes, 'save_convs' wins at
    # 224 where conv recompute costs real FLOPs (docs/PERF_R05.md) — the
    # role cudnn workspace tuning plays in the reference's helper seam
    remat: object = False   # False | True | 'full' | 'save_convs' | 'selective'
    weight_noise: Optional[object] = None  # IWeightNoise (DropConnect/...)

    def defaults_dict(self):
        return {"activation": self.activation, "weight_init": self.weight_init,
                "dist": self.dist, "bias_init": self.bias_init,
                "updater": self.updater, "l1": self.l1, "l2": self.l2,
                "dropout": self.dropout, "weight_noise": self.weight_noise}

    def to_dict(self):
        from deeplearning4j_tpu.nn.dropout import IDropout
        wn = self.weight_noise
        do = self.dropout
        plain = dataclasses.replace(
            self, weight_noise=None,
            dropout=0.0 if isinstance(do, IDropout) else do)
        d = dataclasses.asdict(plain)
        d["updater"] = self.updater.to_dict()
        if wn is not None:
            d["weight_noise"] = wn.to_dict()
        if isinstance(do, IDropout):
            d["dropout"] = do.to_dict()
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d)
        d["updater"] = Updater.from_dict(d["updater"])
        if d.get("dist") is not None:
            d["dist"] = tuple(d["dist"])
        if d.get("weight_noise") is not None:
            from deeplearning4j_tpu.nn.weightnoise import IWeightNoise
            d["weight_noise"] = IWeightNoise.from_dict(d["weight_noise"])
        if isinstance(d.get("dropout"), dict):
            from deeplearning4j_tpu.nn.dropout import IDropout
            d["dropout"] = IDropout.from_dict(d["dropout"])
        return GlobalConf(**d)


class NeuralNetConfiguration:
    """Builder entry point (parity: NeuralNetConfiguration.builder())."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    def __init__(self):
        self._g = GlobalConf()

    # fluent setters -------------------------------------------------------
    def seed(self, s):
        self._g.seed = int(s); return self

    def activation(self, a):
        self._g.activation = a; return self

    def weight_init(self, w, dist=None):
        self._g.weight_init = w
        if dist is not None:
            self._g.dist = tuple(dist)
        return self

    def dist(self, *d):
        self._g.dist = tuple(d); self._g.weight_init = "distribution"; return self

    def bias_init(self, b):
        self._g.bias_init = float(b); return self

    def updater(self, u: Updater):
        self._g.updater = u; return self

    def learning_rate(self, lr):
        self._g.updater = dataclasses.replace(self._g.updater, learning_rate=lr)
        return self

    def l1(self, v):
        self._g.l1 = float(v); return self

    def l2(self, v):
        self._g.l2 = float(v); return self

    def dropout(self, v):
        """Float drop-probability or an IDropout object
        (Dropout/AlphaDropout/GaussianDropout/GaussianNoise)."""
        from deeplearning4j_tpu.nn.dropout import IDropout
        self._g.dropout = v if isinstance(v, IDropout) else float(v)
        return self

    def optimization_algo(self, a):
        self._g.optimization_algo = str(a).lower(); return self

    def gradient_normalization(self, kind, threshold=1.0):
        self._g.gradient_normalization = kind
        self._g.gradient_normalization_threshold = threshold
        return self

    def dtype(self, dt):
        self._g.dtype = dt; return self

    def compute_dtype(self, dt):
        self._g.compute_dtype = dt; return self

    def remat(self, flag=True):
        from deeplearning4j_tpu.util.remat import check_remat_mode
        self._g.remat = check_remat_mode(flag); return self

    def weight_noise(self, wn):
        """DropConnect / WeightNoise applied to every layer (parity:
        NeuralNetConfiguration.Builder.weightNoise)."""
        self._g.weight_noise = wn; return self

    def mini_batch(self, v):
        self._g.mini_batch = bool(v); return self

    def minimize(self, v=True):
        self._g.minimize = bool(v); return self

    # terminal builders ----------------------------------------------------
    def list(self) -> "ListBuilder":
        return ListBuilder(self._g)

    def graph_builder(self) -> "GraphBuilder":
        from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
        return GraphBuilder(self._g)


class ListBuilder:
    """Parity: NeuralNetConfiguration.ListBuilder → MultiLayerConfiguration."""

    def __init__(self, g: GlobalConf):
        self._g = g
        self._layers: List[Layer] = []
        self._input_type: Optional[InputType] = None
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_bwd = 20

    def layer(self, *args):
        """layer(l) or layer(index, l)"""
        if len(args) == 2:
            idx, l = args
            while len(self._layers) <= idx:
                self._layers.append(None)
            self._layers[idx] = l
        else:
            self._layers.append(args[0])
        return self

    def set_input_type(self, it: InputType):
        self._input_type = it; return self

    def backprop_type(self, t, tbptt_fwd=20, tbptt_bwd=20):
        self._backprop_type = t
        self._tbptt_fwd, self._tbptt_bwd = tbptt_fwd, tbptt_bwd
        return self

    def t_bptt_length(self, n):
        self._backprop_type = "tbptt"
        self._tbptt_fwd = self._tbptt_bwd = n
        return self

    def build(self) -> "MultiLayerConfiguration":
        layers = [copy.deepcopy(l) for l in self._layers if l is not None]
        conf = MultiLayerConfiguration(
            global_conf=copy.deepcopy(self._g), layers=layers,
            input_type=self._input_type, backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd, tbptt_back_length=self._tbptt_bwd)
        conf.finalize()
        return conf


@dataclass
class MultiLayerConfiguration:
    """Sequential net config (parity: MultiLayerConfiguration.java)."""
    global_conf: GlobalConf = dc_field(default_factory=GlobalConf)
    layers: List[Layer] = dc_field(default_factory=list)
    input_type: Optional[InputType] = None
    backprop_type: str = "standard"     # 'standard' | 'tbptt'
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    _finalized: bool = False

    def finalize(self):
        """Apply global defaults + run shape inference through the stack
        (parity: MultiLayerConfiguration.setInputType nIn inference +
        preprocessor insertion — here layers handle layout changes natively)."""
        if self._finalized:
            return self
        defaults = self.global_conf.defaults_dict()
        it = self.input_type
        for l in self.layers:
            l.apply_defaults(defaults)
            l.validate()
            if it is not None:
                l.set_n_in(it)
                it = l.output_type(it)
        self._finalized = True
        return self

    def output_types(self) -> List[InputType]:
        it = self.input_type
        outs = []
        for l in self.layers:
            it = l.output_type(it)
            outs.append(it)
        return outs

    # serde ----------------------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "format": "deeplearning4j_tpu/MultiLayerConfiguration/v1",
            "global_conf": self.global_conf.to_dict(),
            "layers": [l.to_dict() for l in self.layers],
            "input_type": self.input_type.to_dict() if self.input_type else None,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "finalized": self._finalized,
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        d = json.loads(s)
        conf = MultiLayerConfiguration(
            global_conf=GlobalConf.from_dict(d["global_conf"]),
            layers=[layer_from_dict(ld) for ld in d["layers"]],
            input_type=InputType.from_dict(d["input_type"]) if d.get("input_type") else None,
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
        )
        conf._finalized = d.get("finalized", False)
        if not conf._finalized:
            conf.finalize()
        return conf


# re-export for `from ...configuration import ComputationGraphConfiguration`
def __getattr__(name):
    if name == "ComputationGraphConfiguration":
        from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration
        return ComputationGraphConfiguration
    raise AttributeError(name)
