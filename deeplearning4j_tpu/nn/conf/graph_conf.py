"""Computation-graph (DAG) configuration + graph vertices.

Parity surface: reference ComputationGraphConfiguration.java (863 LoC),
GraphBuilder, and the vertex set under nn/conf/graph/ + nn/graph/vertex/impl/
(MergeVertex, ElementWiseVertex, SubsetVertex, StackVertex, UnstackVertex,
ScaleVertex, ShiftVertex, L2NormalizeVertex, ReshapeVertex…).

A vertex is a named node with a list of input names; layers are wrapped in an
implicit LayerVertex. Topological execution order is computed once at build
(parity: ComputationGraph.java:394 topo sort) — inside jit the graph is fully
unrolled, so XLA sees one flat fused program.
"""

from __future__ import annotations

import json
import copy
import dataclasses
from dataclasses import dataclass, field as dc_field
from typing import Optional, List, Dict, Any, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import Layer, layer_from_dict
from deeplearning4j_tpu.nn.conf.configuration import GlobalConf

VERTEX_REGISTRY: Dict[str, type] = {}


def register_vertex(cls):
    VERTEX_REGISTRY[cls.__name__] = cls
    return cls


@dataclass
class GraphVertex:
    """Parameterless function vertex: apply(inputs: list[Array]) -> Array."""

    def apply(self, inputs: List[Any]):
        raise NotImplementedError

    def output_type(self, input_types: List[InputType]) -> InputType:
        return input_types[0]

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["@type"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d)
        cls = VERTEX_REGISTRY[d.pop("@type")]
        kwargs = {}
        fields = {f.name for f in dataclasses.fields(cls)}
        for k, v in d.items():
            if k in fields:
                kwargs[k] = tuple(v) if isinstance(v, list) else v
        return cls(**kwargs)


@register_vertex
@dataclass
class MergeVertex(GraphVertex):
    """Concat along feature axis (parity: nn/conf/graph/MergeVertex)."""

    def apply(self, inputs):
        return jnp.concatenate(inputs, axis=-1)

    def output_type(self, input_types):
        t0 = input_types[0]
        if t0.kind == "cnn":
            ch = sum(t.channels for t in input_types)
            return InputType.convolutional(t0.height, t0.width, ch)
        if t0.kind == "rnn":
            return InputType.recurrent(sum(t.size for t in input_types),
                                       t0.timeseries_length)
        return InputType.feed_forward(sum(t.flat_size() for t in input_types))


@register_vertex
@dataclass
class ElementWiseVertex(GraphVertex):
    """add | subtract | product | average | max
    (parity: nn/conf/graph/ElementWiseVertex)."""
    op: str = "add"

    def apply(self, inputs):
        out = inputs[0]
        if self.op == "add":
            for x in inputs[1:]:
                out = out + x
        elif self.op == "subtract":
            out = inputs[0] - inputs[1]
        elif self.op == "product":
            for x in inputs[1:]:
                out = out * x
        elif self.op == "average":
            out = sum(inputs) / len(inputs)
        elif self.op == "max":
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
        else:
            raise ValueError(self.op)
        return out


@register_vertex
@dataclass
class SubsetVertex(GraphVertex):
    """Feature-range slice [from, to] inclusive (parity: SubsetVertex)."""
    from_idx: int = 0
    to_idx: int = 0

    def apply(self, inputs):
        return inputs[0][..., self.from_idx:self.to_idx + 1]

    def output_type(self, input_types):
        n = self.to_idx - self.from_idx + 1
        t0 = input_types[0]
        if t0.kind == "rnn":
            return InputType.recurrent(n, t0.timeseries_length)
        return InputType.feed_forward(n)


@register_vertex
@dataclass
class StackVertex(GraphVertex):
    """Stack along batch axis (parity: StackVertex)."""

    def apply(self, inputs):
        return jnp.concatenate(inputs, axis=0)


@register_vertex
@dataclass
class UnstackVertex(GraphVertex):
    """Take slice i of n along batch axis (parity: UnstackVertex)."""
    from_idx: int = 0
    stack_size: int = 1

    def apply(self, inputs):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_idx * step:(self.from_idx + 1) * step]


@register_vertex
@dataclass
class ScaleVertex(GraphVertex):
    scale: float = 1.0

    def apply(self, inputs):
        return inputs[0] * self.scale


@register_vertex
@dataclass
class ShiftVertex(GraphVertex):
    shift: float = 0.0

    def apply(self, inputs):
        return inputs[0] + self.shift


@register_vertex
@dataclass
class L2NormalizeVertex(GraphVertex):
    eps: float = 1e-8

    def apply(self, inputs):
        x = inputs[0]
        n = jnp.sqrt((x ** 2).sum(axis=-1, keepdims=True))
        return x / jnp.maximum(n, self.eps)


@register_vertex
@dataclass
class ReshapeVertex(GraphVertex):
    shape: Tuple[int, ...] = ()

    def apply(self, inputs):
        return inputs[0].reshape((inputs[0].shape[0],) + tuple(self.shape))


@register_vertex
@dataclass
class LastTimeStepVertex(GraphVertex):
    """(B, T, C) → (B, C): the last time step of a sequence, mask-aware.

    Parity: nn/conf/graph/rnn/LastTimeStepVertex.java — the encoder half of
    the CG seq2seq pattern (GravesLSTM → LastTimeStepVertex →
    DuplicateToTimeSeriesVertex → decoder). ``mask_input`` names the network
    input whose (B, T) mask locates each example's true last step; without a
    mask the final step is taken."""
    mask_input: Optional[str] = None

    def apply(self, inputs, mask=None):
        x = inputs[0]
        if mask is None:
            return x[:, -1, :]
        # index of the LAST set mask entry (the reference scans for the last
        # nonzero — a sum would mis-index gapped/non-left-aligned masks)
        T = mask.shape[1]
        idx = T - 1 - jnp.argmax(mask[:, ::-1] > 0, axis=1).astype(jnp.int32)
        idx = jnp.where(jnp.any(mask > 0, axis=1), idx, 0)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :]

    def output_type(self, input_types):
        return InputType.feed_forward(input_types[0].size)


@register_vertex
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """(B, C) → (B, T, C): broadcast a vector across time, T taken from the
    reference sequence named by ``ref_input`` (parity:
    nn/conf/graph/rnn/DuplicateToTimeSeriesVertex.java — the decoder-seeding
    half of the CG seq2seq pattern). ``ref_input`` is appended to the
    vertex's graph inputs at add time, so topo order and serde carry it."""
    ref_input: Optional[str] = None

    def apply(self, inputs):
        x, ref = inputs[0], inputs[-1]
        return jnp.broadcast_to(x[:, None, :],
                                (x.shape[0], ref.shape[1], x.shape[1]))

    def output_type(self, input_types):
        t0, tref = input_types[0], input_types[-1]
        return InputType.recurrent(t0.flat_size(),
                                   getattr(tref, "timeseries_length", None))


@register_vertex
@dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two activations → (B, 1)
    (parity: nn/conf/graph/L2Vertex.java)."""
    eps: float = 1e-8

    def apply(self, inputs):
        d = inputs[0] - inputs[1]
        ss = (d * d).sum(axis=tuple(range(1, d.ndim)))
        return jnp.sqrt(jnp.maximum(ss, self.eps))[:, None]

    def output_type(self, input_types):
        return InputType.feed_forward(1)


@register_vertex
@dataclass
class PreprocessorVertex(GraphVertex):
    """Shape-transform vertex (parity: nn/conf/graph/PreprocessorVertex.java
    wrapping the InputPreProcessor impls). Named transforms over this build's
    native layouts (NHWC images, (B, T, C) sequences):

    - ``cnn_to_ff``: (B, H, W, C) → (B, H·W·C)
    - ``ff_to_cnn``: (B, H·W·C) → (B, height, width, channels) [fields]
    - ``rnn_to_ff``: (B, T, C) → (B·T, C)
    - ``ff_to_rnn``: (B·T, C) → (B, tsteps, C) [field]
    """
    preprocessor: str = "cnn_to_ff"
    height: int = 0
    width: int = 0
    channels: int = 0
    tsteps: int = 0

    def apply(self, inputs):
        x = inputs[0]
        if self.preprocessor == "cnn_to_ff":
            return x.reshape(x.shape[0], -1)
        if self.preprocessor == "ff_to_cnn":
            return x.reshape(x.shape[0], self.height, self.width,
                             self.channels)
        if self.preprocessor == "rnn_to_ff":
            return x.reshape(x.shape[0] * x.shape[1], x.shape[2])
        if self.preprocessor == "ff_to_rnn":
            return x.reshape(x.shape[0] // self.tsteps, self.tsteps,
                             x.shape[1])
        raise ValueError(f"Unknown preprocessor '{self.preprocessor}'")

    def output_type(self, input_types):
        t = input_types[0]
        if self.preprocessor == "cnn_to_ff":
            return InputType.feed_forward(t.flat_size())
        if self.preprocessor == "ff_to_cnn":
            return InputType.convolutional(self.height, self.width,
                                           self.channels)
        if self.preprocessor == "rnn_to_ff":
            return InputType.feed_forward(t.size)
        if self.preprocessor == "ff_to_rnn":
            return InputType.recurrent(t.flat_size(), self.tsteps)
        raise ValueError(f"Unknown preprocessor '{self.preprocessor}'")


@register_vertex
@dataclass
class PoolHelperVertex(GraphVertex):
    """Crops first row/col (parity: zoo GoogLeNet's PoolHelperVertex)."""

    def apply(self, inputs):
        return inputs[0][:, 1:, 1:, :]

    def output_type(self, input_types):
        t = input_types[0]
        return InputType.convolutional(t.height - 1, t.width - 1, t.channels)


@dataclass
class _Node:
    name: str
    kind: str                     # 'input' | 'layer' | 'vertex'
    layer: Optional[Layer] = None
    vertex: Optional[GraphVertex] = None
    inputs: List[str] = dc_field(default_factory=list)


@dataclass
class ComputationGraphConfiguration:
    """DAG net config (parity: ComputationGraphConfiguration.java)."""
    global_conf: GlobalConf = dc_field(default_factory=GlobalConf)
    nodes: Dict[str, _Node] = dc_field(default_factory=dict)
    network_inputs: List[str] = dc_field(default_factory=list)
    network_outputs: List[str] = dc_field(default_factory=list)
    input_types: Optional[List[InputType]] = None
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    topological_order: List[str] = dc_field(default_factory=list)

    def topo_sort(self):
        """Kahn's algorithm (parity: ComputationGraph.java:394)."""
        indeg = {n: 0 for n in self.nodes}
        children: Dict[str, List[str]] = {n: [] for n in self.nodes}
        for name, node in self.nodes.items():
            for inp in node.inputs:
                if inp not in self.nodes:
                    raise ValueError(f"Vertex '{name}' references unknown input '{inp}'")
                indeg[name] += 1
                children[inp].append(name)
        queue = [n for n, d in sorted(indeg.items()) if d == 0]
        order = []
        while queue:
            n = queue.pop(0)
            order.append(n)
            for c in children[n]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    queue.append(c)
        if len(order) != len(self.nodes):
            cyc = [n for n, d in indeg.items() if d > 0]
            raise ValueError(f"Graph has a cycle involving {cyc}")
        self.topological_order = order
        return order

    def finalize(self):
        defaults = self.global_conf.defaults_dict()
        self.topo_sort()
        # shape inference through topo order
        types: Dict[str, InputType] = {}
        if self.input_types:
            for n, t in zip(self.network_inputs, self.input_types):
                types[n] = t
        for name in self.topological_order:
            node = self.nodes[name]
            if node.kind == "input":
                continue
            in_types = [types.get(i) for i in node.inputs]
            if node.kind == "layer":
                node.layer.apply_defaults(defaults)
                node.layer.validate()
                if in_types and in_types[0] is not None:
                    node.layer.set_n_in(in_types[0])
                    types[name] = node.layer.output_type(in_types[0])
            else:
                if all(t is not None for t in in_types) and in_types:
                    types[name] = node.vertex.output_type(in_types)
        self.node_output_types = types
        return self

    # serde ----------------------------------------------------------------
    def to_json(self):
        return json.dumps({
            "format": "deeplearning4j_tpu/ComputationGraphConfiguration/v1",
            "global_conf": self.global_conf.to_dict(),
            "nodes": [{
                "name": n.name, "kind": n.kind,
                "layer": n.layer.to_dict() if n.layer else None,
                "vertex": n.vertex.to_dict() if n.vertex else None,
                "inputs": n.inputs,
            } for n in self.nodes.values()],
            "network_inputs": self.network_inputs,
            "network_outputs": self.network_outputs,
            "input_types": [t.to_dict() for t in self.input_types] if self.input_types else None,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
        }, indent=2)

    @staticmethod
    def from_json(s):
        d = json.loads(s)
        conf = ComputationGraphConfiguration(
            global_conf=GlobalConf.from_dict(d["global_conf"]),
            network_inputs=d["network_inputs"],
            network_outputs=d["network_outputs"],
            input_types=[InputType.from_dict(t) for t in d["input_types"]]
            if d.get("input_types") else None,
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
        )
        for nd in d["nodes"]:
            conf.nodes[nd["name"]] = _Node(
                name=nd["name"], kind=nd["kind"],
                layer=layer_from_dict(nd["layer"]) if nd.get("layer") else None,
                vertex=GraphVertex.from_dict(nd["vertex"]) if nd.get("vertex") else None,
                inputs=nd.get("inputs", []))
        conf.finalize()
        return conf


class GraphBuilder:
    """Parity: ComputationGraphConfiguration.GraphBuilder."""

    def __init__(self, g: GlobalConf):
        self._conf = ComputationGraphConfiguration(global_conf=copy.deepcopy(g))

    def add_inputs(self, *names):
        for n in names:
            self._conf.network_inputs.append(n)
            self._conf.nodes[n] = _Node(name=n, kind="input")
        return self

    def set_input_types(self, *types):
        self._conf.input_types = list(types)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str):
        layer = copy.deepcopy(layer)
        layer.name = name
        self._conf.nodes[name] = _Node(name=name, kind="layer", layer=layer,
                                       inputs=list(inputs))
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str):
        inputs = list(inputs)
        ref = getattr(vertex, "ref_input", None)
        if ref and ref not in inputs:
            # DuplicateToTimeSeriesVertex's reference sequence is a real data
            # dependency: wire it so topo sort orders it and apply() sees it
            inputs.append(ref)
        self._conf.nodes[name] = _Node(name=name, kind="vertex", vertex=vertex,
                                       inputs=inputs)
        return self

    def set_outputs(self, *names):
        self._conf.network_outputs = list(names)
        return self

    def backprop_type(self, t, tbptt_fwd=20, tbptt_bwd=20):
        self._conf.backprop_type = t
        self._conf.tbptt_fwd_length = tbptt_fwd
        self._conf.tbptt_back_length = tbptt_bwd
        return self

    def build(self):
        return self._conf.finalize()
