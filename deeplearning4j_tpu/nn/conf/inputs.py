"""Input types and shape inference.

Parity surface: reference ``InputType`` system
(deeplearning4j-nn/.../nn/conf/inputs/InputType.java) — feed-forward,
recurrent, convolutional, convolutional-flat — used by
``MultiLayerConfiguration.setInputType`` to infer nIn per layer and insert
preprocessors automatically.

TPU note: internal convolutional layout is NHWC (channels-last), the layout
the TPU vector units and XLA conv tiling prefer; the reference's NCHW
(cuDNN-preferred) exists only at the import boundary.
"""

from __future__ import annotations

from dataclasses import dataclass, asdict


@dataclass(frozen=True)
class InputType:
    kind: str  # 'ff' | 'rnn' | 'cnn' | 'cnn_flat' | 'cnn3d'
    size: int = 0          # ff: feature count
    timeseries_length: int = -1  # rnn: -1 = variable
    height: int = 0
    width: int = 0
    channels: int = 0
    depth: int = 0         # cnn3d

    # ---- factory methods (parity with InputType.feedForward etc.) ----
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="ff", size=size)

    @staticmethod
    def recurrent(size: int, timeseries_length: int = -1) -> "InputType":
        return InputType(kind="rnn", size=size, timeseries_length=timeseries_length)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn", height=height, width=width, channels=channels)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn_flat", height=height, width=width, channels=channels)

    @staticmethod
    def convolutional3d(depth: int, height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn3d", depth=depth, height=height, width=width,
                         channels=channels)

    # ---- helpers ----
    def flat_size(self) -> int:
        if self.kind == "ff":
            return self.size
        if self.kind == "rnn":
            return self.size
        if self.kind in ("cnn", "cnn_flat"):
            return self.height * self.width * self.channels
        if self.kind == "cnn3d":
            return self.depth * self.height * self.width * self.channels
        raise ValueError(self.kind)

    def batch_shape(self, batch: int = 1):
        """Concrete array shape for one minibatch (NHWC for cnn, (B,T,C) for rnn)."""
        if self.kind == "ff" or self.kind == "cnn_flat":
            return (batch, self.flat_size())
        if self.kind == "rnn":
            t = self.timeseries_length if self.timeseries_length > 0 else 8
            return (batch, t, self.size)
        if self.kind == "cnn":
            return (batch, self.height, self.width, self.channels)
        if self.kind == "cnn3d":
            return (batch, self.depth, self.height, self.width, self.channels)
        raise ValueError(self.kind)

    def to_dict(self):
        return asdict(self)

    @staticmethod
    def from_dict(d):
        return InputType(**d)


def conv_output_size(size, kernel, stride, pad, dilation=1, mode="truncate"):
    """Spatial output size of a conv/pool op. mode: 'same'|'truncate'|'strict'
    (reference ConvolutionMode, nn/conf/ConvolutionMode.java)."""
    if mode == "same":
        return -(-size // stride)  # ceil
    eff_k = kernel + (kernel - 1) * (dilation - 1)
    return (size + 2 * pad - eff_k) // stride + 1
