"""Activation functions.

Parity surface: the reference's ``IActivation`` implementations consumed by
every layer (reference nd4j Activation enum; selected per-layer via
NeuralNetConfiguration.Builder.activation, NeuralNetConfiguration.java:570).
Here an activation is just a name → pure jnp function; gradients come from
autodiff rather than hand-written ``backprop`` methods.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_E = 2.718281828459045


def _softmax(x):
    return jax.nn.softmax(x, axis=-1)


def _cube(x):
    return x ** 3


def _hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def _hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def _rationaltanh(x):
    # 1.7159 * tanh(2x/3) approximation used by the reference's RationalTanh
    a = x * (2.0 / 3.0)
    return 1.7159 * jnp.tanh(a)


def _rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


ACTIVATIONS = {
    "identity": lambda x: x,
    "linear": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "leakyrelu": lambda x: jax.nn.leaky_relu(x, 0.01),
    "prelu": lambda x: jax.nn.leaky_relu(x, 0.01),  # alpha handled by PReLU layer when learned
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "tanh": jnp.tanh,
    "rationaltanh": _rationaltanh,
    "rectifiedtanh": _rectifiedtanh,
    "sigmoid": jax.nn.sigmoid,
    "hardsigmoid": _hardsigmoid,
    "hardtanh": _hardtanh,
    "softmax": _softmax,
    "logsoftmax": lambda x: jax.nn.log_softmax(x, axis=-1),
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "cube": _cube,
    "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
    "thresholdedrelu": lambda x: jnp.where(x > 1.0, x, 0.0),
}


def get_activation(name):
    """Resolve an activation by name (case-insensitive) or pass a callable through."""
    if callable(name):
        return name
    key = str(name).lower().replace("_", "")
    if key not in ACTIVATIONS:
        raise ValueError(
            f"Unknown activation '{name}'. Available: {sorted(ACTIVATIONS)}"
        )
    return ACTIVATIONS[key]
