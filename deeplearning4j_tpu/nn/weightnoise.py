"""Weight noise — DropConnect and additive/multiplicative gaussian noise.

Parity surface: reference nn/conf/weightnoise/ — IWeightNoise.java
(getParameter applied to each param at forward time during training),
DropConnect.java (Bernoulli weight retention) and WeightNoise.java
(distribution noise, additive or multiplicative). Applied functionally in
the containers' forward pass: the noised parameters exist only inside the
traced step (no mutation), and gradients flow through the noise exactly as
the reference's backprop does through its masked weights.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

_NOISE_REGISTRY = {}


def _register(cls):
    _NOISE_REGISTRY[cls.__name__] = cls
    return cls


@dataclass
class IWeightNoise:
    """Base: apply(params, rng) -> noised params (bias keys 'b'/'bo'/...
    are skipped unless apply_to_bias)."""
    apply_to_bias: bool = False

    def _noise_one(self, value, rng):
        raise NotImplementedError

    def apply(self, params: dict, rng):
        out = {}
        for i, (k, v) in enumerate(sorted(params.items())):
            sub_rng = jax.random.fold_in(rng, i)
            if isinstance(v, dict):        # wrappers (Bidirectional: fwd/bwd)
                out[k] = self.apply(v, sub_rng)
            elif not hasattr(v, "ndim"):
                out[k] = v
            elif not self.apply_to_bias and k.startswith("b"):
                out[k] = v
            else:
                out[k] = self._noise_one(v, sub_rng)
        return out

    # ---- serde ----------------------------------------------------------
    def to_dict(self):
        import dataclasses as dc
        return {"@noise": type(self).__name__, **dc.asdict(self)}

    @staticmethod
    def from_dict(d):
        d = dict(d)
        cls = _NOISE_REGISTRY[d.pop("@noise")]
        return cls(**d)


@_register
@dataclass
class DropConnect(IWeightNoise):
    """Bernoulli mask on weights (parity: DropConnect.java,
    weightRetainProb). Inverted scaling keeps the expected activation equal
    to the noiseless forward."""
    weight_retain_prob: float = 0.5

    def _noise_one(self, v, rng):
        keep = jax.random.bernoulli(rng, self.weight_retain_prob, v.shape)
        return jnp.where(keep, v / self.weight_retain_prob,
                         jnp.zeros_like(v))


@_register
@dataclass
class WeightNoise(IWeightNoise):
    """Gaussian noise on weights (parity: WeightNoise.java with a
    NormalDistribution): additive ``w + n`` or multiplicative ``w * n``
    with n ~ N(mean, stddev)."""
    mean: float = 0.0
    stddev: float = 0.1
    additive: bool = True

    def _noise_one(self, v, rng):
        n = self.mean + self.stddev * jax.random.normal(rng, v.shape, v.dtype)
        return v + n if self.additive else v * n
