"""Updaters (optimizer update rules) and learning-rate schedules.

Parity surface: the reference's ``IUpdater`` set applied per updater-block
(nn/conf/Updater.java:12 — SGD, ADAM, ADAMAX, ADADELTA, NESTEROVS, NADAM,
ADAGRAD, RMSPROP, NONE) plus ``LearningRatePolicy`` schedules
(nn/conf/LearningRatePolicy.java: Exponential/Inverse/Poly/Sigmoid/Step/
Schedule). Here each updater is a small dataclass that lowers to an optax
``GradientTransformation``; updater state is an immutable pytree carried
through the jit'd train step (replaces the flat mutable updater-state array of
BaseMultiLayerUpdater.java:38).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Dict, Any

import optax


# ---------------------------------------------------------------- schedules

@dataclass(frozen=True)
class Schedule:
    """Learning-rate schedule. kind: constant|exponential|inverse|poly|sigmoid|
    step|map. Iteration-indexed, like the reference's LearningRatePolicy."""
    kind: str = "constant"
    initial: float = 1e-3
    decay_rate: float = 0.99
    power: float = 1.0
    steps: float = 1000.0
    gamma: float = 0.99
    max_iter: float = 10000.0
    values: Optional[Dict[int, float]] = None  # for 'map'

    def to_optax(self):
        k = self.kind
        if k == "constant":
            return self.initial
        if k == "exponential":
            # lr = initial * decay_rate^iter
            return lambda it: self.initial * (self.decay_rate ** it)
        if k == "inverse":
            return lambda it: self.initial / ((1.0 + self.gamma * it) ** self.power)
        if k == "poly":
            return lambda it: self.initial * (
                (1.0 - (it / self.max_iter).clip(0.0, 1.0) if hasattr(it, "clip")
                 else max(0.0, min(1.0, 1.0 - it / self.max_iter))) ** self.power)
        if k == "sigmoid":
            import jax.numpy as jnp
            return lambda it: self.initial / (1.0 + jnp.exp(-self.gamma * (it - self.steps)))
        if k == "step":
            return lambda it: self.initial * (self.decay_rate ** (it // self.steps))
        if k == "map":
            boundaries = sorted((self.values or {}).items())
            import jax.numpy as jnp

            def sched(it):
                lr = self.initial
                for b, v in boundaries:
                    lr = jnp.where(it >= b, v, lr)
                return lr
            return sched
        raise ValueError(f"Unknown schedule kind {k}")

    def to_dict(self):
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d):
        d = dict(d)
        if d.get("values") is not None:
            d["values"] = {int(k): v for k, v in d["values"].items()}
        return Schedule(**d)


def _lr(self):
    if self.schedule is not None:
        return self.schedule.to_optax()
    return self.learning_rate


# ---------------------------------------------------------------- updaters

@dataclass(frozen=True)
class Updater:
    """Base updater config; subclasses lower to optax."""
    learning_rate: float = 1e-3
    schedule: Optional[Schedule] = None

    def to_optax(self) -> optax.GradientTransformation:
        raise NotImplementedError

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["@type"] = type(self).__name__
        if self.schedule is not None:
            d["schedule"] = self.schedule.to_dict()
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d)
        cls = UPDATERS[d.pop("@type")]
        if d.get("schedule") is not None:
            d["schedule"] = Schedule.from_dict(d["schedule"])
        return cls(**d)


@dataclass(frozen=True)
class Sgd(Updater):
    def to_optax(self):
        return optax.sgd(_lr(self))


@dataclass(frozen=True)
class Nesterovs(Updater):
    learning_rate: float = 0.1
    momentum: float = 0.9

    def to_optax(self):
        return optax.sgd(_lr(self), momentum=self.momentum, nesterov=True)


@dataclass(frozen=True)
class Adam(Updater):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adam(_lr(self), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclass(frozen=True)
class AdaMax(Updater):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adamax(_lr(self), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclass(frozen=True)
class NAdam(Updater):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.nadam(_lr(self), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclass(frozen=True)
class AdaGrad(Updater):
    learning_rate: float = 0.1
    epsilon: float = 1e-6

    def to_optax(self):
        return optax.adagrad(_lr(self), eps=self.epsilon)


@dataclass(frozen=True)
class AdaDelta(Updater):
    rho: float = 0.95
    epsilon: float = 1e-6

    def to_optax(self):
        # reference AdaDelta has no lr (lr = 1)
        return optax.adadelta(learning_rate=1.0, rho=self.rho, eps=self.epsilon)


@dataclass(frozen=True)
class RmsProp(Updater):
    learning_rate: float = 0.1
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.rmsprop(_lr(self), decay=self.rms_decay, eps=self.epsilon)


@dataclass(frozen=True)
class AmsGrad(Updater):
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.amsgrad(_lr(self), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@dataclass(frozen=True)
class NoOp(Updater):
    """Updater NONE: the raw gradient is applied unmodified (params -= grad),
    matching the reference's NoOp pass-through semantics."""

    def to_optax(self):
        return optax.sgd(1.0)


UPDATERS = {c.__name__: c for c in
            [Sgd, Nesterovs, Adam, AdaMax, NAdam, AdaGrad, AdaDelta, RmsProp,
             AmsGrad, NoOp]}


def make_gradient_transform(updater: Updater,
                            grad_norm_threshold: Optional[float] = None,
                            grad_clip_value: Optional[float] = None,
                            l2: float = 0.0) -> optax.GradientTransformation:
    """Compose clipping / weight decay / updater, matching the reference's
    order of operations (BaseOptimizer.updateGradientAccordingToParams:
    L2 added to gradient, then clipping, then updater)."""
    chain = []
    if l2 and l2 > 0:
        chain.append(optax.add_decayed_weights(l2))
    if grad_clip_value:
        chain.append(optax.clip(grad_clip_value))
    if grad_norm_threshold:
        chain.append(optax.clip_by_global_norm(grad_norm_threshold))
    chain.append(updater.to_optax())
    return optax.chain(*chain) if len(chain) > 1 else chain[0]


def normalize_layer_grad(g, kind: Optional[str], thr: float):
    """Gradient normalization for ONE layer's gradient pytree (parity:
    GradientNormalization, nn/conf/GradientNormalization.java, applied per
    layer in BaseLayer.update). Shared by MultiLayerNetwork and
    ComputationGraph containers."""
    import jax
    import jax.numpy as jnp
    if not g or not kind or kind == "None":
        return g
    leaves = jax.tree_util.tree_leaves(g)
    if kind == "ClipElementWiseAbsoluteValue":
        return jax.tree_util.tree_map(lambda a: jnp.clip(a, -thr, thr), g)
    if kind in ("ClipL2PerLayer", "RenormalizeL2PerLayer"):
        norm = jnp.sqrt(sum((a ** 2).sum() for a in leaves))
        if kind == "ClipL2PerLayer":
            scale = jnp.minimum(1.0, thr / jnp.maximum(norm, 1e-12))
        else:
            scale = 1.0 / jnp.maximum(norm, 1e-12)
        return jax.tree_util.tree_map(lambda a: a * scale, g)
    if kind in ("ClipL2PerParamType", "RenormalizeL2PerParamType"):
        def per_param(a):
            n = jnp.sqrt((a ** 2).sum())
            if kind == "ClipL2PerParamType":
                s = jnp.minimum(1.0, thr / jnp.maximum(n, 1e-12))
            else:
                s = 1.0 / jnp.maximum(n, 1e-12)
            return a * s
        return jax.tree_util.tree_map(per_param, g)
    return g
