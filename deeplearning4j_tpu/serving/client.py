"""Client for the InferenceServer (JSON + Base64 f32, knn_server style)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import numpy as np

from deeplearning4j_tpu.clustering.knn_server import (
    ndarray_from_b64, ndarray_to_b64)


class InferenceClient:
    def __init__(self, url: str, timeout: float = 30.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _request(self, path, payload=None):
        if payload is None:
            req = urllib.request.Request(self.url + path)
        else:
            req = urllib.request.Request(
                self.url + path, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                out = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                out = json.loads(e.read().decode())
            except Exception:
                raise RuntimeError(f"HTTP {e.code}") from e
        if isinstance(out, dict) and "error" in out:
            raise RuntimeError(out["error"])
        return out

    def predict(self, x) -> np.ndarray:
        """POST one request batch; a 1-D vector is treated as batch of 1
        and the batch dim stripped from the reply (server mirrors this)."""
        out = self._request(
            "/predict", {"ndarray": ndarray_to_b64(np.asarray(x))})
        return ndarray_from_b64(out["ndarray"])

    def warmup(self, input_shape, max_batch=None) -> dict:
        """Pre-compile the server's bucket ladder for ``input_shape`` (a
        per-example feature shape, or list of shapes for graphs)."""
        payload = {"input_shape": list(input_shape)}
        if max_batch is not None:
            payload["max_batch"] = int(max_batch)
        return self._request("/warmup", payload)

    def stats(self) -> dict:
        return self._request("/stats")
