"""Client for the InferenceServer (JSON + Base64 f32, knn_server style).

Transport: one persistent ``http.client.HTTPConnection`` per thread
(keep-alive — the server speaks HTTP/1.1 with exact Content-Length on every
response, so the socket survives across calls and each request skips TCP
connect + slow-start). A dropped socket (server restarted, idle timeout,
half-closed keep-alive) reconnects ONCE within the call before the shared
retry policy even sees an error. ``keep_alive=False`` restores
one-connection-per-call for debugging or aggressive LB rotation.

Error mapping mirrors the server's status codes (docs/FAULT_TOLERANCE.md):
429 → ServerOverloadedError (retryable — the shared retry primitive backs
off and tries again), 503 → BatcherStoppedError (draining; not retryable
against this instance), 504 → DeadlineExceededError (the request's own
budget is spent; retrying would deliver a late answer), 400 → ValueError
(the payload is wrong; identical on every attempt), 500 → RuntimeError.
Connection failures retry under the same policy.
"""

from __future__ import annotations

import http.client
import json
import threading
from typing import Optional
from urllib.parse import urlparse

import numpy as np

from deeplearning4j_tpu.clustering.knn_server import (
    ndarray_from_b64, ndarray_to_b64)
from deeplearning4j_tpu.resilience.errors import (
    BatcherStoppedError, DeadlineExceededError, ServerOverloadedError)
from deeplearning4j_tpu.resilience.retry import RetryPolicy, retry_call


def _error_message(code: int, body: bytes) -> str:
    """Best-effort extraction of the structured error body
    ({"error": {"type", "message"}} — or the legacy plain string)."""
    try:
        out = json.loads(body.decode())
        err = out.get("error")
        if isinstance(err, dict):
            return str(err.get("message", err))
        if err is not None:
            return str(err)
    except Exception:   # noqa: BLE001 — body unreadable; code still speaks
        pass
    return f"HTTP {code}"


def _typed_http_error(code: int, body: bytes) -> Exception:
    msg = _error_message(code, body)
    if code == 429:
        return ServerOverloadedError(msg)
    if code == 503:
        return BatcherStoppedError(msg)
    if code == 504:
        return DeadlineExceededError(msg)
    if 400 <= code < 500:
        return ValueError(msg)
    return RuntimeError(msg)


# socket-level failures that mean "the connection died", not "the server
# answered an error" — eligible for the in-call single reconnect.
# IncompleteRead covers a connection dropped MID-RESPONSE (headers arrived,
# the body didn't — a replica SIGKILLed between write() calls): without it
# only pre-send drops reconnected, and a /generate whose socket died after
# headers surfaced a raw http.client error instead of retrying.
_CONN_ERRORS = (http.client.RemoteDisconnected,   # ConnectionResetError kin
                http.client.CannotSendRequest,    # stale half-closed socket
                http.client.BadStatusLine,
                http.client.IncompleteRead,       # died after headers
                ConnectionError, BrokenPipeError, OSError)


class InferenceClient:
    def __init__(self, url: str, timeout: float = 30.0, retries: int = 3,
                 keep_alive: bool = True):
        self.url = url.rstrip("/")
        parsed = urlparse(self.url)
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or (443 if parsed.scheme == "https" else 80)
        self.timeout = timeout
        self.keep_alive = keep_alive
        self.retry_policy = RetryPolicy(max_attempts=max(1, retries),
                                        base_delay=0.05, max_delay=1.0)
        # one persistent connection PER THREAD — http.client connections are
        # not thread-safe, and this client is shared across worker threads
        self._local = threading.local()

    # ------------------------------------------------------------ transport
    def _conn(self) -> http.client.HTTPConnection:
        c = getattr(self._local, "conn", None)
        if c is None:
            c = http.client.HTTPConnection(self.host, self.port,
                                           timeout=self.timeout)
            self._local.conn = c
        return c

    def close(self) -> None:
        """Drop this thread's persistent connection (safe to call anytime;
        the next request transparently reconnects)."""
        c = getattr(self._local, "conn", None)
        if c is not None:
            try:
                c.close()
            except Exception:   # noqa: BLE001 — already-dead socket
                pass
            self._local.conn = None

    def _roundtrip(self, path, body, headers, reconnect=True, give_up=None):
        method = "GET" if body is None else "POST"
        # attempt 0 may find a keep-alive socket the server already closed
        # (restart, idle reap); reconnect once and retry within this call —
        # a second failure is a real connection problem for the retry policy.
        # The reconnect covers drops BEFORE the send and MID-RESPONSE alike
        # (IncompleteRead in _CONN_ERRORS). ``reconnect=False`` makes it one
        # attempt only; ``give_up()`` (polled before the re-dial) lets a
        # caller that closed our socket on purpose — a hedging router
        # cancelling the losing attempt — abort instead of re-sending.
        attempts = (0, 1) if reconnect else (1,)
        for attempt in attempts:
            conn = self._conn()
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                return resp.status, resp.read(), dict(resp.getheaders())
            except TimeoutError:
                self.close()
                raise
            except _CONN_ERRORS:
                self.close()
                if attempt:
                    raise
                if give_up is not None and give_up():
                    raise

    def post_raw(self, path, body: bytes, headers=None, reconnect=True,
                 give_up=None):
        """Forward pre-encoded bytes and return ``(status, body, headers)``
        WITHOUT raising on HTTP error statuses — the router's upstream
        primitive: it owns failover/hedging, so it needs the status code as
        data, the response headers (``x-request-id``), and the original
        payload passed through byte-for-byte."""
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        try:
            return self._roundtrip(path, body, hdrs, reconnect=reconnect,
                                   give_up=give_up)
        finally:
            if not self.keep_alive:
                self.close()

    def _once(self, path, payload):
        body = None if payload is None else json.dumps(payload).encode()
        headers = {} if body is None else {
            "Content-Type": "application/json"}
        try:
            status, data, _ = self._roundtrip(path, body, headers)
        finally:
            if not self.keep_alive:
                self.close()
        if status >= 400:
            raise _typed_http_error(status, data)
        out = json.loads(data.decode())
        if isinstance(out, dict) and "error" in out:
            err = out["error"]
            raise RuntimeError(err.get("message", str(err))
                               if isinstance(err, dict) else err)
        return out

    def _request(self, path, payload=None):
        # overload (429) and connection failures retry with backoff; 4xx
        # payload errors and expired deadlines surface immediately
        return retry_call(self._once, path, payload,
                          policy=self.retry_policy,
                          component="serving_client")

    # ------------------------------------------------------------------ API
    def predict(self, x, deadline_ms: Optional[float] = None) -> np.ndarray:
        """POST one request batch; a 1-D vector is treated as batch of 1
        and the batch dim stripped from the reply (server mirrors this).

        ``deadline_ms``: per-request budget, enforced server-side through
        the micro-batcher — an expired request is answered 504 fast instead
        of riding a device call."""
        payload = {"ndarray": ndarray_to_b64(np.asarray(x))}
        if deadline_ms is not None:
            payload["deadline_ms"] = float(deadline_ms)
        out = self._request("/predict", payload)
        return ndarray_from_b64(out["ndarray"])

    def generate(self, tokens, max_new_tokens: int = 32, seed: int = 0,
                 temperature: float = 0.0, top_k: int = 0) -> dict:
        """POST /generate — autoregressive decoding through the server's
        DecodeEngine. ``tokens``: prompt token ids. Returns
        {"tokens": [generated ids], "prompt_len": int}."""
        return self._request("/generate", {
            "tokens": [int(t) for t in tokens],
            "max_new_tokens": int(max_new_tokens),
            "seed": int(seed),
            "temperature": float(temperature),
            "top_k": int(top_k)})

    def kv_export(self, tokens) -> dict:
        """POST /kv/export — serialize the replica's cached KV block
        chain for this prompt into a migration payload (see
        serving/kv/migrate.py). Feed the result to another replica's
        ``kv_import`` to hand a finished prefill across the fleet."""
        return self._request("/kv/export",
                             {"tokens": [int(t) for t in tokens]})

    def kv_import(self, payload: dict) -> dict:
        """POST /kv/import — restore a ``kv_export`` payload into this
        replica's pool. An envelope/integrity mismatch raises (HTTP 409)
        with the destination pool untouched."""
        return self._request("/kv/import", dict(payload))

    def warmup(self, input_shape, max_batch=None) -> dict:
        """Pre-compile the server's bucket ladder for ``input_shape`` (a
        per-example feature shape, or list of shapes for graphs)."""
        payload = {"input_shape": list(input_shape)}
        if max_batch is not None:
            payload["max_batch"] = int(max_batch)
        return self._request("/warmup", payload)

    def health(self) -> dict:
        """GET /healthz — {"status": "ok" | "degraded" | "draining"}.
        A draining server answers 503 (load balancers pull it from
        rotation); that still reads as a status here, not an error."""
        try:
            return self._once("/healthz", None)
        except BatcherStoppedError:
            return {"status": "draining"}

    def stats(self) -> dict:
        return self._request("/stats")
