"""Client for the InferenceServer (JSON + Base64 f32, knn_server style).

Error mapping mirrors the server's status codes (docs/FAULT_TOLERANCE.md):
429 → ServerOverloadedError (retryable — the shared retry primitive backs
off and tries again), 503 → BatcherStoppedError (draining; not retryable
against this instance), 504 → DeadlineExceededError (the request's own
budget is spent; retrying would deliver a late answer), 400 → ValueError
(the payload is wrong; identical on every attempt), 500 → RuntimeError.
Connection failures retry under the same policy.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional

import numpy as np

from deeplearning4j_tpu.clustering.knn_server import (
    ndarray_from_b64, ndarray_to_b64)
from deeplearning4j_tpu.resilience.errors import (
    BatcherStoppedError, DeadlineExceededError, ServerOverloadedError)
from deeplearning4j_tpu.resilience.retry import RetryPolicy, retry_call


def _error_message(e: urllib.error.HTTPError) -> str:
    """Best-effort extraction of the structured error body
    ({"error": {"type", "message"}} — or the legacy plain string)."""
    try:
        out = json.loads(e.read().decode())
        err = out.get("error")
        if isinstance(err, dict):
            return str(err.get("message", err))
        if err is not None:
            return str(err)
    except Exception:   # noqa: BLE001 — body unreadable; code still speaks
        pass
    return f"HTTP {e.code}"


def _typed_http_error(e: urllib.error.HTTPError) -> Exception:
    msg = _error_message(e)
    if e.code == 429:
        return ServerOverloadedError(msg)
    if e.code == 503:
        return BatcherStoppedError(msg)
    if e.code == 504:
        return DeadlineExceededError(msg)
    if 400 <= e.code < 500:
        return ValueError(msg)
    return RuntimeError(msg)


class InferenceClient:
    def __init__(self, url: str, timeout: float = 30.0, retries: int = 3):
        self.url = url.rstrip("/")
        self.timeout = timeout
        self.retry_policy = RetryPolicy(max_attempts=max(1, retries),
                                        base_delay=0.05, max_delay=1.0)

    def _once(self, path, payload):
        if payload is None:
            req = urllib.request.Request(self.url + path)
        else:
            req = urllib.request.Request(
                self.url + path, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                out = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            raise _typed_http_error(e) from e
        if isinstance(out, dict) and "error" in out:
            err = out["error"]
            raise RuntimeError(err.get("message", str(err))
                               if isinstance(err, dict) else err)
        return out

    def _request(self, path, payload=None):
        # overload (429) and connection failures retry with backoff; 4xx
        # payload errors and expired deadlines surface immediately
        return retry_call(self._once, path, payload,
                          policy=self.retry_policy,
                          component="serving_client")

    def predict(self, x, deadline_ms: Optional[float] = None) -> np.ndarray:
        """POST one request batch; a 1-D vector is treated as batch of 1
        and the batch dim stripped from the reply (server mirrors this).

        ``deadline_ms``: per-request budget, enforced server-side through
        the micro-batcher — an expired request is answered 504 fast instead
        of riding a device call."""
        payload = {"ndarray": ndarray_to_b64(np.asarray(x))}
        if deadline_ms is not None:
            payload["deadline_ms"] = float(deadline_ms)
        out = self._request("/predict", payload)
        return ndarray_from_b64(out["ndarray"])

    def warmup(self, input_shape, max_batch=None) -> dict:
        """Pre-compile the server's bucket ladder for ``input_shape`` (a
        per-example feature shape, or list of shapes for graphs)."""
        payload = {"input_shape": list(input_shape)}
        if max_batch is not None:
            payload["max_batch"] = int(max_batch)
        return self._request("/warmup", payload)

    def health(self) -> dict:
        """GET /healthz — {"status": "ok" | "degraded" | "draining"}.
        A draining server answers 503 (load balancers pull it from
        rotation); that still reads as a status here, not an error."""
        try:
            return self._once("/healthz", None)
        except BatcherStoppedError:
            return {"status": "draining"}

    def stats(self) -> dict:
        return self._request("/stats")
