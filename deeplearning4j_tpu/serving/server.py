"""HTTP inference endpoint over the micro-batched engine.

Same stdlib ThreadingHTTPServer + JSON/Base64-f32 transport as
clustering/knn_server.py (the reference's NearestNeighborsServer analog);
each POST /predict rides the micro-batcher, so concurrent HTTP clients are
coalesced into shared device calls. Wire format in docs/SERVING.md.

Endpoints:
  POST /predict  {"ndarray": {shape, data}, "deadline_ms"?} → {"ndarray": ...}
  POST /warmup   {"input_shape": [...], "max_batch"}        → {"buckets": [...]}
  POST /admin/swap {"checkpoint": path, "version"?}         → {"version": n}
  POST /admin/profile {"dir": d, "seconds"?}                → timed jax.profiler capture
  GET  /stats                                               → engine+batcher stats
  GET  /metrics                                             → Prometheus text
  GET  /healthz                                             → {"status": ...}
  GET  /trace                                               → span ring buffer (Chrome JSON)
  GET  /programs                                            → compiled-program cost table

/predict and /generate responses carry ``x-model-version`` (the serving
weights' hot-swap version, docs/ONLINE_LEARNING.md); 409 with type
``weight_mismatch`` rejects an incompatible /admin/swap candidate before
the live engines are touched.

Error contract (docs/FAULT_TOLERANCE.md): every error body is structured —
``{"error": {"type": ..., "message": ...}}`` — and the status code
classifies it: **400** malformed payload (bad JSON, missing ``ndarray``,
wrong rank/feature width), **429** queue full (shed immediately, the
handler thread never blocks on a full queue), **503** draining/stopped,
**504** request deadline expired (answered without riding a device call),
**500** engine faults only. ``/healthz`` reports ``ok`` | ``degraded``
(queue ≥ 80% full or a recent engine fault) | ``draining`` (status 503, so
load balancers pull the instance while in-flight work flushes).
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from deeplearning4j_tpu.clustering.knn_server import (
    ndarray_from_b64, ndarray_to_b64)
from deeplearning4j_tpu.monitor import get_registry, trace
from deeplearning4j_tpu.monitor import profiling, tracing
from deeplearning4j_tpu.monitor.slo import BurnRateSLO
from deeplearning4j_tpu.resilience.errors import (
    BatcherStoppedError, CorruptCheckpointError, DeadlineExceededError,
    InjectedFaultError, ServerOverloadedError, WeightSwapError)
from deeplearning4j_tpu.serving.batcher import MicroBatcher
from deeplearning4j_tpu.serving.engine import InferenceEngine
from deeplearning4j_tpu.serving.kv import KVMigrateError

_KNOWN_PATHS = ("/predict", "/generate", "/warmup", "/stats", "/metrics",
                "/healthz", "/chaos", "/admin/swap", "/trace", "/programs",
                "/admin/profile", "/train/diagnostics", "/kv/export",
                "/kv/import", "/requests")


def _http_metrics():
    reg = get_registry()
    return (reg.counter("dl4jtpu_http_requests_total",
                        "HTTP requests served by the inference server.",
                        ("path",)),
            reg.histogram("dl4jtpu_http_request_seconds",
                          "Wall seconds per HTTP request, handler-inclusive.",
                          ("path",)))


class BadRequestError(ValueError):
    """Client-side payload problem → HTTP 400 (never 500)."""


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1 enables keep-alive: clients reuse one TCP connection across
    # requests instead of paying connect + slow-start per call. Safe here
    # because every response path (_json/_error/_text) sets an exact
    # Content-Length, which 1.1 persistence requires.
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    @property
    def _rid(self):
        # router-assigned x-request-id: echoed on every response and into
        # error bodies + trace spans, so one grep follows a request across
        # the router, both halves of a hedged pair, and the replica.
        # Direct-to-replica requests with no id get one MINTED here, so
        # they're never anonymous in the journal or the traces; the mint
        # is cached against this request's header object (fresh per
        # request even on a keep-alive connection), so every response
        # header and journal record of one request agrees.
        rid = self.headers.get("x-request-id")
        if rid:
            return rid
        minted = getattr(self, "_rid_minted", None)
        if minted is None or minted[0] is not self.headers:
            minted = (self.headers, self.server.inference.mint_rid())
            self._rid_minted = minted
        return minted[1]

    def _json(self, obj, code=200, extra_headers=None):
        data = json.dumps(obj).encode()
        self._status = code
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if self._rid:
            self.send_header("x-request-id", self._rid)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _error(self, code: int, err_type: str, message: str):
        err = {"type": err_type, "message": message}
        if self._rid:
            err["request_id"] = self._rid
        self._json({"error": err}, code)

    def _text(self, body: str, content_type: str, code=200):
        data = body.encode()
        self._status = code
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        if self._rid:
            self.send_header("x-request-id", self._rid)
        self.end_headers()
        self.wfile.write(data)

    def _observed(self, path, fn):
        # per-path request count + latency; unknown paths share one series
        # so a URL-probing client can't mint unbounded label values
        counter, hist = _http_metrics()
        label = path if path in _KNOWN_PATHS else "other"
        # router-minted trace context: installed thread-local for the whole
        # handler, so this request's spans (http_request and, via the
        # batcher's queue item, the engine's bucket/pad/device/readback)
        # all carry the fleet trace_id
        ctx = tracing.TraceContext.from_header(
            self.headers.get("x-trace-context"))
        self._status = 200
        t0 = time.perf_counter()
        try:
            with tracing.trace_context(ctx):
                with trace.span("http_request", path=label,
                                request_id=self._rid or ""):
                    fn()
        finally:
            counter.labels(path=label).inc()
            hist.labels(path=label).observe(time.perf_counter() - t0)
            self.server.inference.note_response(label, self._status)

    def do_GET(self):
        srv = self.server.inference
        path = urlparse(self.path).path

        def handle():
            if path == "/stats":
                self._json(srv.stats())
            elif path == "/healthz":
                info = srv.health_info()
                self._json(info,
                           503 if info["status"] == "draining" else 200)
            elif path == "/metrics":
                self._text(get_registry().render(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/trace":
                # this process's span ring buffer as one Chrome trace-event
                # document — what monitor/collect.py pulls per process
                self._json(trace.export())
            elif path == "/requests":
                # the wide-event request journal (predict + decode rings
                # merged on one timeline) — what collect_requests pulls
                # per replica; ?n= bounds the tail
                q = parse_qs(urlparse(self.path).query)
                n = q.get("n", [None])[0]
                try:
                    n = None if n is None else int(n)
                except ValueError:
                    self._error(400, "bad_request",
                                f"n must be an integer, got {n!r}")
                    return
                self._json(srv.request_journal(n))
            elif path == "/programs":
                from deeplearning4j_tpu.exec.programs import get_programs
                self._json({"programs": get_programs().entries()})
            elif path == "/train/diagnostics":
                # the flight recorder's black box: recent per-layer step
                # records + active anomalies (monitor/flight.py)
                if srv.flight_recorder is None:
                    self._error(404, "not_found",
                                "no flight recorder attached to this server")
                else:
                    self._json(srv.flight_recorder.diagnostics())
            else:
                self._error(404, "not_found", f"no such path: {path}")

        self._observed(path, handle)

    def do_POST(self):
        srv = self.server.inference
        path = urlparse(self.path).path
        n = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(n).decode())
            if not isinstance(payload, dict):
                raise ValueError("payload must be a JSON object")
        except Exception as e:  # noqa: BLE001 — client sent junk
            self._error(400, "bad_request", f"bad json: {e}")
            return

        def handle():
            try:
                if path in ("/predict", "/generate") \
                        and srv.fault_injector is not None:
                    # chaos harness hook: injected latency rides the handler
                    # thread; injected faults surface as the configured 5xx
                    srv.fault_injector.maybe_inject(path)
                if path == "/predict":
                    self._predict(srv, payload)
                elif path == "/generate":
                    self._generate(srv, payload)
                elif path == "/chaos":
                    if srv.fault_injector is None:
                        self._error(404, "not_found",
                                    "chaos injection not enabled "
                                    "on this server")
                    else:
                        srv.fault_injector.configure(**payload)
                        self._json({"chaos": srv.fault_injector.describe()})
                elif path == "/kv/export":
                    self._kv_export(srv, payload)
                elif path == "/kv/import":
                    self._kv_import(srv, payload)
                elif path == "/admin/swap":
                    self._admin_swap(srv, payload)
                elif path == "/admin/profile":
                    self._admin_profile(srv, payload)
                elif path == "/warmup":
                    try:
                        shape = payload["input_shape"]
                    except KeyError:
                        raise BadRequestError(
                            "payload missing 'input_shape'") from None
                    shapes = ([tuple(s) for s in shape]
                              if shape and isinstance(shape[0], list)
                              else tuple(shape))
                    buckets = srv.engine.warmup(
                        shapes, max_batch=payload.get("max_batch"))
                    self._json({"buckets": buckets,
                                "seconds": srv.engine.warmup_seconds})
                else:
                    self._error(404, "not_found", f"no such path: {path}")
            except BadRequestError as e:
                self._error(400, "bad_request", str(e))
            except WeightSwapError as e:
                # structured rejection: the live engines were never touched
                self._error(409, "weight_mismatch", str(e))
            except KVMigrateError as e:
                # same discipline: validation rejected the payload before
                # the destination pool was touched
                self._error(409, "kv_migrate_rejected", str(e))
            except (CorruptCheckpointError, FileNotFoundError) as e:
                self._error(400, "bad_checkpoint", str(e))
            except InjectedFaultError as e:
                self._error(e.code, "injected_fault", str(e))
            except ServerOverloadedError as e:
                self._error(429, "overloaded", str(e))
            except BatcherStoppedError as e:
                self._error(503, "draining", str(e))
            except DeadlineExceededError as e:
                self._error(504, "deadline_exceeded", str(e))
            except Exception as e:  # noqa: BLE001 — engine fault: 500
                srv.note_engine_error(e)
                self._error(500, "internal",
                            f"{type(e).__name__}: {e}")

        self._observed(path, handle)

    def _admin_swap(self, srv, payload):
        """POST /admin/swap {"checkpoint": path, "version"?: int} — load a
        checkpoint's weights and hot-swap them into the live engines (the
        online-learning deploy path; see docs/ONLINE_LEARNING.md)."""
        try:
            ck = payload["checkpoint"]
        except KeyError:
            raise BadRequestError("payload missing 'checkpoint'") from None
        version = payload.get("version")
        if version is not None:
            try:
                version = int(version)
            except (TypeError, ValueError):
                raise BadRequestError(
                    f"version must be an int, got {version!r}") from None
        v = srv.swap_checkpoint(ck, version=version)
        self._json({"swapped": True, "version": v,
                    "checkpoint": str(ck),
                    "compiled_programs": srv.engine.trace_count})

    def _admin_profile(self, srv, payload):
        """POST /admin/profile {"dir": path, "seconds"?: float} — wrap the
        next N seconds of live traffic in ``jax.profiler.trace``; one
        session at a time per process (409 while one runs)."""
        if profiling.profile_status()["profiling"]:
            self._error(409, "profile_busy",
                        "a profiling session is already running")
            return
        try:
            out = profiling.start_profile(
                payload.get("dir", ""),
                seconds=float(payload.get("seconds", 5.0)))
        except (TypeError, ValueError) as e:
            raise BadRequestError(str(e)) from None
        except RuntimeError as e:
            self._error(503, "profiler_unavailable", str(e))
            return
        self._json(out)

    def _predict(self, srv, payload):
        try:
            raw = payload["ndarray"]
        except KeyError:
            raise BadRequestError("payload missing 'ndarray'") from None
        try:
            x = ndarray_from_b64(raw)
        except Exception as e:  # noqa: BLE001 — undecodable client bytes
            raise BadRequestError(f"undecodable ndarray: {e}") from None
        deadline_ms = payload.get("deadline_ms", srv.request_timeout_ms)
        if deadline_ms is not None:
            try:
                deadline_ms = float(deadline_ms)
            except (TypeError, ValueError):
                raise BadRequestError(
                    f"deadline_ms must be a number, got "
                    f"{payload.get('deadline_ms')!r}") from None
        squeeze = x.ndim == 1
        if squeeze:
            x = x[None, :]
        srv.validate_features(x)
        if srv.request_mirror is not None:
            try:
                # shadow-evaluation tap (online/gate.TrafficMirror): a copy
                # of real traffic, never allowed to fail a real request
                srv.request_mirror(x)
            except Exception:   # noqa: BLE001 — mirror is best-effort
                pass
        # block=False: a full queue answers 429 NOW — the handler thread is
        # never parked on backpressure while the client waits
        fut = srv.batcher.submit(
            x, deadline_ms=deadline_ms, block=False,
            request_id=self._rid,
            tenant=self.headers.get("x-tenant", "default"),
            priority=self.headers.get("x-priority", "normal"))
        out = fut.result()
        if squeeze:
            out = out[0]
        # version read at response time: a request racing a swap may report
        # the new version for an answer computed on the old weights — the
        # benign direction (versions only move forward; see the docs)
        self._json({"ndarray": ndarray_to_b64(out)},
                   extra_headers={
                       "x-model-version": str(srv.engine.model_version)})

    def _kv_gate(self, srv):
        """Both migration endpoints require a paged decode engine with a
        prefix cache (the chain index IS the migration unit)."""
        dec = srv.decode_engine
        if dec is None or getattr(dec, "_prefix", None) is None:
            self._error(404, "not_found",
                        "KV migration requires a paged decode engine with "
                        "prefix_cache on this server")
            return None
        return dec

    def _kv_export(self, srv, payload):
        """POST /kv/export {"tokens": [...]} — serialize the cached block
        chain covering the prompt's full blocks (disaggregation: the
        prefill replica's half of a handoff)."""
        dec = self._kv_gate(srv)
        if dec is None:
            return
        try:
            tokens = payload["tokens"]
        except KeyError:
            raise BadRequestError("payload missing 'tokens'") from None
        if (not isinstance(tokens, list)
                or not all(isinstance(t, int) for t in tokens)):
            raise BadRequestError("'tokens' must be a list of token ids")
        self._json(dec.kv_export(tokens), extra_headers={
            "x-model-version": str(dec.model_version)})

    def _kv_import(self, srv, payload):
        """POST /kv/import <export payload> — restore a migrated chain
        into this replica's pool (the decode replica's half). Envelope or
        integrity mismatches answer 409 with the pool untouched."""
        dec = self._kv_gate(srv)
        if dec is None:
            return
        self._json(dec.kv_import(payload), extra_headers={
            "x-model-version": str(dec.model_version)})

    def _generate(self, srv, payload):
        if srv.decode_engine is None:
            self._error(404, "not_found",
                        "no decode engine configured on this server")
            return
        try:
            tokens = payload["tokens"]
        except KeyError:
            raise BadRequestError("payload missing 'tokens'") from None
        if (not isinstance(tokens, list)
                or not all(isinstance(t, int) for t in tokens)):
            raise BadRequestError("'tokens' must be a list of token ids")
        try:
            out = srv.decode_engine.generate(
                tokens,
                max_new_tokens=int(payload.get("max_new_tokens", 32)),
                seed=int(payload.get("seed", 0)),
                temperature=float(payload.get("temperature", 0.0)),
                top_k=int(payload.get("top_k", 0)),
                request_id=self._rid,
                tenant=self.headers.get("x-tenant", "default"),
                priority=self.headers.get("x-priority", "normal"))
        except ValueError as e:     # capacity / id-range problems → 400
            raise BadRequestError(str(e)) from None
        self._json(out, extra_headers={
            "x-model-version": str(srv.decode_engine.model_version)})


class InferenceServer:
    """Serve a model container over HTTP through bucketed micro-batching.

        srv = InferenceServer(net, port=0).start()
        out = InferenceClient(f"http://localhost:{srv.port}").predict(x)

    ``max_queue``: bound on queued requests (beyond it: HTTP 429).
    ``request_timeout_ms``: default per-request deadline when the client
    does not send ``deadline_ms`` (None = no deadline).
    """

    _ids = itertools.count()

    def __init__(self, model, port: int = 9300, host: str = "127.0.0.1",
                 max_batch: int = 256, max_latency_ms: float = 2.0,
                 engine: Optional[InferenceEngine] = None,
                 max_queue: int = 1024,
                 request_timeout_ms: Optional[float] = None,
                 decode_engine=None, fault_injector=None,
                 health_hook=None, request_mirror=None,
                 flight_recorder=None, role: str = "mixed",
                 journal_capacity: int = 512):
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'mixed', got {role!r}")
        # disaggregation role advertised in /stats: a routing PREFERENCE
        # the fleet router reads (prefill-specialized replicas take fresh
        # prefills, decode-specialized ones take migrated chains) — the
        # server itself serves every endpoint regardless of role, so a
        # degraded fleet can always fail over across roles
        self.role = role
        self.engine = engine or InferenceEngine(model)
        # serving/decode.DecodeEngine for POST /generate (None = endpoint
        # answers 404; predict-only servers don't pay for decode slots)
        self.decode_engine = decode_engine
        # resilience/faults.ServerFaultInjector (chaos harness): when set,
        # /predict and /generate pass through it (latency / injected 5xx)
        # and POST /chaos reconfigures it live; None = no chaos surface
        self.fault_injector = fault_injector
        # health_hook: () -> {"status": ...} | None — extra health merged
        # into /healthz (the online trainer degrades serving health on a
        # stalled stream instead of dying; docs/ONLINE_LEARNING.md)
        self.health_hook = health_hook
        # request_mirror: (features ndarray) -> None — best-effort tap on
        # /predict traffic (online/gate.TrafficMirror shadow evaluation)
        self.request_mirror = request_mirror
        # flight_recorder: monitor/flight.FlightRecorder — exposes the
        # training black box at GET /train/diagnostics (None = 404) and
        # degrades /healthz while a degrading training anomaly is active
        self.flight_recorder = flight_recorder
        self.batcher = MicroBatcher(self.engine, max_batch=max_batch,
                                    max_latency_ms=max_latency_ms,
                                    max_queue=max_queue,
                                    journal_capacity=journal_capacity)
        self.request_timeout_ms = request_timeout_ms
        self._port_req = port
        self._host = host
        self._httpd = None
        self.port: Optional[int] = None
        self._draining = threading.Event()
        self.last_error: Optional[str] = None
        self._m_engine_errors = get_registry().counter(
            "dl4jtpu_serving_engine_errors_total",
            "Engine faults surfaced as HTTP 500 by the inference server.")
        # per-instance response classes: the SLI under the burn-rate SLO.
        # Labelled by server instance so a restarted replica starts with a
        # clean error budget instead of inheriting the old process-lifetime
        # counters (the registry is process-wide).
        self.id = f"server{next(InferenceServer._ids)}"
        self._m_responses = get_registry().counter(
            "dl4jtpu_http_responses_total",
            "HTTP responses by status class, per server instance.",
            ("server", "path", "class"))
        sli, bad = [], []
        for p in ("/predict", "/generate"):
            for c in ("2xx", "4xx", "5xx"):
                child = self._m_responses.labels(
                    server=self.id, path=p, **{"class": c})
                sli.append(child)
                if c == "5xx":
                    bad.append(child)
        # availability SLO over /predict + /generate: 5xx (engine faults,
        # injected chaos) burn the budget; 4xx are the client's problem.
        # Fast burn at 14.4x ≈ a sustained >14% 5xx rate over BOTH the 5m
        # and 1h windows — /healthz flips to degraded, and recovers as
        # soon as the short window clears (docs/OBSERVABILITY.md).
        self.slo = BurnRateSLO(
            f"availability:{self.id}",
            bad_fn=lambda: sum(c.value for c in bad),
            total_fn=lambda: sum(c.value for c in sli),
            objective=0.99)
        # request-id mint for direct-to-replica requests (no router, no
        # client-supplied id): pid + server instance keeps ids unique
        # across a local fleet so the merged journal never mis-joins
        self._rid_prefix = f"{os.getpid():x}-{self.id}"
        self._rid_counter = itertools.count(1)

    def mint_rid(self) -> str:
        return f"req-{self._rid_prefix}-{next(self._rid_counter):06d}"

    # --------------------------------------------------------------- health
    def note_engine_error(self, e: BaseException) -> None:
        self.last_error = f"{type(e).__name__}: {e}"
        self._m_engine_errors.inc()

    def note_response(self, path: str, code: int) -> None:
        """Count one HTTP response by status class (called by the handler
        for every request; feeds the availability SLO)."""
        try:
            cls = f"{int(code) // 100}xx"
            self._m_responses.labels(server=self.id, path=path,
                                     **{"class": cls}).inc()
        except Exception:   # noqa: BLE001 — accounting never breaks serving
            pass

    def validate_features(self, x: np.ndarray) -> None:
        """400 for wrong rank / feature width when the model's conf declares
        a fixed input type (feed-forward feature count)."""
        itype = getattr(getattr(self.engine, "model", None), "conf", None)
        itype = getattr(itype, "input_type", None)
        if itype is None or getattr(itype, "kind", None) not in (
                "ff", "cnn_flat"):
            return
        expected = itype.batch_shape(1)
        if x.ndim != len(expected) or x.shape[1:] != expected[1:]:
            raise BadRequestError(
                f"input shape {tuple(x.shape)} does not match model input "
                f"(batch, {', '.join(str(d) for d in expected[1:])})")

    def health_info(self) -> dict:
        """``{"status": ...}`` plus a ``reason`` when degraded. Degraded
        states a router acts on: ``queue_pressure`` (micro-batch queue ≥80%
        full), ``kv_pool_exhausted`` (a paged decode engine cannot claim KV
        blocks for the request at its queue head — long-prompt work should
        steer away until blocks free up) and ``decode_saturated`` (every
        DecodeEngine slot busy — new /generate work queues behind a full
        batch, so prefill-heavy traffic should steer to replicas with free
        slots)."""
        if self._draining.is_set() or self.batcher.stopping:
            return {"status": "draining"}
        st = self.batcher.stats()
        if st["queue_capacity"] and (st["queue_depth"]
                                     >= 0.8 * st["queue_capacity"]):
            return {"status": "degraded", "reason": "queue_pressure"}
        if (self.decode_engine is not None
                and getattr(self.decode_engine, "kv_exhausted", False)):
            return {"status": "degraded", "reason": "kv_pool_exhausted",
                    "kv": self.decode_engine.kv_pool_info()}
        if self.decode_engine is not None and self.decode_engine.saturated:
            return {"status": "degraded", "reason": "decode_saturated"}
        if self.health_hook is not None:
            try:
                extra = self.health_hook()
            except Exception:   # noqa: BLE001 — a broken hook can't take
                extra = None    # the whole server unhealthy
            if extra and extra.get("status") not in (None, "ok"):
                return extra
        if self.flight_recorder is not None:
            try:
                fr = self.flight_recorder.health_info()
            except Exception:   # noqa: BLE001 — telemetry can't take the
                fr = None       # whole server unhealthy
            if fr and fr.get("status") not in (None, "ok"):
                return fr
        try:
            slo = self.slo.evaluate()
        except Exception:       # noqa: BLE001 — SLO math can't break health
            slo = None
        if slo is not None and slo.fast_burn:
            return {"status": "degraded", "reason": "slo_fast_burn",
                    "slo": slo.as_dict()}
        return {"status": "ok"}

    def health(self) -> str:
        return self.health_info()["status"]

    def stats(self) -> dict:
        out = {"engine": self.engine.stats(),
               "batcher": self.batcher.stats(),
               "health": self.health(),
               "role": self.role,
               "model_version": self.engine.model_version,
               "last_error": self.last_error}
        if self.decode_engine is not None:
            out["decode"] = self.decode_engine.stats()
        return out

    def request_journal(self, n: Optional[int] = None) -> dict:
        """The wide-event journal this replica serves at ``GET
        /requests?n=``: the /predict (batcher) and /generate (decode)
        rings merged onto one ``ts`` timeline, newest last."""
        logs = [self.batcher.journal]
        if self.decode_engine is not None:
            logs.append(self.decode_engine.journal)
        recs, total, dropped = [], 0, 0
        for lg in logs:
            snap = lg.snapshot()
            recs.extend(snap["records"])
            total += snap["total"]
            dropped += snap["dropped"]
        recs.sort(key=lambda r: r.get("ts") or 0.0)
        if n is not None:
            recs = recs[-n:] if n > 0 else []
        return {"server": self.id, "total": total, "dropped": dropped,
                "records": recs}

    # ------------------------------------------------------------- hot swap
    def swap_weights(self, params, state=None,
                     version: Optional[int] = None) -> int:
        """Hot-swap both engines to a same-shape weight pytree. The decode
        engine (if any) stages first and applies at its next empty step
        boundary — in-flight generations finish on the old weights — then
        /predict cuts over. Validation happens before either engine is
        touched, so a ``WeightSwapError`` leaves serving exactly as it was.
        Returns the new model version."""
        if version is None:
            version = self.engine.model_version + 1
        if self.decode_engine is not None:
            self.decode_engine.swap_weights(params, state, version=version)
        return self.engine.swap_weights(params, state, version=version)

    def swap_checkpoint(self, path, version: Optional[int] = None) -> int:
        """Load a checkpoint zip's (params, state) and hot-swap them in —
        what POST /admin/swap calls. The zip's own configuration is ignored
        (see model_serializer.load_weights), so head-only transfer-learning
        checkpoints swap into the full serving net."""
        from deeplearning4j_tpu.util import model_serializer
        params, state = model_serializer.load_weights(self.engine.model,
                                                      path)
        return self.swap_weights(params, state, version=version)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "InferenceServer":
        self.batcher.start()
        if self.decode_engine is not None:
            self.decode_engine.start()
        self._httpd = _TrackingHTTPServer((self._host, self._port_req),
                                          _Handler)
        self._httpd.inference = self
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self

    def stop(self) -> None:
        """Graceful drain: flag draining (healthz → 503, LBs pull us), let
        the batcher flush everything already queued, then close the HTTP
        listener AND every established keep-alive connection. Requests
        arriving mid-drain get fast 503s, not hangs — and clients are
        forced to redial, so a restart-in-place on the same port never
        leaves them talking to the dead server's handler threads."""
        self._draining.set()
        self.batcher.stop()
        if self.decode_engine is not None:
            self.decode_engine.stop()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd.close_all_connections()


class _TrackingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that remembers established connections.

    ``shutdown()`` only stops the accept loop; keep-alive connections
    stay open and their daemon handler threads keep answering — after a
    graceful stop that means a permanent stream of 503s on sockets a
    freshly restarted server on the same port can never inherit. Closing
    them at stop() turns "stale connection" into a connect-level error
    the client's reconnect-once logic absorbs on its next request."""

    def __init__(self, *args, **kwargs):
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def get_request(self):
        sock_, addr = super().get_request()
        with self._conns_lock:
            self._conns.add(sock_)
        return sock_, addr

    def shutdown_request(self, request):
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self) -> None:
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for sock_ in conns:
            try:
                sock_.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock_.close()
            except OSError:
                pass
