"""HTTP inference endpoint over the micro-batched engine.

Same stdlib ThreadingHTTPServer + JSON/Base64-f32 transport as
clustering/knn_server.py (the reference's NearestNeighborsServer analog);
each POST /predict rides the micro-batcher, so concurrent HTTP clients are
coalesced into shared device calls. Wire format in docs/SERVING.md.

Endpoints:
  POST /predict  {"ndarray": {shape, data}}          → {"ndarray": ...}
  POST /warmup   {"input_shape": [...], "max_batch"} → {"buckets": [...]}
  GET  /stats                                        → engine+batcher stats
  GET  /metrics                                      → Prometheus text
  GET  /healthz                                      → {"status": "ok"}
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse

from deeplearning4j_tpu.clustering.knn_server import (
    ndarray_from_b64, ndarray_to_b64)
from deeplearning4j_tpu.monitor import get_registry
from deeplearning4j_tpu.serving.batcher import MicroBatcher
from deeplearning4j_tpu.serving.engine import InferenceEngine

_KNOWN_PATHS = ("/predict", "/warmup", "/stats", "/metrics", "/healthz")


def _http_metrics():
    reg = get_registry()
    return (reg.counter("dl4jtpu_http_requests_total",
                        "HTTP requests served by the inference server.",
                        ("path",)),
            reg.histogram("dl4jtpu_http_request_seconds",
                          "Wall seconds per HTTP request, handler-inclusive.",
                          ("path",)))


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):
        pass

    def _json(self, obj, code=200):
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _text(self, body: str, content_type: str, code=200):
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _observed(self, path, fn):
        # per-path request count + latency; unknown paths share one series
        # so a URL-probing client can't mint unbounded label values
        counter, hist = _http_metrics()
        label = path if path in _KNOWN_PATHS else "other"
        t0 = time.perf_counter()
        try:
            fn()
        finally:
            counter.labels(path=label).inc()
            hist.labels(path=label).observe(time.perf_counter() - t0)

    def do_GET(self):
        srv = self.server.inference
        path = urlparse(self.path).path

        def handle():
            if path == "/stats":
                self._json(srv.stats())
            elif path == "/healthz":
                self._json({"status": "ok"})
            elif path == "/metrics":
                self._text(get_registry().render(),
                           "text/plain; version=0.0.4; charset=utf-8")
            else:
                self._json({"error": "not found"}, 404)

        self._observed(path, handle)

    def do_POST(self):
        srv = self.server.inference
        path = urlparse(self.path).path
        n = int(self.headers.get("Content-Length", 0))
        try:
            payload = json.loads(self.rfile.read(n).decode())
        except Exception as e:
            self._json({"error": f"bad json: {e}"}, 400)
            return

        def handle():
            try:
                if path == "/predict":
                    x = ndarray_from_b64(payload["ndarray"])
                    if x.ndim == 1:
                        x = x[None, :]
                        out = srv.batcher.predict(x)[0]
                    else:
                        out = srv.batcher.predict(x)
                    self._json({"ndarray": ndarray_to_b64(out)})
                elif path == "/warmup":
                    shape = payload["input_shape"]
                    shapes = ([tuple(s) for s in shape]
                              if shape and isinstance(shape[0], list)
                              else tuple(shape))
                    buckets = srv.engine.warmup(
                        shapes, max_batch=payload.get("max_batch"))
                    self._json({"buckets": buckets,
                                "seconds": srv.engine.warmup_seconds})
                else:
                    self._json({"error": "not found"}, 404)
            except Exception as e:  # noqa: BLE001 — service must answer
                self._json({"error": str(e)}, 500)

        self._observed(path, handle)


class InferenceServer:
    """Serve a model container over HTTP through bucketed micro-batching.

        srv = InferenceServer(net, port=0).start()
        out = InferenceClient(f"http://localhost:{srv.port}").predict(x)
    """

    def __init__(self, model, port: int = 9300, host: str = "127.0.0.1",
                 max_batch: int = 256, max_latency_ms: float = 2.0,
                 engine: Optional[InferenceEngine] = None):
        self.engine = engine or InferenceEngine(model)
        self.batcher = MicroBatcher(self.engine, max_batch=max_batch,
                                    max_latency_ms=max_latency_ms)
        self._port_req = port
        self._host = host
        self._httpd = None
        self.port: Optional[int] = None

    def stats(self) -> dict:
        return {"engine": self.engine.stats(),
                "batcher": self.batcher.stats()}

    def start(self) -> "InferenceServer":
        self.batcher.start()
        self._httpd = ThreadingHTTPServer((self._host, self._port_req),
                                          _Handler)
        self._httpd.inference = self
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        self.batcher.stop()
