"""Signal-driven fleet autoscaling over the routed serving tier
(docs/AUTOSCALING.md).

The ``Autoscaler`` closes ROADMAP item 5's last gap: the replica set
follows load instead of being fixed at boot. It is a control loop over
signals the tier already exports — per-replica outstanding counts from
the router, the router-level burn-rate SLO (monitor/slo.py), and the
per-program cost estimates in the ``/programs`` registry — and it acts
through the two runtime edges the router grew for it:

- scale-up: spawn a replica (``ReplicaProcess(aot=artifact)`` in
  production — the AOT artifact makes cold-start sub-second), gate on
  ``wait_ready()`` (warm /healthz) plus an optional warmup probe, and
  only then ``router.add_upstream``; a replica never takes traffic
  before it can serve it.
- scale-down: pick the least-loaded replica, ``router.remove_upstream``
  (the existing ``admin_down`` → drain path), then stop the process.

Scale-to-zero: with ``min_replicas=0`` an idle fleet drains completely;
the router's ``hold_for_capacity_s`` + ``wake_hook`` (wired to
``Autoscaler.wake``) hold the next request briefly while a replica
AOT-restores, converting the would-be 503 into a served request.

The loop is deliberately conservative: one scale event per evaluation,
a cooldown between events, and an idle grace period before shrinking —
flapping costs more than a briefly oversized fleet. Tests drive
``evaluate_once()`` directly with an injected clock; the background
thread exists only to call it on a cadence and to react to ``wake()``
without waiting out the interval.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from deeplearning4j_tpu.monitor import get_registry

__all__ = ["Autoscaler"]


class Autoscaler:
    """Grow/shrink a router's replica set from load + SLO signals.

    Parameters
    ----------
    router: the ``Router`` to act on (uses ``add_upstream`` /
        ``remove_upstream`` / ``replicas`` / ``slo``).
    spawn: zero-arg factory returning an UNstarted replica handle with
        the ``ReplicaProcess`` shape (``start() → wait_ready() → .url``,
        ``stop()``). Production passes
        ``lambda: ReplicaProcess(workdir, aot=artifact, ...)``; tests
        pass ``InProcessReplica`` factories.
    min_replicas / max_replicas: fleet bounds. ``min_replicas=0``
        enables scale-to-zero (pair the router with
        ``hold_for_capacity_s`` + this scaler's ``wake``).
    scale_up_outstanding: average outstanding requests per replica above
        which the fleet grows (the queueing signal).
    scale_down_outstanding: average below which a replica is a
        candidate to drain, once idle for ``idle_grace_s``.
    idle_grace_s: how long the shrink condition must hold continuously.
    cooldown_s: minimum time between scale events (wake-from-zero is
        exempt — it is the emergency path).
    warmup_probe: optional ``handle -> bool`` extra admission gate run
        after ``wait_ready``; a False/raising probe stops the replica
        instead of admitting it.
    ready_timeout_s: passed to ``wait_ready``.
    clock / sleep: injectable time (tests drive a fake clock through
        ``evaluate_once``).
    """

    def __init__(self, router, spawn: Callable[[], object],
                 min_replicas: int = 1, max_replicas: int = 4,
                 scale_up_outstanding: float = 8.0,
                 scale_down_outstanding: float = 1.0,
                 idle_grace_s: float = 30.0,
                 cooldown_s: float = 10.0,
                 interval_s: float = 1.0,
                 warmup_probe: Optional[Callable[[object], bool]] = None,
                 ready_timeout_s: float = 180.0,
                 drain_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 name: str = "autoscaler"):
        if min_replicas < 0 or max_replicas < max(1, min_replicas):
            raise ValueError(
                f"need 0 <= min_replicas <= max_replicas (and max >= 1), "
                f"got {min_replicas}/{max_replicas}")
        self.router = router
        self.spawn = spawn
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_outstanding = float(scale_up_outstanding)
        self.scale_down_outstanding = float(scale_down_outstanding)
        self.idle_grace_s = float(idle_grace_s)
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self.warmup_probe = warmup_probe
        self.ready_timeout_s = float(ready_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.id = name
        self._clock = clock
        self._sleep = sleep
        self._fleet: Dict[str, object] = {}     # url -> replica handle
        self._lock = threading.Lock()
        self._last_event = -float("inf")
        self._idle_since: Optional[float] = None
        self._kick = threading.Event()
        self._wake_pending = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        reg = get_registry()
        self._m_replicas = reg.gauge(
            "dl4jtpu_autoscaler_replicas",
            "Replicas the autoscaler currently owns (admitted to the "
            "router or mid-admission).", ("scaler",))
        self._m_replicas.labels(scaler=self.id).set_function(
            lambda: float(len(self._fleet)))
        self._m_events = reg.counter(
            "dl4jtpu_autoscaler_scale_events_total",
            "Fleet resize decisions that completed, by direction "
            "(up: replica admitted after ready+probe gates; down: "
            "replica drained and stopped).", ("scaler", "direction"))
        self._m_wakeups = reg.counter(
            "dl4jtpu_autoscaler_wakeups_total",
            "wake() calls (the router's scale-to-zero hold path poking "
            "the scaler to bring up capacity NOW).", ("scaler",))

    # ---------------------------------------------------------------- fleet
    @property
    def replica_count(self) -> int:
        return len(self._fleet)

    def adopt(self, handle) -> None:
        """Track an already-running, already-admitted replica (the boot
        fleet) so scale-down can drain it later."""
        with self._lock:
            self._fleet[handle.url] = handle

    # -------------------------------------------------------------- signals
    def signals(self) -> dict:
        """The decision inputs, as one readable dict (also what the
        autoscale bench row records)."""
        reps = self.router.replicas
        outs = [r.outstanding for r in list(reps.values())
                if not r.admin_down]
        n = max(1, len(outs))
        try:
            slo = self.router.slo.evaluate()
            fast_burn = bool(slo.fast_burn)
        except Exception:   # noqa: BLE001 — SLO math can't break scaling
            fast_burn = False
        # program-cost signal: total registered program cost approximates
        # how expensive a cold replica is, i.e. how early to scale up
        try:
            from deeplearning4j_tpu.exec.programs import get_programs
            compile_cost_s = sum(
                (e.get("compile_seconds") or 0.0)
                for e in get_programs().entries())
        except Exception:   # noqa: BLE001
            compile_cost_s = 0.0
        return {"replicas": len(self._fleet),
                "routable": len(outs),
                "outstanding_total": float(sum(outs)),
                "outstanding_per_replica": float(sum(outs)) / n,
                "fast_burn": fast_burn,
                "compile_cost_s": compile_cost_s}

    # ------------------------------------------------------------ decisions
    def evaluate_once(self) -> Optional[str]:
        """One control-loop pass. Returns "up"/"down" when a scale event
        completed, None otherwise. Thread-safe; the loop thread and tests
        share this entry."""
        with self._lock:
            now = self._clock()
            wake = self._wake_pending
            self._wake_pending = False

            if wake and not self._fleet:
                # scale-from-zero: bypass the cooldown — a request is
                # being held at the router right now
                return self._scale_up(now)

            sig = self.signals()
            in_cooldown = now - self._last_event < self.cooldown_s

            want_up = (sig["fast_burn"]
                       or sig["outstanding_per_replica"]
                       >= self.scale_up_outstanding
                       or len(self._fleet) < self.min_replicas)
            if want_up and not in_cooldown \
                    and len(self._fleet) < self.max_replicas:
                self._idle_since = None
                return self._scale_up(now)

            calm = (not sig["fast_burn"]
                    and sig["outstanding_per_replica"]
                    <= self.scale_down_outstanding)
            if calm and len(self._fleet) > self.min_replicas:
                if self._idle_since is None:
                    self._idle_since = now
                elif (now - self._idle_since >= self.idle_grace_s
                      and not in_cooldown):
                    return self._scale_down(now)
            else:
                self._idle_since = None
            return None

    def _scale_up(self, now: float) -> Optional[str]:
        handle = self.spawn()
        try:
            handle.start()
            handle.wait_ready(timeout=self.ready_timeout_s)
            if self.warmup_probe is not None \
                    and not self.warmup_probe(handle):
                raise RuntimeError("warmup probe rejected the replica")
        except Exception:   # noqa: BLE001 — a failed boot must not leak
            try:
                handle.stop()
            except Exception:   # noqa: BLE001
                pass
            return None
        self.router.add_upstream(handle.url)
        self._fleet[handle.url] = handle
        self._last_event = self._clock()
        self._m_events.labels(scaler=self.id, direction="up").inc()
        return "up"

    def _scale_down(self, now: float) -> Optional[str]:
        reps = self.router.replicas
        # least outstanding first; ties retire the NEWEST member (LIFO over
        # the insertion-ordered fleet) so the longest-lived replica survives
        cands = [(reps[url].outstanding if url in reps else 0, -i, url)
                 for i, url in enumerate(self._fleet)]
        if not cands:
            return None
        _, _, url = min(cands)
        handle = self._fleet.pop(url)
        self.router.remove_upstream(url, drain_timeout=self.drain_timeout_s)
        try:
            handle.stop()
        except Exception:   # noqa: BLE001 — already-dead replica is fine
            pass
        self._last_event = self._clock()
        self._idle_since = None
        self._m_events.labels(scaler=self.id, direction="down").inc()
        return "down"

    # ----------------------------------------------------------------- wake
    def wake(self) -> None:
        """The router's scale-to-zero hook: a request arrived with no
        routable replica. Kicks the loop immediately (and flags the
        cooldown-exempt scale-from-zero path)."""
        self._m_wakeups.labels(scaler=self.id).inc()
        self._wake_pending = True
        self._kick.set()

    # ----------------------------------------------------------------- loop
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name=f"{self.id}-loop", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(timeout=self.interval_s)
            self._kick.clear()
            if self._stop.is_set():
                return
            try:
                self.evaluate_once()
            except Exception:   # noqa: BLE001 — the loop must survive
                pass

    def stop(self, stop_fleet: bool = True) -> None:
        """Stop the loop; with ``stop_fleet`` also drain + stop every
        owned replica (test teardown)."""
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if stop_fleet:
            with self._lock:
                fleet = dict(self._fleet)
                self._fleet.clear()
            for url, handle in fleet.items():
                try:
                    self.router.remove_upstream(url, drain_timeout=5.0)
                except Exception:   # noqa: BLE001
                    pass
                try:
                    handle.stop()
                except Exception:   # noqa: BLE001
                    pass
