"""Acceptance rules for speculative decoding — and the ONE sampling oracle.

The whole correctness story of the draft/verify subsystem reduces to a
single function: ``oracle_token`` is the engine's deterministic sampling
rule — top-k filter on ``log(probs)``, argmax when ``temperature == 0``,
else ``categorical(fold_in(PRNGKey(seed), position), logits / temp)``.
It is a pure function of (distribution, request seed, position), never of
the slot index, co-tenants, or arrival schedule (docs/DECODING.md
"Determinism rules"). `DecodeEngine._step_impl`, ``generate_naive`` AND
the speculative verify program all call this one definition, so the
token the verifier would have emitted at a position is — by construction,
not by tolerance — the token the non-speculative engine emits there.

Acceptance is *sample matching*: drafted token ``d_j`` is accepted iff it
equals the oracle token for position j computed from the TARGET model's
distribution. Accepted prefixes are therefore bitwise-identical to the
non-speculative trajectory for greedy (exact-match acceptance, the
Leviathan et al. 2023 greedy special case) and for temperature sampling
(the seeded sample is the same sample the engine would have drawn — the
fixed-seed trace form of lossless rejection sampling). The first
mismatching position emits the oracle token itself (the "bonus" /
correction token), so every verify call advances each slot by at least
one token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def oracle_token(logits, seed, pos, temp, top_k):
    """The engine sampling rule for ONE distribution row.

    ``logits``: (V,) log-probabilities (any monotone transform of the
    output softmax); ``seed``/``pos``/``temp``/``top_k``: scalars. Returns
    the sampled token id (int32). Greedy (``temp == 0``) is the argmax of
    the top-k-filtered row; sampled is categorical under the per-request
    key ``fold_in(PRNGKey(seed), pos)``. Op-for-op the historical
    DecodeEngine/_step_impl and generate_naive rule — both now call this.
    """
    V = logits.shape[-1]
    k = jnp.where(top_k > 0, jnp.clip(top_k, 1, V), V)
    thr = jnp.sort(logits)[::-1][k - 1]
    logits = jnp.where(logits >= thr, logits, -jnp.inf)
    greedy = jnp.argmax(logits).astype(jnp.int32)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
    safe_t = jnp.where(temp > 0, temp, 1.0).astype(logits.dtype)
    sampled = jax.random.categorical(key, logits / safe_t).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy)


# batched rule: one row per slot — (S, V) logits, (S,) seed/pos/temp/top_k
oracle_tokens = jax.vmap(oracle_token)


def accept_length(oracle, draft, n_in):
    """Leading-match acceptance over a k-token draft window.

    ``oracle``/``draft``: (..., k) token ids — the target's oracle tokens
    and the draft's proposals for the same positions. ``n_in``: (...,)
    number of valid draft positions this call (0 = slot inert). Returns
    ``(accepted, emitted)``:

    - ``accepted`` = length of the longest prefix where every drafted
      token equals its oracle token (capped at ``n_in``),
    - ``emitted`` = ``min(accepted + 1, n_in)`` — the accepted prefix plus
      the oracle's correction token at the first mismatch (when the whole
      window matches there is no correction slot left inside the window,
      so emitted == accepted == n_in).

    Pure jnp, shape-polymorphic: runs inside the verify program on (S, k)
    arrays and eagerly on numpy rows in tests (the host-side reference).
    """
    k = draft.shape[-1]
    valid = jnp.arange(k) < n_in[..., None]
    m = ((oracle == draft) & valid).astype(jnp.int32)
    accepted = jnp.cumprod(m, axis=-1).sum(axis=-1)
    emitted = jnp.minimum(accepted + 1, n_in)
    return accepted, emitted
