"""Self-drafting: speculative decoding with ZERO extra checkpoints.

A separate distilled draft model is an ops burden — another artifact to
train, version, ship and keep vocabulary-aligned. Self-drafting reuses
the TARGET's own weights as the draft in one of two ways:

- ``self_draft="int8"`` / ``"fp8"`` — the draft IS the target, run at
  quantized precision through the existing serving-quantization policy
  (``exec.prepare_params`` → ``dequantize_tree`` inside the draft
  program, docs/QUANTIZATION.md). The draft streams weights at
  quantized width and agrees with the f32 target almost always
  (quantization noise rarely flips the oracle), so acceptance is near 1
  and the win is dispatch amortization: one k-step draft scan + one
  batched verify replaces k+1 sequential target dispatches.
- ``self_draft="early_exit:M"`` — a truncated-stack VIEW of the target:
  its first M layers plus the shared readout layer, no copied weights
  (properties alias the target's params), giving a genuinely cheaper
  draft at lower agreement. Requires a MultiLayerNetwork target whose
  intermediate width matches the readout's input width (uniform-width
  stacks — e.g. the charRNN zoo models).

Both forms plug into the unchanged ``DraftEngine`` — the draft model is
just a model with the incremental-decode protocol — so tree drafting,
carry snapshots and the one-program pin all apply as-is. Configure via
``SpecConfig(draft_model=None, self_draft=...)``; replica flag
``--spec-self-draft`` (serving/replica.py).
"""

from __future__ import annotations

from deeplearning4j_tpu.models.multi_layer_network import (MultiLayerNetwork
                                                           as _MLN)

SELF_DRAFT_QUANT = ("int8", "fp8")


def parse_self_draft(mode):
    """Validate a ``self_draft`` mode string → ``("quant", precision)``
    or ``("early_exit", M)``."""
    if mode in SELF_DRAFT_QUANT:
        return ("quant", mode)
    if isinstance(mode, str) and mode.startswith("early_exit:"):
        try:
            m = int(mode.split(":", 1)[1])
        except ValueError:
            m = 0
        if m < 1:
            raise ValueError(
                f"self_draft {mode!r}: early_exit needs a positive layer "
                "count, e.g. 'early_exit:1'")
        return ("early_exit", m)
    raise ValueError(
        f"self_draft must be one of {SELF_DRAFT_QUANT} or 'early_exit:M', "
        f"got {mode!r}")


class EarlyExitDraft:
    """Truncated-stack view of a MultiLayerNetwork target: layers
    ``0..M-1`` plus the final readout, weights ALIASED from the target
    (``params``/``state`` are properties — a hot swap in the target is a
    hot swap in the draft). Implements exactly the slice of the model
    protocol the DraftEngine drives — ``init_decode_state`` and
    ``decode_step`` are MultiLayerNetwork's own methods over the
    truncated layer list, so the draft math is the target's math minus
    the skipped layers."""

    def __init__(self, target, m):
        if hasattr(target.conf, "network_inputs"):
            raise ValueError(
                "early_exit self-drafting needs a MultiLayerNetwork "
                "target (a graph has no unique layer stack to truncate); "
                "use self_draft='int8'/'fp8' instead")
        m = int(m)
        if not 1 <= m <= len(target.layers) - 1:
            raise ValueError(
                f"early_exit:{m} out of range for a "
                f"{len(target.layers)}-layer target (need 1 <= M <= "
                f"{len(target.layers) - 1})")
        readout, last = target.layers[-1], target.layers[m - 1]
        n_mid = getattr(last, "n_out", None) or getattr(last, "n_in", None)
        n_ro = getattr(readout, "n_in", None)
        if n_mid and n_ro and n_mid != n_ro:
            raise ValueError(
                f"early_exit:{m}: layer {m - 1} outputs {n_mid} features "
                f"but the readout expects {n_ro} — early exit needs a "
                "width-compatible truncation point")
        self._target = target
        self.m = m
        self.conf = target.conf          # global_conf + input_type riders
        self.layers = list(target.layers[:m]) + [readout]
        self._executor = getattr(target, "_executor", None)

    @property
    def params(self):
        t = self._target.params
        return [t[i] for i in range(self.m)] + [t[-1]]

    @property
    def state(self):
        t = self._target.state
        if not t:
            return t
        return [t[i] for i in range(self.m)] + [t[-1]]

    # the container decode protocol, verbatim over the truncated stack
    init_decode_state = _MLN.init_decode_state
    decode_step = _MLN.decode_step


def build_self_draft(target, spec):
    """Resolve ``SpecConfig.self_draft`` → ``(draft_model, precision)``
    for the DraftEngine (serving/decode.py)."""
    kind, arg = parse_self_draft(spec.self_draft)
    if kind == "quant":
        if spec.draft_precision not in (None, arg):
            raise ValueError(
                f"self_draft={spec.self_draft!r} conflicts with "
                f"draft_precision={spec.draft_precision!r}")
        return target, arg
    return EarlyExitDraft(target, arg), spec.draft_precision


__all__ = ["EarlyExitDraft", "build_self_draft", "parse_self_draft",
           "SELF_DRAFT_QUANT"]
