"""Carry-vs-positional decode-state surgery for speculative rewind.

A draft/verify tick advances decode state by up to k positions and then
rolls back to the accepted prefix. The rollback strategy differs by leaf
class (nn/layers/base.py ``positional_state_keys``):

- POSITIONAL leaves (attention KV caches, dense ``k``/``v`` or paged
  ``pk``/``pv``): written at explicit position indices and read through a
  causal ``key_pos <= query_pos`` mask — rejected positions are simply
  left in place. The next tick re-writes them (scatter-before-gather
  inside the same device call) before any query's causal horizon reaches
  them, so a stale row is never read. No rollback state needed.
- CARRY leaves (recurrent h/c tuples): position-free — the carry after
  token t depends on every token up to t, so rejecting token t means the
  carry must be restored to its value after token ``a`` (the last
  accepted one). These are snapshotted per chunk position
  (``prefill_chunk(..., carry_stack=True)`` / the draft scan) and the
  rollback selects snapshot ``e - 1``.

The helpers here walk a model's decode-state container (list for
MultiLayerNetwork, node-name dict for ComputationGraph) with the OWNING
layer in hand, so dict keys can be classified against that layer's
``positional_state_keys``.
"""

from __future__ import annotations

import jax


def layer_entries(model):
    """``[(key, layer)]`` pairs where ``key`` indexes the model's decode
    state container: integers for MultiLayerNetwork's per-layer list,
    layer-node names for ComputationGraph's dict."""
    if hasattr(model.conf, "network_inputs"):
        return [(n, model.conf.nodes[n].layer)
                for n in model.conf.topological_order
                if model.conf.nodes[n].kind == "layer"]
    return list(enumerate(model.layers))


def _map_sub(sub, pos_keys, on_carry, on_positional, rest):
    """Map one layer's decode-state sub-tree, dispatching each leaf to
    ``on_carry`` or ``on_positional``. Only dict entries can be positional
    (attention caches are dicts); tuples (LSTM (h, c)) and bare leaves are
    always carries. ``rest``: extra same-structure sub-trees passed as
    additional leaf arguments."""
    if sub is None:
        return None
    tmap = jax.tree_util.tree_map
    if isinstance(sub, dict):
        return {k: tmap(on_positional if k in pos_keys else on_carry,
                        v, *(r[k] for r in rest))
                for k, v in sub.items()}
    return tmap(on_carry, sub, *rest)


def map_state(model, dstate, on_carry, on_positional, rest=()):
    """Rebuild ``dstate`` applying ``on_carry`` to recurrent-carry leaves
    and ``on_positional`` to position-indexed cache leaves. ``rest`` is a
    tuple of additional trees with the same container structure whose
    matching leaves ride along as extra arguments (their leaf SHAPES may
    differ — e.g. a (K,)-stacked snapshot tree zipped with the flat
    final state)."""
    out = dict(dstate) if isinstance(dstate, dict) else list(dstate)
    for key, layer in layer_entries(model):
        pos_keys = frozenset(getattr(layer, "positional_state_keys", ()))
        out[key] = _map_sub(dstate[key], pos_keys, on_carry, on_positional,
                            [r[key] for r in rest])
    return out


def rewound_state(model, new_d, stacks, idx, rows):
    """Post-verify state: positional leaves pass through (the causal/
    ancestry mask hides rejected positions until the accepted path is
    committed over them); layers that returned a carry snapshot stack
    are rolled back to snapshot ``idx`` — (K, B, ...) stacks indexed as
    ``s[idx, rows]``. The index axis is whatever the producer stacked
    over: chunk positions for a linear prefill window, NODE indices for
    a tree verify (``Layer.tree_chunk``) — either way ``idx`` selects
    the carry after the last emitted token of each slot."""
    out = dict(new_d) if isinstance(new_d, dict) else list(new_d)
    for key, _layer in layer_entries(model):
        st = stacks[key]
        if st is None:
            continue
        out[key] = jax.tree_util.tree_map(lambda s: s[idx, rows], st)
    return out
