"""Verify half of the speculative decoder: score a whole drafted token
TREE for every active slot in ONE batched target step.

The verify program feeds each slot's N tree nodes as extra window
positions through ``model.tree_chunk``: node n sits at stream position
``pos0 + depth(n)`` and attends to the committed cache plus its own
root-path only (the causal tree-mask, built from the static ancestor
tables in serving/spec/tree.py — ancestry replaces linearity). The
sampling oracle (serving/spec/accept.py — the SAME function the
non-speculative step uses) turns every node's distribution into the
token the engine would have emitted there, and the acceptance walk
(``TreeSpec.walk``) follows oracle matches from the root to the longest
accepted path ``a``; the host appends the path's ``a + 1`` oracle tokens
(accepted prefix + the deepest node's bonus/correction), so the emitted
stream is bitwise the non-speculative trajectory for greedy AND seeded
temperature sampling. A linear draft is the ``kvec = (1,) * k`` tree —
one program, one code path.

Rejected nodes are never "erased" — they are never WRITTEN: sibling
nodes share stream positions, so tree attention reads per-node effective
caches instead of scattering, and only the accepted path's K/V commits
(``model.tree_commit``, still inside this one program). Recurrent
carries roll back via the node-indexed snapshot stacks ``tree_chunk``
returns: the final carry is the accepted node's snapshot
(serving/spec/rewind.py). Inert rows (``n_in == 0``) follow the chunked
prefill discipline exactly: paged commits land in scratch block 0, dense
commits rewrite their current bytes, and a final freeze keeps their
state bitwise.
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.quant import dequantize_tree
from deeplearning4j_tpu.serving.kv import map_slot_leaves
from deeplearning4j_tpu.serving.spec.accept import oracle_tokens
from deeplearning4j_tpu.serving.spec.rewind import rewound_state


class SpecVerifier:
    """Owns the single verify program for one DecodeEngine (``owner`` =
    its id). ``tree``: the engine's static ``TreeSpec`` — every shape in
    the program is a function of it alone, so the program compiles once
    regardless of tree acceptance history. ``kv``/``kv_max_blocks``
    mirror the engine: the paged variant takes the (S, max_blocks) page
    table as one more data arg, same shape every call."""

    def __init__(self, model, owner, slots, max_len, tree, vocab,
                 kv="dense", kv_max_blocks=0):
        self.model = model
        self.owner = owner
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.tree = tree
        self.vocab = int(vocab)
        self.kv = kv
        self.kv_max_blocks = int(kv_max_blocks)
        self.programs = 0            # exact XLA trace count (pin: 1)
        from deeplearning4j_tpu import exec as ex
        execu = getattr(model, "_executor", None) or ex.get_executor()
        if kv == "paged":
            self._jit = execu.jit(
                self._impl_paged,
                in_specs=(ex.PARAMS, ex.STATE, ex.SLOTS) + (ex.BATCH,) * 8,
                out_specs=(ex.BATCH,) * 4 + (ex.SLOTS,),
                donate_argnums=(2,))
        else:
            self._jit = execu.jit(
                self._impl,
                in_specs=(ex.PARAMS, ex.STATE, ex.SLOTS) + (ex.BATCH,) * 7,
                out_specs=(ex.BATCH,) * 4 + (ex.SLOTS,),
                donate_argnums=(2,))

    # ------------------------------------------------------------- program
    def _impl(self, params, state, dstate, tokens, pos0, n_in, reset,
              seeds, temps, topk, btab=None):
        """ONE verify for all S slots. ``tokens`` (S, N): each slot's
        flattened tree node tokens (node 0 = the last emitted token,
        then depth groups in ``TreeSpec`` order); ``n_in`` (S,): emit
        budget — at most n_in tokens may advance this tick (0 = inert
        row). Returns ``(emit, accepted, emitted, spine_acc,
        new_dstate)`` — ``emit`` (S, D+1) holds the accepted path's
        oracle tokens masked to the emitted prefix."""
        from deeplearning4j_tpu.exec.programs import is_registering
        if not is_registering():
            self.programs += 1
        params = dequantize_tree(params)
        S, tr = self.slots, self.tree
        tmap = (jax.tree_util.tree_map if btab is None else map_slot_leaves)

        def wipe(a):
            r = reset.reshape((S,) + (1,) * (a.ndim - 1))
            return jnp.where(r, jnp.zeros_like(a), a)

        # a fresh slot's first target-model call may be a verify (e.g. a
        # one-token prompt): the reset wipe lives here like in the step
        dstate = tmap(wipe, dstate)
        x = jax.nn.one_hot(tokens, self.vocab, dtype=jnp.float32)
        y, stacks, wins = self.model.tree_chunk(
            params, state, dstate, x, pos0, tr, n_in, block_tables=btab)
        # the target's own emission at every tree node, under the
        # request's fold_in(seed, position) rule — identical by
        # construction to what the non-speculative step would sample at
        # that node's stream position after that node's prefix
        oracle = jnp.stack(
            [oracle_tokens(jnp.log(y[:, i]), seeds,
                           pos0 + int(tr.depth[i]), temps, topk)
             for i in range(tr.n_nodes)], axis=1)
        accepted, emitted, spine_acc, path = tr.walk(tokens, oracle, n_in)
        rows = jnp.arange(S)
        # carries roll back to the accepted node's snapshot; positional
        # KV commits only the accepted path (masked rows → scratch/no-op)
        node_idx = jnp.take_along_axis(path, accepted[:, None],
                                       axis=1)[:, 0]
        merged = rewound_state(self.model, dstate, stacks, node_idx, rows)
        merged = self.model.tree_commit(merged, wins, path, pos0, emitted,
                                        block_tables=btab)
        live = n_in > 0

        def freeze(new, old):
            m = live.reshape((S,) + (1,) * (new.ndim - 1))
            return jnp.where(m, new, old)

        merged = tmap(freeze, merged, dstate)
        emit = jnp.take_along_axis(oracle, path, axis=1)      # (S, D+1)
        emit = jnp.where(jnp.arange(tr.d + 1)[None, :] < emitted[:, None],
                         emit, 0).astype(jnp.int32)
        return emit, accepted, emitted, spine_acc, merged

    def _impl_paged(self, params, state, dstate, btab, tokens, pos0, n_in,
                    reset, seeds, temps, topk):
        """Paged verify: page table right after the donated state (same
        argument discipline as the paged step program)."""
        return self._impl(params, state, dstate, tokens, pos0, n_in,
                          reset, seeds, temps, topk, btab=btab)

    # ---------------------------------------------------------------- host
    def run(self, params, state, dstate, *args):
        """Run one verify; returns (emit, accepted, emitted, spine_acc)
        as numpy plus the new donated state tree."""
        c0, t0 = self.programs, time.perf_counter()
        emit, accepted, emitted, spine_acc, new_d = self._jit(
            params, state, dstate, *args)
        out = (np.asarray(emit), np.asarray(accepted),
               np.asarray(emitted), np.asarray(spine_acc))
        if self.programs > c0:
            from deeplearning4j_tpu.exec.programs import get_programs
            get_programs().record(
                self.owner, "verify", self._jit,
                (params, state, new_d) + tuple(args),
                compile_seconds=time.perf_counter() - t0)
        return out + (new_d,)
