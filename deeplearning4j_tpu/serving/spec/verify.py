"""Verify half of the speculative decoder: score all k drafted tokens
for every active slot in ONE batched target step.

The verify program is the paged-KV chunked-prefill write path
(``model.prefill_chunk``) pointed at generated tokens instead of prompt
tokens: slot i feeds its k-token window ``[tok0, d_0 .. d_{k-2}]`` at
positions ``pos0 .. pos0+n_in-1``, the model produces the target
distribution at every window position in one call, and the sampling
oracle (serving/spec/accept.py — the SAME function the non-speculative
step uses) turns each distribution into the token the engine would have
emitted there. ``accept_length`` then gives the per-slot accepted prefix
``a`` and emit count ``e = min(a+1, n_in)``; the host appends
``oracle[:e]``, so the emitted stream is bitwise the non-speculative
trajectory for greedy AND seeded temperature sampling.

Rejected positions are never "erased": positional KV written for them is
left in place and hidden by the causal position mask until the next
tick's chunk overwrites it (scatter-before-gather inside one program —
see docs/DECODING.md "Speculative decoding"); recurrent carries roll
back via the per-position snapshot stacks ``carry_stack=True`` returns
(serving/spec/rewind.py). Inert rows (``n_in == 0``) follow the chunked
prefill discipline exactly: paged writes land in scratch block 0, dense
rows are write-masked, and a final freeze keeps their state bitwise.
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.quant import dequantize_tree
from deeplearning4j_tpu.serving.kv import map_slot_leaves
from deeplearning4j_tpu.serving.spec.accept import accept_length, oracle_tokens
from deeplearning4j_tpu.serving.spec.rewind import rewound_state


class SpecVerifier:
    """Owns the single verify program for one DecodeEngine (``owner`` =
    its id). ``kv``/``kv_max_blocks`` mirror the engine: the paged
    variant takes the (S, max_blocks) page table as one more data arg,
    same shape every call."""

    def __init__(self, model, owner, slots, max_len, k, vocab, kv="dense",
                 kv_max_blocks=0):
        self.model = model
        self.owner = owner
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.k = int(k)
        self.vocab = int(vocab)
        self.kv = kv
        self.kv_max_blocks = int(kv_max_blocks)
        self.programs = 0            # exact XLA trace count (pin: 1)
        from deeplearning4j_tpu import exec as ex
        execu = getattr(model, "_executor", None) or ex.get_executor()
        if kv == "paged":
            self._jit = execu.jit(
                self._impl_paged,
                in_specs=(ex.PARAMS, ex.STATE, ex.SLOTS) + (ex.BATCH,) * 9,
                out_specs=(ex.BATCH, ex.BATCH, ex.BATCH, ex.SLOTS),
                donate_argnums=(2,))
        else:
            self._jit = execu.jit(
                self._impl,
                in_specs=(ex.PARAMS, ex.STATE, ex.SLOTS) + (ex.BATCH,) * 8,
                out_specs=(ex.BATCH, ex.BATCH, ex.BATCH, ex.SLOTS),
                donate_argnums=(2,))

    # ------------------------------------------------------------- program
    def _impl(self, params, state, dstate, tokens, draft, pos0, n_in,
              reset, seeds, temps, topk, btab=None):
        """ONE verify for all S slots. ``tokens`` (S, k): the window fed
        to the target (``tok0`` then the first k-1 proposals); ``draft``
        (S, k): all k proposals to judge; ``n_in`` (S,): valid window
        length (0 = inert row). Returns ``(oracle, accepted, emitted,
        new_dstate)`` — oracle masked to the emitted prefix."""
        from deeplearning4j_tpu.exec.programs import is_registering
        if not is_registering():
            self.programs += 1
        params = dequantize_tree(params)
        S, K = self.slots, self.k
        tmap = (jax.tree_util.tree_map if btab is None else map_slot_leaves)

        def wipe(a):
            r = reset.reshape((S,) + (1,) * (a.ndim - 1))
            return jnp.where(r, jnp.zeros_like(a), a)

        # a fresh slot's first target-model call may be a verify (e.g. a
        # one-token prompt): the reset wipe lives here like in the step
        dstate = tmap(wipe, dstate)
        x = jax.nn.one_hot(tokens, self.vocab, dtype=jnp.float32)
        y, new_d, stacks = self.model.prefill_chunk(
            params, state, dstate, x, pos0, n_in, block_tables=btab,
            carry_stack=True)
        # the target's own emission at every window position, under the
        # request's fold_in(seed, position) rule — identical by
        # construction to what the non-speculative step would sample
        oracle = jnp.stack(
            [oracle_tokens(jnp.log(y[:, t]), seeds, pos0 + t, temps, topk)
             for t in range(K)], axis=1)
        accepted, emitted = accept_length(oracle, draft, n_in)
        rows = jnp.arange(S)
        idx = jnp.clip(emitted - 1, 0, K - 1)
        merged = rewound_state(self.model, new_d, stacks, idx, rows)
        live = n_in > 0

        def freeze(new, old):
            m = live.reshape((S,) + (1,) * (new.ndim - 1))
            return jnp.where(m, new, old)

        merged = tmap(freeze, merged, dstate)
        oracle = jnp.where(jnp.arange(K)[None, :] < emitted[:, None],
                           oracle, 0).astype(jnp.int32)
        return oracle, accepted, emitted, merged

    def _impl_paged(self, params, state, dstate, btab, tokens, draft,
                    pos0, n_in, reset, seeds, temps, topk):
        """Paged verify: page table right after the donated state (same
        argument discipline as the paged step program)."""
        return self._impl(params, state, dstate, tokens, draft, pos0,
                          n_in, reset, seeds, temps, topk, btab=btab)

    # ---------------------------------------------------------------- host
    def run(self, params, state, dstate, *args):
        """Run one verify; returns (oracle, accepted, emitted) as numpy
        plus the new donated state tree."""
        c0, t0 = self.programs, time.perf_counter()
        oracle, accepted, emitted, new_d = self._jit(params, state, dstate,
                                                     *args)
        out = (np.asarray(oracle), np.asarray(accepted),
               np.asarray(emitted))
        if self.programs > c0:
            from deeplearning4j_tpu.exec.programs import get_programs
            get_programs().record(
                self.owner, "verify", self._jit,
                (params, state, new_d) + tuple(args),
                compile_seconds=time.perf_counter() - t0)
        return out + (new_d,)
