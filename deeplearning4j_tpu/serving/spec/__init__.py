"""Speculative decoding subsystem: draft/verify serving acceleration.

Autoregressive decode is latency-bound, not compute-bound: every token
costs one full forward of the target model, and the accelerator idles on
weight bandwidth while the host round-trips. Speculative decoding
(Leviathan et al. 2023; Chen et al. 2023) breaks the one-token-per-
forward barrier: a tiny DRAFT model proposes k tokens per tick, the
target VERIFIES all k in one batched multi-position step (k positions
through one program costs barely more than one), and an acceptance rule
keeps the emitted stream exactly the target's own distribution — here
in its strongest form: bitwise-identical to the non-speculative engine
for greedy AND seeded temperature sampling, because draft, verify and
the plain step all share one sampling oracle (accept.py).

Wiring (``DecodeEngine(spec=SpecConfig(draft_model, k))``):

- ``accept.py`` — ``oracle_token`` (the engine sampling rule, also used
  by the non-speculative step and ``generate_naive``) and
  ``accept_length`` (leading-match acceptance + correction token).
- ``draft.py``  — slot-aligned k-step draft scan, one donated compiled
  program, carry snapshot stacks for rewind, optional int8/fp8 weights.
- ``verify.py`` — one batched target step over each slot's k-token
  window through the chunked-prefill write path; rejected positions are
  causally masked until overwritten, carries roll back via snapshots.
- ``rewind.py`` — carry-vs-positional state classification and rollback
  (``Layer.positional_state_keys``).

Scheduling stays data-not-shapes: per tick the engine issues at most one
draft call, one (prefill) step and one verify, each a fixed-(S, k) shape
program compiled exactly once regardless of arrival schedule — the same
trace-count pins the plain decode path enforces. See docs/DECODING.md
"Speculative decoding".
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from deeplearning4j_tpu.serving.spec.accept import (accept_length,
                                                    oracle_token,
                                                    oracle_tokens)
from deeplearning4j_tpu.serving.spec.draft import DraftEngine
from deeplearning4j_tpu.serving.spec.verify import SpecVerifier


@dataclass
class SpecConfig:
    """Speculative decoding knobs for ``DecodeEngine(spec=...)``.

    ``draft_model``: a model container (MultiLayerNetwork /
    ComputationGraph) implementing the incremental-decode protocol over
    the SAME vocabulary as the target. ``k``: tokens proposed per tick —
    tuning table in docs/DECODING.md. ``draft_precision``: quantize the
    draft weights (``"int8"``/``"fp8"``; None = f32)."""

    draft_model: Any
    k: int = 4
    draft_precision: Optional[str] = None


__all__ = ["SpecConfig", "DraftEngine", "SpecVerifier", "accept_length",
           "oracle_token", "oracle_tokens"]
