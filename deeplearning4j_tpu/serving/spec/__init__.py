"""Speculative decoding subsystem: tree draft/verify serving acceleration.

Autoregressive decode is latency-bound, not compute-bound: every token
costs one full forward of the target model, and the accelerator idles on
weight bandwidth while the host round-trips. Speculative decoding
(Leviathan et al. 2023; Chen et al. 2023) breaks the one-token-per-
forward barrier: a DRAFT proposes tokens, the target VERIFIES them all
in one batched multi-position step, and an acceptance rule keeps the
emitted stream exactly the target's own distribution — here in its
strongest form: bitwise-identical to the non-speculative engine for
greedy AND seeded temperature sampling, because draft, verify and the
plain step all share one sampling oracle (accept.py).

Two upgrades over the linear subsystem this grew from (PR 14 shape):

- TREE speculation (Medusa / SpecInfer): the draft proposes a static
  token tree per slot (``tree.py``) — its own trajectory as the spine
  plus top-logit alternatives as siblings — and ONE verify scores every
  node under an ancestry mask, so one early mismatch no longer discards
  the whole tail. A linear draft is the ``(1,) * k`` tree; one code
  path serves both.
- SELF-drafting (``selfdraft.py``): the draft reuses the target's own
  weights (int8/fp8 quantized, or an early-exit truncated stack) —
  speculation with zero extra checkpoints.

Wiring (``DecodeEngine(spec=SpecConfig(...))``):

- ``accept.py``    — ``oracle_token`` (the engine sampling rule, also
  used by the non-speculative step and ``generate_naive``) and
  ``accept_length`` (the linear acceptance rule, kept as the host-side
  reference the tree walk degenerates to).
- ``tree.py``      — static tree shapes: flattened node list, parent/
  depth/ancestor tables, the in-program acceptance walk.
- ``draft.py``     — slot-aligned draft scan (spine + side proposals),
  one donated compiled program, carry snapshot stacks for rewind,
  optional int8/fp8 weights.
- ``verify.py``    — one batched target step over each slot's node
  tree; rejected nodes are never written, accepted paths commit inside
  the same program, carries roll back via node snapshots.
- ``rewind.py``    — carry-vs-positional state classification and
  rollback (``Layer.positional_state_keys``).
- ``selfdraft.py`` — the target as its own draft.

Scheduling stays data-not-shapes: per tick the engine issues at most one
draft call, one (prefill) step and one verify, each a fixed-shape
program compiled exactly once regardless of arrival schedule — the same
trace-count pins the plain decode path enforces. See docs/DECODING.md
"Tree speculation & self-drafting".
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

from deeplearning4j_tpu.serving.spec.accept import (accept_length,
                                                    oracle_token,
                                                    oracle_tokens)
from deeplearning4j_tpu.serving.spec.draft import DraftEngine
from deeplearning4j_tpu.serving.spec.tree import TreeSpec, parse_kvec
from deeplearning4j_tpu.serving.spec.verify import SpecVerifier


@dataclass
class SpecConfig:
    """Speculative decoding knobs for ``DecodeEngine(spec=...)``.

    ``draft_model``: a model container (MultiLayerNetwork /
    ComputationGraph) implementing the incremental-decode protocol over
    the SAME vocabulary as the target — or None with ``self_draft`` set.
    ``k``: spine length of the default linear tree (ignored when
    ``tree`` is given). ``tree``: branching factors per depth, e.g.
    ``(3, 2, 2)`` — tuning table in docs/DECODING.md. ``self_draft``:
    ``"int8"`` / ``"fp8"`` (the target as its own quantized draft) or
    ``"early_exit:M"`` (first M layers + shared readout) — see
    spec/selfdraft.py. ``draft_precision``: quantize the draft weights
    (``"int8"``/``"fp8"``; None = f32)."""

    draft_model: Any = None
    k: int = 4
    tree: Optional[Tuple[int, ...]] = None
    self_draft: Optional[str] = None
    draft_precision: Optional[str] = None

    def kvec(self) -> Tuple[int, ...]:
        """The effective tree shape: ``tree`` or the linear ``(1,)*k``."""
        if self.tree is not None:
            return tuple(int(v) for v in self.tree)
        return (1,) * int(self.k)


__all__ = ["SpecConfig", "DraftEngine", "SpecVerifier", "TreeSpec",
           "parse_kvec", "accept_length", "oracle_token", "oracle_tokens"]
