"""Static token-tree shapes for tree speculation (Medusa / SpecInfer).

A linear k-token draft discards its whole tail at the first mismatch.
A token TREE hedges: at every depth the draft proposes its best guess
(the SPINE — its own autoregressive trajectory) plus the next-best
alternatives as siblings, and ONE batched verify scores every node; the
longest root-path whose tokens match the sampling oracle advances. The
shape is fixed at trace time so the verify program compiles exactly
once regardless of acceptance history (the same data-not-shapes
discipline as the rest of the engine).

The shape here is the *caterpillar* tree ``kvec = (k_1, .., k_D)``: the
spine node at depth d-1 gets ``k_d`` children — the spine continuation
(the draft's own sampled token, always child 0 of its depth group) and
``k_d - 1`` top-logit alternatives with the spine token masked out, so
siblings are distinct and at most one can match the oracle. Side nodes
have no children (a side acceptance ends the path but still banks the
token plus the oracle's bonus). Node count is ``1 + sum(kvec)``; a
linear draft is exactly ``kvec = (1,) * k``, so one code path serves
both and the PR-14 linear semantics are the degenerate tree.

Everything static lives in numpy on the host (``parent``/``depth``/
``anc_at_depth`` index tables baked into the trace); the acceptance
walk (``walk``) is pure jnp and runs INSIDE the verify program.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def parse_kvec(text):
    """``"3,2,2"`` → ``(3, 2, 2)`` (the replica flag format)."""
    kvec = tuple(int(p) for p in str(text).split(",") if p.strip())
    if not kvec:
        raise ValueError(f"empty tree spec {text!r}")
    return kvec


class TreeSpec:
    """Immutable flattened token tree for one engine.

    Node 0 is the root (the last emitted token, depth 0). Depth-d nodes
    occupy the contiguous index range ``first[d-1] .. first[d-1]+k_d-1``
    with the spine child FIRST; every depth-d node's parent is the
    depth-(d-1) spine node. Tables (all static numpy, shapes fixed by
    ``kvec`` alone):

    - ``parent``       (N,)    parent node index, -1 for the root
    - ``depth``        (N,)    node depth, 0..D
    - ``spine``        (D+1,)  spine node index per depth
    - ``first``        (D,)    first node index of each depth group
    - ``anc_at_depth`` (N, D+1) ancestor-or-self of node n at depth dd
      (for dd > depth[n] the entry saturates to n — callers mask on
      ``dd <= depth[n]``). Row n IS node n's root-path, which is how the
      verify attention builds each node's effective causal cache.
    """

    def __init__(self, kvec):
        kvec = tuple(int(k) for k in kvec)
        if not kvec or any(k < 1 for k in kvec):
            raise ValueError(
                f"tree kvec must be positive ints per depth, got {kvec}")
        self.kvec = kvec
        self.d = len(kvec)                       # spine length
        self.n_nodes = 1 + sum(kvec)
        parent, depth, spine, first = [-1], [0], [0], []
        nid = 1
        for dd, k in enumerate(kvec, start=1):
            first.append(nid)
            for _ in range(k):
                parent.append(spine[dd - 1])
                depth.append(dd)
            spine.append(nid)                    # spine = first child
            nid += k
        self.parent = np.asarray(parent, np.int32)
        self.depth = np.asarray(depth, np.int32)
        self.spine = np.asarray(spine, np.int32)
        self.first = np.asarray(first, np.int32)
        aad = np.zeros((self.n_nodes, self.d + 1), np.int32)
        for n in range(self.n_nodes):
            chain, cur = [], n
            while cur >= 0:
                chain.append(cur)
                cur = int(self.parent[cur])
            chain = chain[::-1]                  # root .. n
            aad[n, :len(chain)] = chain
            aad[n, len(chain):] = n              # saturate past own depth
        self.anc_at_depth = aad

    def ancestor_matrix(self):
        """(N, N) bool — ``anc[i, j]`` iff node j is on node i's
        root-path (ancestor-or-self): the causal tree-mask in matrix
        form (docs/DECODING.md "Tree speculation")."""
        N = self.n_nodes
        anc = np.zeros((N, N), bool)
        for i in range(N):
            anc[i, self.anc_at_depth[i, :self.depth[i] + 1]] = True
        return anc

    # ------------------------------------------------------ acceptance walk
    def walk(self, node_tokens, oracle, n_in):
        """Longest accepted root-path, vectorized over slots, traced into
        the verify program (static loop over depths).

        ``node_tokens``/``oracle``: (S, N) — each node's drafted token
        and the oracle token sampled from the target's distribution AT
        that node. ``n_in``: (S,) emit budget (0 = inert row). A depth-d
        node extends the path iff the path sits at the depth-(d-1) spine
        node (side nodes are leaves) and the node's token equals the
        oracle token of the path node above it — the same sample-match
        rule as linear acceptance, over branches instead of a chain.

        Returns ``(a, emitted, spine_acc, path)``:

        - ``a``        (S,) accepted depth, already capped at n_in - 1
        - ``emitted``  (S,) tokens to emit = a + 1 (0 for inert rows)
        - ``spine_acc`` (S,) longest accepted prefix that followed the
          draft's OWN spine — the draft's carry snapshots are consistent
          exactly that far (decode.py resyncs the draft past it)
        - ``path``     (S, D+1) node index of the path at each depth
          (saturates at the deepest accepted node; entries past ``a``
          are masked by every consumer)
        """
        S = node_tokens.shape[0]
        cur = jnp.zeros(S, jnp.int32)
        a = jnp.zeros(S, jnp.int32)
        ok = jnp.ones(S, bool)
        on_spine = jnp.ones(S, bool)
        spine_acc = jnp.zeros(S, jnp.int32)
        path = [cur]
        for dd in range(1, self.d + 1):
            f, kd = int(self.first[dd - 1]), self.kvec[dd - 1]
            want = jnp.take_along_axis(oracle, cur[:, None], axis=1)[:, 0]
            toks = node_tokens[:, f:f + kd]              # (S, k_d) static
            m = toks == want[:, None]
            hit = (m.any(axis=1) & ok
                   & (cur == int(self.spine[dd - 1]))
                   & (dd < n_in))                        # emit budget cap
            child = (f + jnp.argmax(m, axis=1)).astype(jnp.int32)
            cur = jnp.where(hit, child, cur)
            a = a + hit
            on_spine = on_spine & hit & (child == int(self.spine[dd]))
            spine_acc = spine_acc + on_spine
            ok = ok & hit
            path.append(cur)
        live = n_in > 0
        emitted = jnp.where(live, a + 1, 0).astype(jnp.int32)
        return (a.astype(jnp.int32), emitted, spine_acc.astype(jnp.int32),
                jnp.stack(path, axis=1))


__all__ = ["TreeSpec", "parse_kvec"]
