"""Draft half of the speculative decoder: a small model proposing a
token TREE per scheduler tick through ONE compiled, donated program.

The draft engine is slot-aligned with its owning ``DecodeEngine``: slot i
of the draft state tree shadows slot i of the target engine, and every
scheduling decision rides in as (S,)-shaped data (``n_steps`` masks,
never shapes) so the program compiles exactly once — the same
trace-count discipline the target step program pins.

One call runs a length-``k`` ``lax.scan`` of the draft model's
``decode_step``: position t consumes ``given[:, t]`` while t < n_given
(the correction/prompt tokens the host supplies) and the draft's own
previous proposal after that, and proposes via the SAME sampling oracle
as the target (serving/spec/accept.py) — under temperature sampling the
shared ``fold_in(seed, position)`` key couples the draft's categorical
draw to the target's (Gumbel-max with shared noise), which is what makes
a good draft's proposals match the target oracle far more often than an
independent draw would. The oracle proposals form the tree's SPINE; when
the engine runs a branching tree (``side_k > 0``) each position also
emits its ``side_k`` best alternatives (the spine token's logit masked
out, so siblings are distinct) — these fill the tree's side branches
(serving/spec/tree.py), all inside the same scan, same single program.

Rewind: recurrent carries are snapshotted after every scan position into
(S, k, ...) stacks held INSIDE the donated tree; the next call resumes
from stack index ``sel`` (host-computed from the verify's spine-
consistent prefix — see decode.py ``_tick_spec``). Positional leaves
(attention KV, always dense here) stay in place and are overwritten next
tick before the causal mask can read them (serving/spec/rewind.py).
"""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.quant import (dequantize_tree, record_weight_bytes,
                                      resolve_precision, tree_bytes)
from deeplearning4j_tpu.serving.spec.accept import oracle_tokens
from deeplearning4j_tpu.serving.spec.rewind import map_state


class DraftEngine:
    """Tree-draft proposer for one DecodeEngine (``owner`` = its id).

    ``k``: scan positions per call (tree spine depth + 1 — the extra
    position keeps a snapshot live for the fully-accepted case);
    ``side_k``: alternatives proposed per position (0 = pure linear
    drafting). ``precision`` quantizes the draft weights through the
    same policy as serving weights (docs/QUANTIZATION.md): int8/fp8
    drafts stream from HBM at quantized width — the draft step is tiny
    and bandwidth-bound, so this is nearly free acceptance-rate-per-
    second. With the TARGET model itself as ``model`` this is
    self-drafting (spec/selfdraft.py): quantization makes the draft
    cheaper than the target while agreeing with it almost always.
    """

    def __init__(self, model, owner, slots, max_len, k, vocab,
                 precision=None, side_k=0):
        self.model = model
        self.owner = owner
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.k = int(k)
        self.side_k = int(side_k)
        self.vocab = int(vocab)
        self.programs = 0            # exact XLA trace count (pin: 1)
        self.precision = (resolve_precision(precision)
                          if precision is not None else "f32")
        from deeplearning4j_tpu import exec as ex
        execu = getattr(model, "_executor", None) or ex.get_executor()
        self._live = None
        if self.precision != "f32":
            qp = execu.prepare_params(model.params, self.precision)
            st = jax.tree_util.tree_map(jnp.asarray, model.state)
            self._live = (qp, st)
            record_weight_bytes(f"{owner}-draft", self.precision,
                                tree_bytes(qp))
        self._tree = None
        self._run = execu.jit(
            self._impl,
            in_specs=(ex.PARAMS, ex.STATE, ex.SLOTS) + (ex.BATCH,) * 9,
            out_specs=(ex.BATCH, ex.BATCH, ex.SLOTS),
            donate_argnums=(2,))

    def _weights(self):
        if self._live is not None:
            return self._live
        return self.model.params, self.model.state

    def ensure_state(self):
        """Donated draft tree: the model's dense decode state with every
        carry leaf widened to a (S, k, ...) snapshot stack (index = carry
        after scan position t); positional leaves keep their cache shape."""
        if self._tree is None:
            base = self.model.init_decode_state(self.slots, self.max_len)
            self._tree = map_state(
                self.model, base,
                on_carry=lambda a: jnp.zeros(
                    (a.shape[0], self.k) + a.shape[1:], a.dtype),
                on_positional=lambda a: a)

    # ------------------------------------------------------------- program
    def _impl(self, params, state, tree, given, n_given, n_steps, pos0,
              sel, reset, seeds, temps, topk):
        """ONE draft tick for all S slots: slot i resumes its carries from
        snapshot ``sel[i]``, consumes ``given[i, :n_given[i]]`` then its
        own proposals, runs ``n_steps[i]`` scan positions (0 = inert,
        state bit-frozen) at positions ``pos0[i] + t``, and returns the
        (S, k) spine proposals, the (S, k, side_k) per-position
        alternatives, and the re-stacked donated tree."""
        from deeplearning4j_tpu.exec.programs import is_registering
        if not is_registering():
            self.programs += 1
        params = dequantize_tree(params)
        S, K = self.slots, self.k
        rows = jnp.arange(S)

        def wipe(a):
            r = reset.reshape((S,) + (1,) * (a.ndim - 1))
            return jnp.where(r, jnp.zeros_like(a), a)

        # fresh slots wipe INSIDE the program (same rule as the target
        # step): stacks and caches go to zero, sel=0 resumes a zero carry
        tree0 = jax.tree_util.tree_map(wipe, tree)
        d0 = map_state(self.model, tree0,
                       on_carry=lambda a: a[rows, sel],
                       on_positional=lambda a: a)

        def body(carry, t):
            d, prev = carry
            tok = jnp.where(t < n_given, given[:, t], prev).astype(jnp.int32)
            x = jax.nn.one_hot(tok, self.vocab, dtype=jnp.float32)[:, None, :]
            y, nd = self.model.decode_step(params, state, d, x, pos0 + t)
            logits = jnp.log(y[:, 0, :])
            prop = oracle_tokens(logits, seeds, pos0 + t, temps, topk)
            live = t < n_steps

            def keep(new, old):
                m = live.reshape((S,) + (1,) * (new.ndim - 1))
                return jnp.where(m, new, old)

            nd = jax.tree_util.tree_map(keep, nd, d)
            prop = jnp.where(live, prop, 0).astype(jnp.int32)
            if self.side_k > 0:
                # side branches: best alternatives with the spine token
                # masked to -inf, so siblings are pairwise distinct and
                # at most one tree child can ever match the oracle
                masked = logits.at[rows, prop].set(-jnp.inf)
                side = jax.lax.top_k(masked, self.side_k)[1]
                side = jnp.where(live[:, None], side, 0).astype(jnp.int32)
            else:
                side = jnp.zeros((S, 0), jnp.int32)
            # snapshot the carries only; positional caches would stack to
            # k full copies — a scalar dummy keeps the pytree constant
            snap = map_state(self.model, nd,
                             on_carry=lambda a: a,
                             on_positional=lambda a: jnp.zeros((), a.dtype))
            return (nd, prop), (prop, side, snap)

        prev0 = jnp.zeros(S, jnp.int32)
        (d, _), (props, sides, snaps) = jax.lax.scan(body, (d0, prev0),
                                                     jnp.arange(K))
        # donated tree out: carries re-stacked from the (K, S, ...) scan
        # snapshots, positional caches from the final scan state
        new_tree = map_state(self.model, snaps,
                             on_carry=lambda s, f: jnp.moveaxis(s, 0, 1),
                             on_positional=lambda s, f: f,
                             rest=(d,))
        live = n_steps > 0

        def freeze(new, old):
            m = live.reshape((S,) + (1,) * (new.ndim - 1))
            return jnp.where(m, new, old)

        # inert slots stay bit-identical (their stacks are NOT re-stacked
        # with repeated carries — frozen against the pre-scan tree)
        new_tree = jax.tree_util.tree_map(freeze, new_tree, tree0)
        return (jnp.moveaxis(props, 0, 1), jnp.moveaxis(sides, 0, 1),
                new_tree)

    # ---------------------------------------------------------------- host
    def step(self, given, n_given, n_steps, pos0, sel, reset, seeds,
             temps, topk):
        """Run one draft tick; returns the (S, k) spine proposals and the
        (S, k, side_k) alternatives as numpy."""
        self.ensure_state()
        params, state = self._weights()
        c0, t0 = self.programs, time.perf_counter()
        props, sides, self._tree = self._run(params, state, self._tree,
                                             given, n_given, n_steps, pos0,
                                             sel, reset, seeds, temps, topk)
        props, sides = np.asarray(props), np.asarray(sides)
        if self.programs > c0:
            from deeplearning4j_tpu.exec.programs import get_programs
            get_programs().record(
                self.owner, "draft", self._run,
                (params, state, self._tree, given, n_given, n_steps, pos0,
                 sel, reset, seeds, temps, topk),
                compile_seconds=time.perf_counter() - t0)
        return props, sides
