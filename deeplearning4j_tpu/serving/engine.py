"""Shape-bucketed inference execution.

The naive path jits one forward per EXACT batch shape, so a traffic mix of
request sizes pays one fresh XLA compile per distinct size — 20-120 s per
program on tunneled TPU attachments (util/compile_cache.py). The engine
instead pads every batch up to a small power-of-two ladder of bucket sizes:
⌈log2(max_batch)⌉+1 compiled programs cover every request size from 1 to
max_batch, and anything larger is chunked through the top bucket.

Padding is numerics-neutral for inference: ``output()`` runs train=False, so
every op the containers emit (dense/conv matmuls, pooling, BN with running
stats, per-row softmax, per-example LSTM scan) computes row i of the output
from row i of the input alone — pad rows are dead weight that is sliced off
after the device call, and the engine's test suite pins the bucketed result
bitwise-equal to the exact-shape forward. (Train-mode batch statistics WOULD
couple rows; the engine is inference-only for exactly that reason.)

``warmup()`` pre-executes the ladder through the persistent compilation
cache (util/compile_cache.py), so a fresh server process — whose in-process
jit cache starts empty — serves its first request with ~0 compile time.

Trace accounting: the traced python body increments ``trace_count`` exactly
once per new XLA program signature, giving tests and /stats an exact
compiled-program count with no XLA internals involved.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.monitor import get_registry, trace
from deeplearning4j_tpu.quant import (dequantize_tree, record_weight_bytes,
                                      resolve_precision, tree_bytes)
from deeplearning4j_tpu.resilience.errors import WeightSwapError


def _tree_signature(tree):
    """Flattened ``{path: (shape, dtype)}`` of a pytree — the swap
    compatibility key. Same path convention as util/model_serializer."""
    sig = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        arr = leaf if hasattr(leaf, "shape") else np.asarray(leaf)
        sig[key] = (tuple(arr.shape), str(arr.dtype))
    return sig


def validate_swap(current, candidate, what: str = "params") -> None:
    """Reject a hot-swap candidate whose pytree does not match the live
    weights array-for-array (path set, shapes, dtypes). Raising HERE — before
    any engine state is touched — is what makes a rejected swap a no-op; a
    mismatch that slipped through would either retrace a fresh XLA program
    (shape/dtype change) or crash a device call mid-request."""
    _validate_sig(_tree_signature(current), _tree_signature(candidate), what)


def _validate_sig(cur, new, what: str = "params") -> None:
    """Signature-level half of ``validate_swap``: quantizing engines keep
    the ORIGINAL f32 signature and validate swap candidates against it
    (candidates always arrive in f32 — quantization happens after the
    gate, so the quantized shapes/dtypes match the live program's and the
    jit cache still hits)."""
    problems = []
    for key in sorted(set(cur) - set(new)):
        problems.append(f"missing array {key!r}")
    for key in sorted(set(new) - set(cur)):
        problems.append(f"unexpected array {key!r}")
    for key in sorted(set(cur) & set(new)):
        if cur[key] != new[key]:
            problems.append(
                f"{key!r} expected {cur[key][0]}/{cur[key][1]}, "
                f"got {new[key][0]}/{new[key][1]}")
    if problems:
        raise WeightSwapError(
            f"candidate {what} incompatible with live weights", problems)


def bucket_for(n: int, max_batch: int, min_bucket: int = 1,
               ladder: Optional[Sequence[int]] = None) -> int:
    """Smallest rung ≥ n. Default rungs are the power-of-two ladder; an
    explicit ``ladder`` (sorted ascending, topped by max_batch — the
    autotuned ladders ``autotune_ladder`` produces) overrides it."""
    if n < 1:
        raise ValueError(f"batch size must be ≥ 1, got {n}")
    if ladder:
        for b in ladder:
            if b >= n:
                return b
        return ladder[-1]
    b = max(min_bucket, 1)
    while b < n:
        b <<= 1
    return min(b, max_batch)


def bucket_ladder(max_batch: int, min_bucket: int = 1) -> List[int]:
    """The full ladder [min_bucket, 2·min_bucket, ..., max_batch]."""
    out = []
    b = max(min_bucket, 1)
    while b < max_batch:
        out.append(b)
        b <<= 1
    out.append(max_batch)
    return out


def autotune_ladder(counts, max_batch: int, max_rungs: Optional[int] = None,
                    min_bucket: int = 1) -> List[int]:
    """Choose bucket rungs from MEASURED traffic instead of blind powers
    of two.

    ``counts`` maps observed batch size -> request count (the engine's
    per-size histogram). Candidate rungs are the observed sizes plus the
    pow2 rungs; a DP picks at most ``max_rungs`` of them (default: the
    pow2 ladder's length) minimizing total padding rows, with
    ``max_batch`` always kept as the top rung so oversize chunking still
    works. The pow2 ladder itself is a feasible choice, so the optimum
    NEVER pads more than pow2 does, with never more rungs (= compiled
    programs) — the two acceptance bars the bench row asserts.
    """
    pow2 = bucket_ladder(max_batch, min_bucket)
    K = int(max_rungs) if max_rungs else len(pow2)
    lo = max(min_bucket, 1)
    # sizes above max_batch arrive pre-chunked (the dispatch recursion
    # re-buckets tails), below min_bucket they pad up to it
    sizes = {}
    for s, c in dict(counts).items():
        s = min(max(int(s), lo), max_batch)
        sizes[s] = sizes.get(s, 0) + int(c)
    if not sizes:
        return pow2
    cand = sorted(set(sizes) | set(pow2) | {max_batch})
    cand = [c for c in cand if lo <= c <= max_batch]

    def seg_cost(i: int, j: int) -> float:
        """Pad rows when sizes in (cand[i], cand[j]] all round to cand[j]."""
        lo_v = cand[i] if i >= 0 else 0
        r = cand[j]
        return float(sum(c * (r - s) for s, c in sizes.items()
                         if lo_v < s <= r))

    p = len(cand)
    INF = float("inf")
    dp = [[INF] * (K + 1) for _ in range(p)]
    back = [[None] * (K + 1) for _ in range(p)]
    for j in range(p):
        dp[j][1] = seg_cost(-1, j)
        for k in range(2, K + 1):
            for i in range(j):
                if dp[i][k - 1] == INF:
                    continue
                v = dp[i][k - 1] + seg_cost(i, j)
                if v < dp[j][k]:
                    dp[j][k] = v
                    back[j][k] = i
    top = p - 1                              # cand[top] == max_batch
    best_k = min(range(1, K + 1), key=lambda k: (dp[top][k], k))
    rungs, j, k = [cand[top]], top, best_k
    while k > 1 and back[j][k] is not None:
        j = back[j][k]
        k -= 1
        rungs.append(cand[j])
    return sorted(rungs)


def prune_ladder(ladder: Sequence[int], counts, rung_costs) -> List[int]:
    """Drop rungs whose measured one-time compile cost exceeds the padding
    run-time they save on the observed traffic.

    ``rung_costs`` maps rung -> {"compile_s", "run_s"} as recorded by
    ``warmup()``. A rung saves (next_rung - rung) pad rows per request it
    absorbs; valued at the rung's measured per-row run time, if that
    saving is worth less wall-clock than the rung's compile, the rung is
    merged upward. The top rung is never dropped. This trades pad-waste
    back for compiles, so it is opt-in (``autotune(prune=True)``)."""
    ladder = sorted(ladder)
    sizes = {int(s): int(c) for s, c in dict(counts).items()}
    changed = True
    while changed and len(ladder) > 1:
        changed = False
        for idx in range(len(ladder) - 1):
            r, nxt = ladder[idx], ladder[idx + 1]
            cost = rung_costs.get(r, {})
            compile_s, run_s = cost.get("compile_s"), cost.get("run_s")
            if compile_s is None or run_s is None or run_s <= 0:
                continue
            lo = ladder[idx - 1] if idx > 0 else 0
            absorbed = sum(c for s, c in sizes.items() if lo < s <= r)
            extra_run_s = absorbed * (nxt - r) * (run_s / max(r, 1))
            if extra_run_s < compile_s:
                ladder.pop(idx)
                changed = True
                break
    return ladder


class InferenceEngine:
    """Bucketed inference over a model container.

    ``model`` is a MultiLayerNetwork or ComputationGraph (anything with
    ``params``/``state``/``_forward`` and the container conf surface).
    Parameters are read from the model at call time, so the engine stays
    valid across further ``fit()`` calls — only the program structure is
    cached, never the weights.

    ``swap_weights`` hot-swaps the serving weights for a same-shape pytree
    (the online-learning deploy path, docs/ONLINE_LEARNING.md): after the
    first swap the engine serves its own pinned ``(params, state)`` pair
    instead of reading the model, so a trainer mutating the model can no
    longer affect serving. Identical shapes/dtypes mean the jit cache hits —
    a swap performs ZERO new XLA compiles by construction (the regression
    tests pin ``trace_count`` across swaps).
    """

    _ids = itertools.count()

    def __init__(self, model, max_batch: int = 1024, min_bucket: int = 1,
                 precision: Optional[str] = None):
        from deeplearning4j_tpu import exec as ex
        self.model = model
        self.max_batch = int(max_batch)
        self.min_bucket = int(min_bucket)
        self._traced_keys = set()
        self._fwd = None
        # AOT-restored executables by (bucket, has_mask) — consulted by
        # _dispatch before the traced path (exec/aot.py; filled by
        # ``warmup(aot=...)``). Restores never touch trace_count.
        self._aot: dict = {}
        self._lock = threading.Lock()
        self._live = None          # (params, state) after the first swap
        self._version = 0
        self._is_graph = hasattr(model.conf, "network_inputs")
        self.warmup_seconds: Optional[float] = None
        # measurement-driven ladder state: per-size traffic histogram
        # (fed by live dispatches, read by ``autotune``), per-rung
        # compile/run costs (recorded by ``warmup``), and the active
        # ladder (None = the pow2 default)
        self.ladder: Optional[List[int]] = None
        self.rung_costs: dict = {}
        self._size_counts: dict = {}
        self._in_warmup = False
        # serving precision: explicit arg > the executor's declarative
        # policy (Executor(precision=...) / DL4JTPU_PRECISION). For
        # int8/fp8 the engine pins the quantized weights at construction
        # and keeps the f32 signature for swap validation — candidates
        # arrive in f32 and are quantized AFTER the gate, so the
        # quantized shapes/dtypes never change and swaps stay
        # zero-new-compiles (docs/QUANTIZATION.md).
        execu = getattr(model, "_executor", None) or ex.get_executor()
        self.precision = (resolve_precision(precision)
                          if precision is not None else execu.precision)
        self._raw_sig = None
        if self.precision != "f32":
            self._raw_sig = _tree_signature(model.params)
            qp = execu.prepare_params(model.params, self.precision)
            st = jax.tree_util.tree_map(jnp.asarray, model.state)
            self._live = (qp, st)
        # registry-backed counters: /stats and /metrics read the SAME cells
        self.id = f"engine{next(InferenceEngine._ids)}"
        reg = get_registry()
        lab = {"engine": self.id}
        self._m_compiled = reg.counter(
            "dl4jtpu_serving_compiled_programs_total",
            "XLA programs traced by the inference engine (one per bucket "
            "shape signature).", ("engine",)).labels(**lab)
        self._m_rows = reg.counter(
            "dl4jtpu_serving_batch_rows_total",
            "Real (un-padded) rows executed through bucketed device calls.",
            ("engine",)).labels(**lab)
        self._m_pad_rows = reg.counter(
            "dl4jtpu_serving_pad_rows_total",
            "Padding rows added to round batches up to bucket sizes "
            "(pad-waste = pad / (pad + rows)).", ("engine",)).labels(**lab)
        self._m_version = reg.gauge(
            "dl4jtpu_model_version",
            "Version of the weights currently serving (0 = the model's "
            "initial weights; bumped by every hot swap).",
            ("engine",)).labels(**lab)
        self._m_swaps = reg.counter(
            "dl4jtpu_model_swaps_total",
            "Weight hot-swaps applied with zero new XLA compiles.",
            ("engine",)).labels(**lab)
        self._m_rungs = reg.gauge(
            "dl4jtpu_serving_bucket_rungs",
            "Rungs in the active bucket ladder (= compiled programs the "
            "ladder needs; drops when autotune merges rungs).",
            ("engine",)).labels(**lab)
        self._m_version.set(0.0)
        self._m_rungs.set(float(len(bucket_ladder(self.max_batch,
                                                  self.min_bucket))))
        if self.precision != "f32":
            record_weight_bytes(self.id, self.precision,
                                tree_bytes(self._live[0]))

    @property
    def trace_count(self) -> int:
        """Compiled-program count (reads the registry counter — the single
        source of truth shared with ``/metrics``)."""
        return int(self._m_compiled.value)

    @property
    def model_version(self) -> int:
        return self._version

    def _weights(self):
        """The live (params, state) pair: the engine's own swapped weights
        once a swap happened, the model's otherwise. Read under the lock so
        a concurrent swap can never tear params against state."""
        with self._lock:
            if self._live is not None:
                return self._live
        return self.model.params, self.model.state

    def swap_weights(self, params, state=None, version: Optional[int] = None):
        """Atomically replace the serving weights with a same-shape pytree.

        The candidate is validated (path set, shapes, dtypes) BEFORE any
        state changes — a mismatch raises ``WeightSwapError`` and leaves the
        engine untouched. In-flight ``predict`` calls already captured their
        weight references and finish on the old weights; subsequent
        dispatches see the new pair. Same shapes/dtypes → the cached jitted
        forward is reused, so a swap costs zero new XLA compiles. Returns
        the new model version (``version`` or previous + 1).

        Under int8/fp8 precision the candidate still arrives in f32 (the
        trainer/checkpoint format): it is validated against the ORIGINAL
        f32 signature, then quantized — same quantized shapes/dtypes as
        the live tree, so the zero-new-compiles invariant holds."""
        cur_p, cur_s = self._weights()
        if self._raw_sig is not None:
            _validate_sig(self._raw_sig, _tree_signature(params), "params")
        else:
            validate_swap(cur_p, params, "params")
        if state is not None:
            validate_swap(cur_s, state, "state")
        # device-resident once, at swap time — numpy trees fresh from a
        # checkpoint zip would otherwise pay a host→device copy per request
        params = jax.tree_util.tree_map(jnp.asarray, params)
        if self.precision != "f32":
            from deeplearning4j_tpu import exec as ex
            execu = getattr(self.model, "_executor", None) \
                or ex.get_executor()
            params = execu.prepare_params(params, self.precision)
            record_weight_bytes(self.id, self.precision, tree_bytes(params))
        state = (cur_s if state is None
                 else jax.tree_util.tree_map(jnp.asarray, state))
        with self._lock:
            self._live = (params, state)
            self._version = (int(version) if version is not None
                             else self._version + 1)
            v = self._version
        self._m_version.set(float(v))
        self._m_swaps.inc()
        return v

    # ------------------------------------------------------------- forward
    def _forward_fn(self):
        if self._fwd is not None:
            return self._fwd
        model = self.model

        # dequant-on-the-fly INSIDE the traced body: XLA fuses the
        # codes→f32 scale-multiply into the consuming matmuls, so the
        # weights live in HBM at int8/fp8 width and widen in registers.
        # On the f32 path ``dequantize_tree`` is the identity on every
        # leaf — the emitted program is byte-identical to before.
        if self._is_graph:
            def fwd(params, state, inputs, mask):
                self._note_trace(inputs, mask)
                params = dequantize_tree(params)
                acts, _, _ = model._forward(params, state, inputs,
                                            train=False, rng=None)
                return [acts[n] for n in model.conf.network_outputs]
        else:
            def fwd(params, state, inputs, mask):
                self._note_trace(inputs, mask)
                params = dequantize_tree(params)
                act, _, _ = model._forward(params, state, inputs[0],
                                           train=False, rng=None, mask=mask)
                return [act]

        from deeplearning4j_tpu import exec as ex
        execu = getattr(model, "_executor", None) or ex.get_executor()
        self._fwd = execu.jit(
            fwd, in_specs=(ex.PARAMS, ex.STATE, ex.BATCH, ex.BATCH),
            out_specs=(ex.BATCH,))
        return self._fwd

    def _note_trace(self, inputs, mask):
        # runs only while jit traces a NEW (shape, dtype, mask-presence)
        # signature — i.e. exactly once per compiled program. Registration
        # relowers the same body; that trace must not count twice.
        from deeplearning4j_tpu.exec.programs import is_registering
        if is_registering():
            return
        key = (tuple((tuple(x.shape), str(x.dtype)) for x in inputs),
               None if mask is None else (tuple(mask.shape), str(mask.dtype)))
        self._m_compiled.inc()
        self._traced_keys.add(key)

    # ------------------------------------------------------------- padding
    @staticmethod
    def _pad_rows(a, b: int):
        n = a.shape[0]
        if n == b:
            return a
        widths = [(0, b - n)] + [(0, 0)] * (a.ndim - 1)
        if isinstance(a, np.ndarray):
            return np.pad(a, widths)
        return jnp.pad(a, widths)

    def _dispatch(self, inputs: Sequence, mask=None, phases=None) -> List:
        """One bucketed device call: pad → run → slice. Returns the list of
        output device arrays (async — not yet host-read). Batches larger
        than ``max_batch`` are chunked through the top bucket.

        ``phases``: optional dict the call ACCUMULATES wall seconds into
        under ``bucket``/``pad``/``device`` keys — the per-batch phase
        attribution the micro-batcher's wide-event records carry
        (docs/OBSERVABILITY.md "Request lifecycle")."""
        n = inputs[0].shape[0]
        if n > self.max_batch:
            # each chunk recurses through THIS method, so the tail chunk
            # (n % max_batch rows) re-buckets via bucket_for(tail) instead
            # of padding to the full top bucket — its saved pad rows simply
            # never hit the pad-waste counter below
            pieces = [self._dispatch(
                [x[i:i + self.max_batch] for x in inputs],
                None if mask is None else mask[i:i + self.max_batch],
                phases=phases)
                for i in range(0, n, self.max_batch)]
            return [jnp.concatenate([p[j] for p in pieces])
                    for j in range(len(pieces[0]))]
        if not self._in_warmup:
            self._size_counts[n] = self._size_counts.get(n, 0) + 1
        tp = time.perf_counter()
        with trace.span("bucket", n=n):
            b = bucket_for(n, self.max_batch, self.min_bucket, self.ladder)
        if phases is not None:
            t = time.perf_counter()
            phases["bucket"] = phases.get("bucket", 0.0) + (t - tp)
            tp = t
        with trace.span("pad", bucket=b):
            padded = [self._pad_rows(x, b) for x in inputs]
            mask_p = None if mask is None else self._pad_rows(mask, b)
        if phases is not None:
            t = time.perf_counter()
            phases["pad"] = phases.get("pad", 0.0) + (t - tp)
            tp = t
        with trace.span("device", bucket=b):
            params, state = self._weights()
            prog = self._aot.get((b, mask_p is not None))
            if prog is not None:
                try:
                    outs = prog(params, state, padded, mask_p)
                except Exception:
                    # the restored executable was serialized under
                    # different shapes/dtypes than this call (e.g. a mask
                    # length the artifact never saw): drop the entry and
                    # retrace — correctness beats the fast path
                    self._aot.pop((b, mask_p is not None), None)
                    prog = None
            if prog is None:
                c0 = self.trace_count
                t0 = time.perf_counter()
                outs = self._forward_fn()(params, state, padded, mask_p)
        if prog is None and self.trace_count > c0:
            # a fresh program was traced: register its cost/memory analysis
            # (the relower hits the compile cache; guarded, off-hot-path)
            from deeplearning4j_tpu.exec.programs import get_programs
            key = f"b{b}" if mask_p is None else f"b{b}_mask"
            get_programs().record(
                self.id, key, self._fwd, (params, state, padded, mask_p),
                compile_seconds=time.perf_counter() - t0)
        if phases is not None:
            t = time.perf_counter()
            phases["device"] = phases.get("device", 0.0) + (t - tp)
        self._m_rows.inc(n)
        self._m_pad_rows.inc(b - n)
        return [o[:n] for o in outs]

    # ----------------------------------------------------------- public API
    def predict(self, x, mask=None, phases=None):
        """Bucketed forward. ``x``: one batch array, or a list of input
        arrays for multi-input graphs; returns device array(s) shaped like
        the model's own ``output()`` (slicing already applied). The call is
        async — reading the result to the host is the caller's sync point.
        ``phases``: optional dict accumulating bucket/pad/device wall
        seconds (see ``_dispatch``)."""
        single = not isinstance(x, (list, tuple))
        inputs = [jnp.asarray(x)] if single else [jnp.asarray(a) for a in x]
        if mask is not None:
            mask = jnp.asarray(mask)
        outs = self._dispatch(inputs, mask, phases=phases)
        if self._is_graph:
            return outs[0] if len(outs) == 1 else outs
        return outs[0]

    def predict_host(self, x, mask=None, phases=None):
        """``predict`` + host read; returns np.ndarray (or list of them).
        With ``phases``, the host read lands under ``readback``."""
        out = self.predict(x, mask, phases=phases)
        t0 = time.perf_counter() if phases is not None else 0.0
        with trace.span("readback"):
            if isinstance(out, list):
                out = [np.asarray(o) for o in out]
            else:
                out = np.asarray(out)
        if phases is not None:
            phases["readback"] = (phases.get("readback", 0.0)
                                  + (time.perf_counter() - t0))
        return out

    def predict_stream(self, batches, depth: int = 2):
        """Pipelined inference over an iterable of batches: keeps up to
        ``depth`` dispatches in flight so the device executes batch k+1
        while the host reads batch k's result (the role AsyncDataSetIterator
        prefetch plays on the input side). Yields host np arrays — one per
        input batch, in order; multi-output graphs yield lists."""
        pending = deque()

        def read(out):
            if isinstance(out, list) and self._is_graph and len(out) > 1:
                return [np.asarray(o) for o in out]
            o = out[0] if isinstance(out, list) else out
            return np.asarray(o)

        for x in batches:
            pending.append(self.predict(x))
            while len(pending) >= max(depth, 1):
                yield read(pending.popleft())
        while pending:
            yield read(pending.popleft())

    # -------------------------------------------------------------- warmup
    def _aot_key(self, b: int, shapes, dtype,
                 mask_len: Optional[int] = None) -> str:
        """Artifact key of one ladder rung: bucket + per-example shapes +
        dtype (+ mask length for the mask-carrying variant)."""
        s = ";".join("x".join(str(d) for d in tuple(shp)) for shp in shapes)
        kind = "graph" if self._is_graph else "mln"
        key = f"engine:{kind}:b{b}:{s}:{np.dtype(dtype).name}"
        return key if mask_len is None else f"{key}:mask{mask_len}"

    def warmup(self, example_shape, dtype=np.float32, max_batch=None,
               with_mask_len: Optional[int] = None,
               aot: Optional[str] = None):
        """Pre-compile the bucket ladder through the persistent compilation
        cache so the first real request pays ~0 compile time.

        ``example_shape``: per-example feature shape (no batch dim), or a
        list of shapes for multi-input graphs. ``max_batch`` caps the ladder
        (default: the engine's max_batch). ``with_mask_len``: also compile
        the mask-carrying variants for (B, T=with_mask_len) masks.

        ``aot``: path to an AOT artifact (exec/aot.py). Rungs found there
        are deserialized in milliseconds instead of retraced — trace_count
        stays 0 for them, restores count in ``dl4jtpu_aot_restores_total``.
        Any miss (absent file, env/model mismatch, unknown rung) falls back
        to trace-and-save: the rung compiles as usual and the fresh
        executable is merged back into the artifact.

        Each rung is dispatched twice with the second run timed separately,
        so ``rung_costs[b] = {"compile_s", "run_s"}`` records what the rung
        actually cost — the measurements ``autotune(prune=True)`` uses to
        merge rungs not worth their compile. Returns the bucket sizes
        compiled (the ACTIVE ladder — autotuned if one was applied)."""
        from deeplearning4j_tpu.util.compile_cache import setup_compile_cache
        setup_compile_cache()
        shapes = (example_shape if isinstance(example_shape, list)
                  else [example_shape])
        shapes = [tuple(s) for s in shapes]
        cap = min(max_batch or self.max_batch, self.max_batch)
        ladder = [b for b in (self.ladder
                              or bucket_ladder(cap, self.min_bucket))
                  if b <= cap]
        bundle = None
        added = 0
        if aot is not None:
            from deeplearning4j_tpu.exec import aot as aot_mod
            p, s = self._weights()
            sig = aot_mod.model_signature(p, s)
            bundle, _reason = aot_mod.open_bundle(aot, sig, self.precision)
            if bundle is None:
                bundle = aot_mod.AotBundle(sig, self.precision)
        t0 = time.perf_counter()
        self._in_warmup = True    # warmup traffic must not skew autotune
        try:
            for b in ladder:
                zeros = [jnp.zeros((b,) + s, dtype) for s in shapes]
                key = self._aot_key(b, shapes, dtype)
                if bundle is not None and (b, False) not in self._aot:
                    prog = bundle.restore(key, engine=self.id)
                    if prog is not None:
                        self._aot[(b, False)] = prog
                ta = time.perf_counter()
                jax.block_until_ready(self._dispatch(zeros))
                tb = time.perf_counter()
                jax.block_until_ready(self._dispatch(zeros))
                tc = time.perf_counter()
                self.rung_costs[b] = {
                    "compile_s": max((tb - ta) - (tc - tb), 0.0),
                    "run_s": tc - tb}
                if bundle is not None and (b, False) not in self._aot:
                    from deeplearning4j_tpu.exec import aot as aot_mod
                    params, state = self._weights()
                    bundle.add_compiled(key, aot_mod.export_compiled(
                        self._forward_fn(), (params, state, zeros, None)))
                    added += 1
                if with_mask_len is not None and not self._is_graph:
                    m = jnp.ones((b, with_mask_len), dtype)
                    mkey = self._aot_key(b, shapes, dtype, with_mask_len)
                    if bundle is not None and (b, True) not in self._aot:
                        prog = bundle.restore(mkey, engine=self.id)
                        if prog is not None:
                            self._aot[(b, True)] = prog
                    jax.block_until_ready(self._dispatch(zeros, m))
                    if bundle is not None and (b, True) not in self._aot:
                        from deeplearning4j_tpu.exec import aot as aot_mod
                        params, state = self._weights()
                        bundle.add_compiled(mkey, aot_mod.export_compiled(
                            self._forward_fn(), (params, state, zeros, m)))
                        added += 1
        finally:
            self._in_warmup = False
        self.warmup_seconds = time.perf_counter() - t0
        if bundle is not None and added:
            bundle.save(aot)
        return ladder

    def autotune(self, max_rungs: Optional[int] = None, apply: bool = True,
                 prune: bool = False, counts: Optional[dict] = None,
                 ) -> List[int]:
        """Re-derive the bucket ladder from the traffic this engine has
        actually served (the per-size histogram ``_dispatch`` records).

        The DP (``autotune_ladder``) never pads more than pow2 and never
        uses more rungs; ``prune=True`` additionally merges rungs whose
        measured compile cost (from ``warmup``'s rung_costs) exceeds the
        run-time their padding saves. ``apply=False`` just returns the
        proposal. ``counts`` substitutes an external size histogram (e.g.
        another engine's measured traffic) for this engine's own. Call
        after a representative traffic window; already-compiled pow2
        programs stay cached, so switching ladders mid-run only ever ADDS
        at most len(new ladder) compiles."""
        counts = dict(self._size_counts if counts is None else counts)
        ladder = autotune_ladder(counts, self.max_batch, max_rungs,
                                 self.min_bucket)
        if prune and self.rung_costs:
            ladder = prune_ladder(ladder, counts, self.rung_costs)
        if apply:
            self.ladder = ladder
            self._m_rungs.set(float(len(ladder)))
        return ladder

    # --------------------------------------------------------------- stats
    def stats(self) -> dict:
        from deeplearning4j_tpu.util.compile_cache import cache_stats
        rows = self._m_rows.value
        pad = self._m_pad_rows.value
        return {"id": self.id,
                "max_batch": self.max_batch,
                "bucket_ladder": (list(self.ladder) if self.ladder
                                  else bucket_ladder(self.max_batch,
                                                     self.min_bucket)),
                "ladder_autotuned": self.ladder is not None,
                "rung_costs": {int(k): dict(v)
                               for k, v in self.rung_costs.items()},
                "precision": self.precision,
                "weight_bytes": tree_bytes(self._weights()[0]),
                "model_version": self._version,
                "compiled_programs": self.trace_count,
                "rows": int(rows),
                "pad_rows": int(pad),
                "pad_waste_frac": (pad / (pad + rows)) if rows else 0.0,
                "warmup_seconds": self.warmup_seconds,
                "compile_cache": cache_stats()}
