"""Dynamic micro-batching: coalesce concurrent requests into one device call.

Parity cousin: parallel/inference.py's ParallelInference merges requests to
feed a sharded multi-device forward; this batcher is the single-engine
serving variant — a bounded queue whose worker drains it under a
max-latency / max-batch policy and answers each request with its slice of
the merged result. Combined with the engine's shape buckets, a storm of
odd-sized requests becomes a steady stream of identically-shaped device
calls that never trigger a fresh XLA compile.

Backpressure: the queue is bounded; ``submit`` blocks (up to
``submit_timeout``) when serving falls behind, which is the knob that keeps
a traffic spike from growing the heap without bound.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional

import numpy as np


class MicroBatcher:
    """Merge concurrent ``submit()`` batches into single engine calls.

    ``engine``: an InferenceEngine (or anything with ``predict_host``).
    ``max_batch``: merged rows per device call (requests above this are
    still served — the engine chunks internally). ``max_latency_ms``: how
    long the worker waits for co-travellers after the first request of a
    batch arrives; the classic throughput/latency trade.
    """

    def __init__(self, engine, max_batch: int = 256,
                 max_latency_ms: float = 2.0, max_queue: int = 1024,
                 submit_timeout: float = 30.0):
        self.engine = engine
        self.max_batch = int(max_batch)
        self.max_latency_ms = float(max_latency_ms)
        self.submit_timeout = submit_timeout
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # serving counters (exposed at /stats)
        self.n_requests = 0
        self.n_rows = 0
        self.n_device_calls = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "MicroBatcher":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # fail anything still queued so callers don't hang on dead futures
        while True:
            try:
                _, fut = self._q.get_nowait()
            except queue.Empty:
                break
            fut.set_exception(RuntimeError("micro-batcher stopped"))

    # -------------------------------------------------------------- serving
    def submit(self, x) -> Future:
        """Queue a request batch (n, features...); returns a Future whose
        result is the (n, ...) output slice. Blocks when the queue is full
        (bounded-queue backpressure)."""
        if self._thread is None:
            self.start()
        x = np.asarray(x)
        fut: Future = Future()
        self._q.put((x, fut), timeout=self.submit_timeout)
        return fut

    def predict(self, x):
        """Synchronous convenience: submit + wait."""
        return self.submit(x).result()

    def _worker(self):
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            batch = [first]
            total = first[0].shape[0]
            deadline = time.perf_counter() + self.max_latency_ms / 1000.0
            while total < self.max_batch:
                remaining = deadline - time.perf_counter()
                try:
                    item = (self._q.get_nowait() if remaining <= 0
                            else self._q.get(timeout=remaining))
                except queue.Empty:
                    break
                batch.append(item)
                total += item[0].shape[0]
                if remaining <= 0:
                    break
            try:
                merged = (batch[0][0] if len(batch) == 1
                          else np.concatenate([b[0] for b in batch]))
                out = self.engine.predict_host(merged)
                if isinstance(out, list):   # multi-output graph: first head
                    out = out[0]
                ofs = 0
                for x, fut in batch:
                    fut.set_result(out[ofs:ofs + x.shape[0]])
                    ofs += x.shape[0]
                with self._lock:
                    self.n_requests += len(batch)
                    self.n_rows += total
                    self.n_device_calls += 1
            except Exception as e:  # noqa: BLE001 — answer every caller
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)

    # ---------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            calls = self.n_device_calls
            return {"requests": self.n_requests, "rows": self.n_rows,
                    "device_calls": calls,
                    "avg_merge": (self.n_requests / calls) if calls else 0.0,
                    "queue_depth": self._q.qsize(),
                    "max_batch": self.max_batch,
                    "max_latency_ms": self.max_latency_ms}
