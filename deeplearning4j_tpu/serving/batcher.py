"""Dynamic micro-batching: coalesce concurrent requests into one device call.

Parity cousin: parallel/inference.py's ParallelInference merges requests to
feed a sharded multi-device forward; this batcher is the single-engine
serving variant — a bounded queue whose worker drains it under a
max-latency / max-batch policy and answers each request with its slice of
the merged result. Combined with the engine's shape buckets, a storm of
odd-sized requests becomes a steady stream of identically-shaped device
calls that never trigger a fresh XLA compile.

Backpressure: the queue is bounded; ``submit`` blocks (up to
``submit_timeout``) when serving falls behind, which is the knob that keeps
a traffic spike from growing the heap without bound.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional

import numpy as np

from deeplearning4j_tpu.monitor import (
    DEFAULT_LATENCY_BUCKETS, get_registry, trace)


class MicroBatcher:
    """Merge concurrent ``submit()`` batches into single engine calls.

    ``engine``: an InferenceEngine (or anything with ``predict_host``).
    ``max_batch``: merged rows per device call (requests above this are
    still served — the engine chunks internally). ``max_latency_ms``: how
    long the worker waits for co-travellers after the first request of a
    batch arrives; the classic throughput/latency trade.
    """

    _ids = itertools.count()

    def __init__(self, engine, max_batch: int = 256,
                 max_latency_ms: float = 2.0, max_queue: int = 1024,
                 submit_timeout: float = 30.0):
        self.engine = engine
        self.max_batch = int(max_batch)
        self.max_latency_ms = float(max_latency_ms)
        self.submit_timeout = submit_timeout
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # serving counters live in the process-wide registry: /stats, the
        # bench snapshots and GET /metrics all read the same cells
        self.id = f"batcher{next(MicroBatcher._ids)}"
        reg = get_registry()
        lab = {"batcher": self.id}
        self._m_requests = reg.counter(
            "dl4jtpu_serving_requests_total",
            "Requests answered by the micro-batcher.",
            ("batcher",)).labels(**lab)
        self._m_rows = reg.counter(
            "dl4jtpu_serving_rows_total",
            "Rows answered by the micro-batcher.", ("batcher",)).labels(**lab)
        self._m_device_calls = reg.counter(
            "dl4jtpu_serving_device_calls_total",
            "Merged device calls issued (avg merge = requests / calls).",
            ("batcher",)).labels(**lab)
        self._m_latency = reg.histogram(
            "dl4jtpu_serving_request_latency_seconds",
            "End-to-end request latency: submit() to future resolution "
            "(queueing + merge wait + device call + readback).",
            ("batcher",), buckets=DEFAULT_LATENCY_BUCKETS).labels(**lab)
        reg.gauge(
            "dl4jtpu_serving_queue_depth",
            "Requests waiting in the micro-batch queue right now.",
            ("batcher",)).labels(**lab).set_function(self._q.qsize)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "MicroBatcher":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # fail anything still queued so callers don't hang on dead futures
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            item[1].set_exception(RuntimeError("micro-batcher stopped"))

    # -------------------------------------------------------------- serving
    def submit(self, x) -> Future:
        """Queue a request batch (n, features...); returns a Future whose
        result is the (n, ...) output slice. Blocks when the queue is full
        (bounded-queue backpressure)."""
        if self._thread is None:
            self.start()
        x = np.asarray(x)
        fut: Future = Future()
        with trace.span("enqueue", rows=int(x.shape[0])):
            self._q.put((x, fut, time.perf_counter()),
                        timeout=self.submit_timeout)
        return fut

    def predict(self, x):
        """Synchronous convenience: submit + wait."""
        return self.submit(x).result()

    def _worker(self):
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            batch = [first]
            total = first[0].shape[0]
            deadline = time.perf_counter() + self.max_latency_ms / 1000.0
            while total < self.max_batch:
                remaining = deadline - time.perf_counter()
                try:
                    item = (self._q.get_nowait() if remaining <= 0
                            else self._q.get(timeout=remaining))
                except queue.Empty:
                    break
                batch.append(item)
                total += item[0].shape[0]
                if remaining <= 0:
                    break
            try:
                merged = (batch[0][0] if len(batch) == 1
                          else np.concatenate([b[0] for b in batch]))
                out = self.engine.predict_host(merged)
                if isinstance(out, list):   # multi-output graph: first head
                    out = out[0]
                ofs = 0
                done = time.perf_counter()
                for x, fut, t0 in batch:
                    fut.set_result(out[ofs:ofs + x.shape[0]])
                    self._m_latency.observe(done - t0)
                    ofs += x.shape[0]
                self._m_requests.inc(len(batch))
                self._m_rows.inc(total)
                self._m_device_calls.inc()
            except Exception as e:  # noqa: BLE001 — answer every caller
                for item in batch:
                    if not item[1].done():
                        item[1].set_exception(e)

    # ---------------------------------------------------------------- stats
    # the legacy counter attributes are read-only views over the registry
    # cells, so /stats and /metrics can never disagree
    @property
    def n_requests(self) -> int:
        return int(self._m_requests.value)

    @property
    def n_rows(self) -> int:
        return int(self._m_rows.value)

    @property
    def n_device_calls(self) -> int:
        return int(self._m_device_calls.value)

    def stats(self) -> dict:
        calls = self.n_device_calls
        p50 = self._m_latency.percentile(0.5)
        p99 = self._m_latency.percentile(0.99)
        return {"id": self.id,
                "requests": self.n_requests, "rows": self.n_rows,
                "device_calls": calls,
                "avg_merge": (self.n_requests / calls) if calls else 0.0,
                "queue_depth": self._q.qsize(),
                "latency_p50_ms": None if p50 is None else p50 * 1e3,
                "latency_p99_ms": None if p99 is None else p99 * 1e3,
                "max_batch": self.max_batch,
                "max_latency_ms": self.max_latency_ms}
