"""Dynamic micro-batching: coalesce concurrent requests into one device call.

Parity cousin: parallel/inference.py's ParallelInference merges requests to
feed a sharded multi-device forward; this batcher is the single-engine
serving variant — a bounded queue whose worker drains it under a
max-latency / max-batch policy and answers each request with its slice of
the merged result. Combined with the engine's shape buckets, a storm of
odd-sized requests becomes a steady stream of identically-shaped device
calls that never trigger a fresh XLA compile.

Overload protection (docs/FAULT_TOLERANCE.md):

- the queue is bounded; ``submit(block=False)`` sheds load immediately with
  ``ServerOverloadedError`` (HTTP 429 upstairs) instead of blocking a
  handler thread, and blocking submits still time out;
- each request can carry a **deadline**; expired requests are answered
  fast with ``DeadlineExceededError`` — at pop AND again right before
  dispatch, so an expired request never rides a device call;
- ``stop()`` drains gracefully: no new submits (``BatcherStoppedError``,
  immediately), the worker flushes everything already queued, then exits —
  and the stopping flag flips under the same lock that gates every
  enqueue, so a racing ``submit`` either lands before the drain (and is
  flushed) or is rejected; no Future is ever left unresolved.
"""

from __future__ import annotations

import inspect
import itertools
import queue
import threading
import time
from concurrent.futures import Future
from typing import Optional

import numpy as np

from deeplearning4j_tpu.monitor import (
    DEFAULT_LATENCY_BUCKETS, get_registry, trace)
from deeplearning4j_tpu.monitor import tracing
from deeplearning4j_tpu.monitor.reqlog import RequestLog, new_record
from deeplearning4j_tpu.resilience.errors import (
    BatcherStoppedError, DeadlineExceededError, ServerOverloadedError)


class MicroBatcher:
    """Merge concurrent ``submit()`` batches into single engine calls.

    ``engine``: an InferenceEngine (or anything with ``predict_host``).
    ``max_batch``: merged rows per device call (requests above this are
    still served — the engine chunks internally). ``max_latency_ms``: how
    long the worker waits for co-travellers after the first request of a
    batch arrives; the classic throughput/latency trade.
    """

    _ids = itertools.count()

    def __init__(self, engine, max_batch: int = 256,
                 max_latency_ms: float = 2.0, max_queue: int = 1024,
                 submit_timeout: float = 30.0, journal_capacity: int = 512):
        self.engine = engine
        # wide-event journal: one terminal record per request, rejections
        # included (docs/OBSERVABILITY.md "Request lifecycle")
        self.journal = RequestLog(journal_capacity)
        # phase attribution needs the engine to accept predict_host(phases=);
        # anything else (a bare callable in tests) still serves, unphased
        try:
            self._phases_ok = "phases" in inspect.signature(
                engine.predict_host).parameters
        except (AttributeError, TypeError, ValueError):
            self._phases_ok = False
        self.max_batch = int(max_batch)
        self.max_latency_ms = float(max_latency_ms)
        self.max_queue = int(max_queue)
        self.submit_timeout = submit_timeout
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        # gates every enqueue AND the stopping-flag flip: a submit can
        # never slip into the queue after stop() started rejecting
        self._state_lock = threading.Lock()
        # serving counters live in the process-wide registry: /stats, the
        # bench snapshots and GET /metrics all read the same cells
        self.id = f"batcher{next(MicroBatcher._ids)}"
        reg = get_registry()
        lab = {"batcher": self.id}
        self._m_requests = reg.counter(
            "dl4jtpu_serving_requests_total",
            "Requests answered by the micro-batcher.",
            ("batcher",)).labels(**lab)
        self._m_rows = reg.counter(
            "dl4jtpu_serving_rows_total",
            "Rows answered by the micro-batcher.", ("batcher",)).labels(**lab)
        self._m_device_calls = reg.counter(
            "dl4jtpu_serving_device_calls_total",
            "Merged device calls issued (avg merge = requests / calls).",
            ("batcher",)).labels(**lab)
        rejected = reg.counter(
            "dl4jtpu_serving_rejected_total",
            "Requests shed instead of served. reason: queue_full (429) | "
            "stopped (503) | deadline (504, answered before any device "
            "call).", ("batcher", "reason"))
        self._m_rej_full = rejected.labels(batcher=self.id,
                                           reason="queue_full")
        self._m_rej_stopped = rejected.labels(batcher=self.id,
                                              reason="stopped")
        self._m_rej_deadline = rejected.labels(batcher=self.id,
                                               reason="deadline")
        self._m_latency = reg.histogram(
            "dl4jtpu_serving_request_latency_seconds",
            "End-to-end request latency: submit() to future resolution "
            "(queueing + merge wait + device call + readback).",
            ("batcher",), buckets=DEFAULT_LATENCY_BUCKETS).labels(**lab)
        self._m_queue = reg.histogram(
            "dl4jtpu_predict_queue_seconds",
            "Time a /predict request waited in the micro-batch queue: "
            "submit() to dispatch of its merged device call.",
            ("batcher",), buckets=DEFAULT_LATENCY_BUCKETS).labels(**lab)
        reg.gauge(
            "dl4jtpu_serving_queue_depth",
            "Requests waiting in the micro-batch queue right now.",
            ("batcher",)).labels(**lab).set_function(self._q.qsize)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "MicroBatcher":
        """Start (or explicitly restart after stop()) the worker."""
        if self._thread is not None:
            return self
        self._stopping.clear()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Graceful drain: stop accepting, flush everything in-flight, join.
        Every queued Future settles — with its result if the worker reaches
        it, never by being silently dropped."""
        with self._state_lock:
            self._stopping.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        # anything still queued (worker never ran / join timed out): settle
        # it. New submits can't land — stopping is set under the lock.
        with self._state_lock:
            self._reject_queued()

    def _reject_queued(self):
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if not item[1].done():
                self._m_rej_stopped.inc()
                self._journal_terminal(item, "error")
                item[1].set_exception(
                    BatcherStoppedError("micro-batcher stopped"))

    @property
    def stopping(self) -> bool:
        return self._stopping.is_set()

    # -------------------------------------------------------------- serving
    def submit(self, x, deadline_ms: Optional[float] = None,
               block: bool = True, request_id: Optional[str] = None,
               tenant: str = "default", priority: str = "normal") -> Future:
        """Queue a request batch (n, features...); returns a Future whose
        result is the (n, ...) output slice.

        ``deadline_ms``: per-request budget from NOW; once expired the
        request is answered with ``DeadlineExceededError`` without touching
        the device. ``block=False``: never wait on a full queue — raise
        ``ServerOverloadedError`` immediately (the HTTP 429 path). Blocking
        submits apply backpressure up to ``submit_timeout`` seconds, then
        raise the same. Raises ``BatcherStoppedError`` once ``stop()`` has
        begun — a post-stop submit fails fast instead of hanging forever.
        ``request_id``/``tenant``/``priority`` identify the request in the
        wide-event journal; every exit — served OR rejected — leaves
        exactly one terminal record there.
        """
        x = np.asarray(x)
        t0 = time.perf_counter()
        expires = None if deadline_ms is None else t0 + deadline_ms / 1000.0
        fut: Future = Future()
        # the submitting thread's trace context rides the queue item so the
        # worker can stamp the device spans with the request's trace_id;
        # the meta dict carries journal identity to the terminal record
        meta = {"rid": request_id, "tenant": tenant, "priority": priority}
        item = (x, fut, t0, expires, tracing.get_context(), meta)
        give_up_at = (None if self.submit_timeout is None
                      else t0 + self.submit_timeout)
        with trace.span("enqueue", rows=int(x.shape[0])):
            while True:
                with self._state_lock:
                    if self._stopping.is_set():
                        self._m_rej_stopped.inc()
                        self._journal_terminal(item, "error")
                        raise BatcherStoppedError(
                            "micro-batcher is draining/stopped; "
                            "submit() rejected")
                    if self._thread is None:
                        self.start()
                    try:
                        self._q.put_nowait(item)
                        return fut
                    except queue.Full:
                        pass
                if not block or (give_up_at is not None
                                 and time.perf_counter() >= give_up_at):
                    self._m_rej_full.inc()
                    self._journal_terminal(item, "shed")
                    raise ServerOverloadedError(
                        f"serving queue full ({self.max_queue} waiting); "
                        "load shed")
                time.sleep(0.002)    # bounded backpressure, stop-aware

    def predict(self, x, deadline_ms: Optional[float] = None):
        """Synchronous convenience: submit + wait."""
        return self.submit(x, deadline_ms=deadline_ms).result()

    # ---------------------------------------------------------- wide events
    def _journal_terminal(self, item, outcome, now: Optional[float] = None,
                          **extra) -> None:
        """Append the ONE terminal wide-event record for a request —
        called at every exit: served, shed, deadline, stopped, errored."""
        x, _, t0, _, ctx, meta = item
        now = time.perf_counter() if now is None else now
        rec = new_record(
            meta["rid"], "predict",
            trace_id=None if ctx is None else ctx.trace_id,
            outcome=outcome, tenant=meta["tenant"],
            priority=meta["priority"], batcher=self.id,
            rows=int(x.shape[0]), wall_seconds=now - t0)
        rec.update(extra)
        self.journal.append(rec)

    # --------------------------------------------------------------- worker
    def _expired(self, item, now) -> bool:
        """Settle an expired request with DeadlineExceededError. True if it
        was expired (caller drops it from the batch)."""
        expires = item[3]
        if expires is None or now < expires:
            return False
        if not item[1].done():
            self._m_rej_deadline.inc()
            self._journal_terminal(item, "deadline", now=now)
            item[1].set_exception(DeadlineExceededError(
                "request deadline expired before dispatch "
                f"({(now - item[2]) * 1e3:.1f} ms in queue)"))
        return True

    def _worker(self):
        while True:
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._stopping.is_set():
                    return      # graceful exit: queue fully flushed
                continue
            if self._expired(first, time.perf_counter()):
                continue
            batch = [first]
            total = first[0].shape[0]
            wait_until = time.perf_counter() + self.max_latency_ms / 1000.0
            while total < self.max_batch:
                remaining = wait_until - time.perf_counter()
                try:
                    item = (self._q.get_nowait() if remaining <= 0
                            else self._q.get(timeout=remaining))
                except queue.Empty:
                    break
                if self._expired(item, time.perf_counter()):
                    continue
                batch.append(item)
                total += item[0].shape[0]
                if remaining <= 0:
                    break
            # final deadline check at dispatch time: a request that expired
            # while waiting for co-travellers must NOT ride the device call
            now = time.perf_counter()
            batch = [it for it in batch if not self._expired(it, now)]
            if not batch:
                continue
            total = sum(it[0].shape[0] for it in batch)
            # queue phase ends here: every rider is about to ride one
            # merged device call
            for it in batch:
                self._m_queue.observe(now - it[2], exemplar=it[5]["rid"])
            try:
                merged = (batch[0][0] if len(batch) == 1
                          else np.concatenate([b[0] for b in batch]))
                # phase attribution for the merged call (bucket / pad /
                # device / readback); the spans are shared — every
                # co-traveller's record carries the same batch phases
                ph: Optional[dict] = {} if self._phases_ok else None
                # the merged device call runs under the first rider's trace
                # context (one call serves many requests; Perfetto shows the
                # co-travellers via their own enqueue spans)
                with tracing.trace_context(batch[0][4]):
                    out = (self.engine.predict_host(merged, phases=ph)
                           if ph is not None
                           else self.engine.predict_host(merged))
                if isinstance(out, list):   # multi-output graph: first head
                    out = out[0]
                ofs = 0
                done = time.perf_counter()
                for x, fut, t0, _, ctx, meta in batch:
                    fut.set_result(out[ofs:ofs + x.shape[0]])
                    self._m_latency.observe(done - t0, exemplar=meta["rid"])
                    phases = {"queue": now - t0}
                    if ph:
                        phases.update(ph)
                    self._journal_terminal(
                        (x, fut, t0, None, ctx, meta), "ok",
                        now=done, phases=phases, batch=len(batch))
                    ofs += x.shape[0]
                self._m_requests.inc(len(batch))
                self._m_rows.inc(total)
                self._m_device_calls.inc()
            except Exception as e:  # noqa: BLE001 — answer every caller
                for item in batch:
                    if not item[1].done():
                        self._journal_terminal(item, "error")
                        item[1].set_exception(e)

    # ---------------------------------------------------------------- stats
    # the legacy counter attributes are read-only views over the registry
    # cells, so /stats and /metrics can never disagree
    @property
    def n_requests(self) -> int:
        return int(self._m_requests.value)

    @property
    def n_rows(self) -> int:
        return int(self._m_rows.value)

    @property
    def n_device_calls(self) -> int:
        return int(self._m_device_calls.value)

    @property
    def n_rejected(self) -> dict:
        return {"queue_full": int(self._m_rej_full.value),
                "stopped": int(self._m_rej_stopped.value),
                "deadline": int(self._m_rej_deadline.value)}

    def _slo_stats(self) -> dict:
        """SLO summaries + per-bucket exemplars (request ids) so a bad
        percentile resolves to a concrete journal record."""
        def block(h):
            p50, p99 = h.percentile(0.5), h.percentile(0.99)
            return {"count": int(h.count),
                    "p50_ms": None if p50 is None else round(p50 * 1e3, 4),
                    "p99_ms": None if p99 is None else round(p99 * 1e3, 4),
                    "exemplars": [
                        ["+Inf" if b == float("inf") else b, rid, v]
                        for b, rid, v in h.exemplars()]}
        return {"queue": block(self._m_queue),
                "latency": block(self._m_latency)}

    def stats(self) -> dict:
        calls = self.n_device_calls
        p50 = self._m_latency.percentile(0.5)
        p99 = self._m_latency.percentile(0.99)
        return {"id": self.id,
                "requests": self.n_requests, "rows": self.n_rows,
                "device_calls": calls,
                "avg_merge": (self.n_requests / calls) if calls else 0.0,
                "queue_depth": self._q.qsize(),
                "queue_capacity": self.max_queue,
                "rejected": self.n_rejected,
                "state": "draining" if self.stopping else "serving",
                "latency_p50_ms": None if p50 is None else p50 * 1e3,
                "latency_p99_ms": None if p99 is None else p99 * 1e3,
                "slo": self._slo_stats(),
                "journal": {"capacity": self.journal.capacity,
                            "records": len(self.journal),
                            "total": self.journal.total,
                            "dropped": self.journal.dropped},
                "max_batch": self.max_batch,
                "max_latency_ms": self.max_latency_ms}
