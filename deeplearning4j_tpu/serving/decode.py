"""Incremental decoding engine: stateful step caches + slot-based
continuous batching for autoregressive serving.

``InferenceEngine`` (engine.py) amortizes compiles across request SHAPES;
this module amortizes the autoregressive loop across concurrent REQUESTS.
A naive text-generation server re-runs the full prefix forward for every
token (O(T²) work per sequence) and batches only at request granularity —
a long sequence blocks the batch until it finishes. Here, decode state
(LSTM (h, c) carries, attention KV caches) stays resident on device in ONE
batched tree of S slots, and the server batches at ITERATION granularity
(the Orca/vLLM scheduling model): every device call advances all active
sequences by one token, new requests claim free slots mid-flight, finished
sequences free their slot without touching the compiled program.

Design rules the tests pin:

- ONE compiled program. Every step runs the same (S,)-shaped jitted
  function (donated state buffers), regardless of which slots are active,
  how requests arrive, or when they finish. ``trace_count`` counts XLA
  programs exactly, engine.py-style.
- Bitwise parity. A token decoded incrementally is bitwise-equal to the
  same position of a teacher-forced full-prefix forward (layer contract in
  nn/layers/base.py ``decode_step``; see docs/DECODING.md for the XLA:CPU
  fusion subtleties this requires).
- No state leakage. A freed slot's state is wiped INSIDE the step (reset
  mask) when re-claimed, so slot reuse can never see a previous request's
  carries; inactive slots are frozen by an active mask (their state is
  bit-identical across steps they don't participate in).
- Deterministic sampling. The PRNG key for a token is
  ``fold_in(PRNGKey(request_seed), position)`` — a pure function of the
  request, never of the slot index or co-tenants — so any arrival
  schedule produces the same text for the same seed.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.monitor import get_registry, trace
from deeplearning4j_tpu.monitor.reqlog import RequestLog, new_record
from deeplearning4j_tpu.monitor.tracing import get_context
from deeplearning4j_tpu.resilience.errors import (
    BatcherStoppedError, ServerOverloadedError)
from deeplearning4j_tpu.quant import (dequantize_tree, record_weight_bytes,
                                      resolve_precision, tree_bytes)
from deeplearning4j_tpu.serving.engine import (_tree_signature,
                                               _validate_sig, validate_swap)
from deeplearning4j_tpu.serving.kv import (BlockPool, PoolExhaustedError,
                                           PrefixCache, blocks_for_span,
                                           map_pool_leaves, map_slot_leaves)
from deeplearning4j_tpu.serving.spec.accept import oracle_token, oracle_tokens
from deeplearning4j_tpu.serving.spec.draft import DraftEngine
from deeplearning4j_tpu.serving.spec.verify import SpecVerifier


class _Request:
    """Host-side bookkeeping for one occupied slot."""

    __slots__ = ("prompt", "max_new", "seed", "temperature", "top_k",
                 "cursor", "generated", "future", "fresh", "t_start",
                 "kv_blocks", "draft_cursor", "draft_sel", "draft_fresh",
                 "rid", "tenant", "priority", "trace_id",
                 "t_admit", "t_prefill0", "t_first", "t_last",
                 "verify_s", "drafted", "accepted",
                 "prefix_hit", "host_restores")

    def __init__(self, prompt, max_new, seed, temperature, top_k, future,
                 rid=None, tenant="default", priority="normal",
                 trace_id=None):
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.seed = int(seed)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.cursor = 0          # next input position to feed
        self.generated: List[int] = []
        self.future = future
        self.fresh = True        # first step must wipe the slot's state
        self.t_start = time.perf_counter()
        self.kv_blocks: List[int] = []   # paged engines: claimed pool blocks
        # speculative engines: the draft model's own progress through this
        # stream (it prefills the prompt independently of the target)
        self.draft_cursor = 0    # next input position the DRAFT will feed
        self.draft_sel = 0       # snapshot stack index to resume carries at
        self.draft_fresh = True  # first draft call must wipe the draft slot
        # request-lifecycle identity + host-side perf_counter stamps (the
        # wide-event record, docs/OBSERVABILITY.md "Request lifecycle").
        # Every stamp rides an existing host-side point in the tick loop
        # — the instrumentation adds ZERO device syncs.
        self.rid = rid
        self.tenant = tenant
        self.priority = priority
        self.trace_id = trace_id
        self.t_admit = None      # slot claimed (queue phase ends)
        self.t_prefill0 = None   # first prefill work dispatched
        self.t_first = None      # first token emitted (TTFT)
        self.t_last = None       # latest emission run (ITL reference)
        self.verify_s = 0.0      # spec: wall spent in verify calls
        self.drafted = 0         # spec: tokens proposed for this stream
        self.accepted = 0        # spec: tokens accepted for this stream
        self.prefix_hit = 0      # paged: prompt positions reused from cache
        self.host_restores = 0   # paged: host-tier blocks promoted for us


class DecodeEngine:
    """Continuous-batching autoregressive decoder over a model container.

    ``model`` is a MultiLayerNetwork or ComputationGraph whose layers
    implement the incremental-decode protocol (``init_decode_state`` /
    ``decode_step``) and whose output layer emits per-token probabilities
    (e.g. RnnOutputLayer softmax). Inputs are token ids; the engine
    one-hots them on device to the model's input width.

        eng = DecodeEngine(net, slots=32, max_len=256).start()
        toks = eng.generate([3, 1, 4], max_new_tokens=64)["tokens"]

    ``slots``: concurrent streams held in the batched state tree.
    ``max_len``: fixed KV-cache capacity = max prompt+generated length.
    ``eos_id``: token id that finishes a stream early (None = length only).
    ``max_queue``: bound on waiting requests (beyond it: overload error,
    HTTP 429 through the server).
    ``kv``: ``"dense"`` (per-slot contiguous caches, the default) or
    ``"paged"`` (device-resident block pool + per-slot page tables —
    docs/DECODING.md "Paged KV cache"). Paged engines accept
    ``kv_block_size`` (tokens per block), ``kv_blocks`` (pool size; default
    sizes the pool for full occupancy), ``prefix_cache`` (reuse completed
    prefill blocks across requests sharing a prompt prefix; requires a
    model with no recurrent per-slot decode state) and ``chunk_tokens``
    (split prefill into chunks of this many tokens that ride the batched
    iteration cadence next to live decode slots, instead of occupying one
    decode step per prompt token).
    ``spec``: a ``serving.spec.SpecConfig`` switches the scheduler to
    speculative decoding — a draft (a separate model, or the target
    itself via ``self_draft``) proposes a token TREE per tick
    (``tree=(k_1,..,k_D)``; plain ``k`` = the linear chain) and the
    target verifies every node in one batched step, emitting 1..D+1
    tokens per tick while staying bitwise-identical to the
    non-speculative engine (docs/DECODING.md "Tree speculation &
    self-drafting").
    """

    _ids = itertools.count()

    def __init__(self, model, slots: int = 8, max_len: int = 256,
                 eos_id: Optional[int] = None, max_queue: int = 256,
                 precision: Optional[str] = None, kv: str = "dense",
                 kv_block_size: int = 16, kv_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 chunk_tokens: Optional[int] = None,
                 host_kv_bytes: Optional[int] = None,
                 spec=None, journal_capacity: int = 512):
        self.model = model
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.eos_id = eos_id
        self.max_queue = int(max_queue)
        if kv not in ("dense", "paged"):
            raise ValueError(f"kv must be 'dense' or 'paged', got {kv!r}")
        if kv == "dense" and chunk_tokens is not None:
            raise ValueError("chunk_tokens requires kv='paged'")
        if kv == "paged" and self.max_len % int(kv_block_size) != 0:
            # the gathered paged cache must cover exactly max_len positions
            # for bitwise parity with the dense step program
            raise ValueError(
                f"max_len ({max_len}) must be a multiple of kv_block_size "
                f"({kv_block_size})")
        if chunk_tokens is not None and int(chunk_tokens) < 1:
            raise ValueError("chunk_tokens must be >= 1")
        if host_kv_bytes is not None and (
                kv != "paged" or not prefix_cache):
            raise ValueError(
                "host_kv_bytes requires kv='paged' with prefix_cache=True "
                "(the tier holds evicted prefix-cache blocks)")
        self.kv = kv
        self.kv_block_size = int(kv_block_size)
        self.chunk_tokens = (int(chunk_tokens) if chunk_tokens is not None
                             else None)
        self.kv_max_blocks = (self.max_len // self.kv_block_size
                              if kv == "paged" else 0)
        self._pool: Optional[BlockPool] = None
        self._prefix: Optional[PrefixCache] = None
        self._tables: Optional[np.ndarray] = None
        self._pending_cows: List[tuple] = []
        self._host_tier = None
        # bid -> per-leaf host rows: tier restores claimed during match
        # whose host→device scatter is still pending (applied in one
        # batch before the next device call, like _pending_cows)
        self._pending_restores: dict = {}
        # export/import closures marshalled onto the loop thread — the
        # only thread allowed to touch the donated decode state
        self._kv_ops: deque = deque()
        self._kv_blocked = False
        self._is_graph = hasattr(model.conf, "network_inputs")
        itype = (model.conf.input_types[0] if self._is_graph
                 else model.conf.input_type)
        self.vocab = itype.size
        self.warmup_seconds: Optional[float] = None
        self._spec = spec
        if spec is not None:
            from deeplearning4j_tpu.serving.spec import TreeSpec
            from deeplearning4j_tpu.serving.spec.selfdraft import \
                build_self_draft
            if int(spec.k) < 1:
                raise ValueError(f"spec.k must be >= 1, got {spec.k}")
            # static tree shape: SpecConfig.tree or the linear (1,)*k
            self._spec_tree = TreeSpec(spec.kvec())
            # draft scan width: spine depth + 1 snapshot slack (the extra
            # position keeps a resume snapshot live at full acceptance)
            self._spec_k = self._spec_tree.d + 1
            dm = spec.draft_model
            if (dm is None) == (spec.self_draft is None):
                raise ValueError(
                    "spec needs exactly one of draft_model or self_draft "
                    f"(got draft_model={dm!r}, "
                    f"self_draft={spec.self_draft!r})")
            if spec.self_draft is not None:
                dm, self._spec_draft_precision = build_self_draft(
                    model, spec)
            else:
                # the draft proposes TOKEN IDS the target verifies — only
                # meaningful over the exact same vocabulary
                ditype = (dm.conf.input_types[0]
                          if hasattr(dm.conf, "network_inputs")
                          else dm.conf.input_type)
                if ditype.size != self.vocab:
                    raise ValueError(
                        f"draft model vocabulary ({ditype.size}) must "
                        f"match the target's ({self.vocab})")
                self._spec_draft_precision = spec.draft_precision
            self._spec_draft_model = dm

        from deeplearning4j_tpu import exec as ex
        execu = getattr(model, "_executor", None) or ex.get_executor()
        # serving precision (engine.py policy, docs/QUANTIZATION.md):
        # int8/fp8 pins the quantized weights now and keeps the f32
        # signature so staged swaps validate f32 candidates and quantize
        # AFTER the gate — the one step program never re-traces
        self.precision = (resolve_precision(precision)
                          if precision is not None else execu.precision)
        self._raw_sig = None
        if self.kv == "paged":
            # same step program shape every call: the (S, max_blocks) page
            # table rides in as one more (S,)-leading data argument
            self._step = execu.jit(
                self._step_impl_paged,
                in_specs=(ex.PARAMS, ex.STATE, ex.SLOTS, ex.BATCH, ex.BATCH,
                          ex.BATCH, ex.BATCH, ex.BATCH, ex.BATCH, ex.BATCH,
                          ex.BATCH),
                out_specs=(ex.BATCH, ex.SLOTS),
                donate_argnums=(2,))
        else:
            self._step = execu.jit(
                self._step_impl,
                in_specs=(ex.PARAMS, ex.STATE, ex.SLOTS, ex.BATCH, ex.BATCH,
                          ex.BATCH, ex.BATCH, ex.BATCH, ex.BATCH, ex.BATCH),
                out_specs=(ex.BATCH, ex.SLOTS),
                donate_argnums=(2,))
        self._prefill = None
        self._cow = None
        if self.chunk_tokens is not None:
            self._prefill = execu.jit(
                self._prefill_impl,
                in_specs=(ex.PARAMS, ex.STATE, ex.SLOTS, ex.BATCH, ex.BATCH,
                          ex.BATCH, ex.BATCH, ex.BATCH),
                out_specs=(ex.SLOTS,),
                donate_argnums=(2,))
        if self.kv == "paged" and prefix_cache:
            self._cow = execu.jit(
                self._cow_impl,
                in_specs=(ex.SLOTS, ex.REPL, ex.REPL),
                out_specs=(ex.SLOTS,),
                donate_argnums=(0,))
        self._dstate = None
        self._live = None          # (params, state) after the first swap
        if self.precision != "f32":
            self._raw_sig = _tree_signature(model.params)
            qp = execu.prepare_params(model.params, self.precision)
            st = jax.tree_util.tree_map(jnp.asarray, model.state)
            self._live = (qp, st)
        self._pending_swap = None  # staged (params, state, version, Event)
        self._version = 0
        self._slot_reqs: List[Optional[_Request]] = [None] * self.slots
        self._queue: deque = deque()
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._decode_seconds = 0.0

        self.id = f"decode{next(DecodeEngine._ids)}"
        reg = get_registry()
        lab = {"engine": self.id}
        self._m_compiled = reg.counter(
            "dl4jtpu_decode_compiled_programs_total",
            "XLA programs traced for the batched decode step (design "
            "target: exactly one per model).", ("engine",)).labels(**lab)
        self._m_steps = reg.counter(
            "dl4jtpu_decode_steps_total",
            "Batched decode-step device calls.", ("engine",)).labels(**lab)
        self._m_tokens = reg.counter(
            "dl4jtpu_decode_tokens_total",
            "Tokens generated (sampled outputs only — prefill positions "
            "are not counted).", ("engine",)).labels(**lab)
        self._m_requests = reg.counter(
            "dl4jtpu_decode_requests_total",
            "Generation requests completed.", ("engine",)).labels(**lab)
        self._m_occupancy = reg.gauge(
            "dl4jtpu_decode_active_slots",
            "Slots occupied by live streams at the last step.",
            ("engine",)).labels(**lab)
        self._m_token_seconds = reg.histogram(
            "dl4jtpu_decode_token_seconds",
            "Per-token latency: wall seconds of one batched step (every "
            "active stream advances one token per step).",
            ("engine",)).labels(**lab)
        # request-lifecycle SLO histograms (docs/OBSERVABILITY.md
        # "Request lifecycle"): fed from host-side perf_counter stamps at
        # existing emission points — zero device syncs added to the tick
        # loop. Observations carry the request id as a bucket exemplar.
        self._m_ttft = reg.histogram(
            "dl4jtpu_decode_ttft_seconds",
            "Time-to-first-token: submit to first emitted token, queue "
            "wait included (the prefill-dominated serving SLO).",
            ("engine",)).labels(**lab)
        self._m_itl = reg.histogram(
            "dl4jtpu_decode_itl_seconds",
            "Inter-token latency: wall between consecutive emitted "
            "tokens; speculative runs contribute one sample per accepted "
            "token (run wall / run length).", ("engine",)).labels(**lab)
        self._m_queue = reg.histogram(
            "dl4jtpu_decode_queue_seconds",
            "Admission queue wait: submit to slot claim.",
            ("engine",)).labels(**lab)
        # the wide-event request journal (terminal record per request,
        # completions AND rejections) served at GET /requests
        self.journal = RequestLog(journal_capacity)
        self._m_version = reg.gauge(
            "dl4jtpu_model_version",
            "Version of the weights currently serving (0 = the model's "
            "initial weights; bumped by every hot swap).",
            ("engine",)).labels(**lab)
        self._m_swaps = reg.counter(
            "dl4jtpu_model_swaps_total",
            "Weight hot-swaps applied with zero new XLA compiles.",
            ("engine",)).labels(**lab)
        self._m_version.set(0.0)
        if self.precision != "f32":
            record_weight_bytes(self.id, self.precision,
                                tree_bytes(self._live[0]))

        if self.kv == "paged":
            if kv_blocks is None:
                # full occupancy by default: every slot can hold max_len
                # tokens, +1 for the reserved scratch block
                kv_blocks = self.slots * self.kv_max_blocks + 1
            self._pool = BlockPool(int(kv_blocks), self.kv_block_size,
                                   engine=self.id)
            self._tables = np.zeros((self.slots, self.kv_max_blocks),
                                    np.int32)
            if prefix_cache:
                # prefix reuse assumes a slot's KV blocks are the ONLY
                # per-slot decode state — recurrent carries (LSTM h/c)
                # depend on every earlier token and cannot be shared.
                probe = self.model.init_decode_state(
                    1, self.max_len,
                    kv={"num_blocks": 2, "block_size": self.kv_block_size})
                from deeplearning4j_tpu.serving.kv import is_pool_path
                carries = []
                jax.tree_util.tree_map_with_path(
                    lambda p, a: carries.append(p)
                    if not is_pool_path(p) else None, probe)
                if carries:
                    raise ValueError(
                        "prefix_cache=True requires a model whose only "
                        "per-slot decode state is the paged KV cache; this "
                        "model carries recurrent state "
                        f"({len(carries)} non-pool leaves). Pass "
                        "prefix_cache=False.")
                self._prefix = PrefixCache(self._pool)
                if host_kv_bytes is not None:
                    from deeplearning4j_tpu.serving.kv import HostKVTier
                    self._host_tier = HostKVTier(int(host_kv_bytes),
                                                 engine=self.id)
                    self._prefix.tier = self._host_tier
                    self._prefix.spill_fn = self._spill_block
                    self._prefix.restore_fn = self._restore_block
            self._m_kv_programs = reg.counter(
                "dl4jtpu_kv_compiled_programs_total",
                "XLA programs traced for the paged-KV side programs "
                "(chunked prefill + copy-on-write; design target: at most "
                "one each).", ("engine",)).labels(**lab)
            self._m_kv_exhausted = reg.counter(
                "dl4jtpu_kv_pool_exhausted_total",
                "Admissions stalled because the KV block pool could not "
                "cover the request at the queue head.",
                ("engine",)).labels(**lab)
            self._m_prefix_hits = reg.counter(
                "dl4jtpu_kv_prefix_hits_total",
                "Requests that reused at least one cached prefix block.",
                ("engine",)).labels(**lab)
            self._m_prefix_saved = reg.counter(
                "dl4jtpu_kv_prefix_tokens_saved_total",
                "Prefill positions skipped by prefix-cache reuse.",
                ("engine",)).labels(**lab)
            self._m_cow = reg.counter(
                "dl4jtpu_kv_cow_copies_total",
                "Copy-on-write block copies (partial prefix match claimed "
                "then diverged into a private block).",
                ("engine",)).labels(**lab)
            self._m_prefill_chunks = reg.counter(
                "dl4jtpu_kv_prefill_chunks_total",
                "Chunked-prefill slot-chunks executed.",
                ("engine",)).labels(**lab)
            self._m_prefill_tokens = reg.counter(
                "dl4jtpu_kv_prefill_tokens_total",
                "Prompt tokens prefilled through the chunked-prefill "
                "program.", ("engine",)).labels(**lab)
            self._m_host_restores = reg.counter(
                "dl4jtpu_kv_host_restores_total",
                "Spilled prefix blocks promoted back from the host tier "
                "on a second-chance match hit.", ("engine",)).labels(**lab)
            self._m_migrate_exports = reg.counter(
                "dl4jtpu_kv_migrate_exports_total",
                "Block chains serialized for replica-to-replica KV "
                "migration (/kv/export).", ("engine",)).labels(**lab)
            self._m_migrate_imports = reg.counter(
                "dl4jtpu_kv_migrate_imports_total",
                "Block chains restored from a migration payload "
                "(/kv/import).", ("engine",)).labels(**lab)
            self._m_migrate_rejects = reg.counter(
                "dl4jtpu_kv_migrate_rejects_total",
                "Migration payloads rejected before touching the pool "
                "(envelope mismatch, torn bytes, exhausted destination).",
                ("engine", "reason"))

        self._verifier = None
        self._draft = None
        if spec is not None:
            self._verifier = SpecVerifier(
                self.model, self.id, self.slots, self.max_len,
                self._spec_tree, self.vocab, kv=self.kv,
                kv_max_blocks=self.kv_max_blocks)
            self._draft = DraftEngine(
                self._spec_draft_model, self.id, self.slots, self.max_len,
                self._spec_k, self.vocab,
                precision=self._spec_draft_precision,
                side_k=max(self._spec_tree.kvec) - 1)
            self._m_spec_drafted = reg.counter(
                "dl4jtpu_spec_drafted_tokens_total",
                "Tokens proposed by the speculative draft model.",
                ("engine",)).labels(**lab)
            self._m_spec_accepted = reg.counter(
                "dl4jtpu_spec_accepted_tokens_total",
                "Drafted tokens accepted by target verification "
                "(exact-match against the sampling oracle).",
                ("engine",)).labels(**lab)
            self._m_spec_rate = reg.gauge(
                "dl4jtpu_spec_acceptance_rate",
                "Lifetime accepted/drafted ratio — the draft-quality "
                "signal that decides whether speculation pays.",
                ("engine",)).labels(**lab)
            self._m_spec_draft_seconds = reg.histogram(
                "dl4jtpu_spec_draft_step_seconds",
                "Wall seconds of one k-token draft-model call (compare "
                "against dl4jtpu_decode_token_seconds: speculation wins "
                "while draft cost + one verify < k target steps).",
                ("engine",)).labels(**lab)
            self._m_spec_depth = reg.histogram(
                "dl4jtpu_spec_accepted_depth",
                "Accepted tree depth per verify (0 = root correction "
                "only): the distribution behind the acceptance-rate "
                "gauge — a mass pile-up at 0 means the tree's depth "
                "budget is wasted.",
                ("engine",),
                buckets=tuple(float(d)
                              for d in range(self._spec_tree.d + 1))
            ).labels(**lab)
            self._m_spec_nodes = reg.gauge(
                "dl4jtpu_spec_tree_nodes",
                "Static speculation-tree size (nodes scored per verify "
                "call) — the verify-cost side of the tree-shape "
                "trade-off.", ("engine",)).labels(**lab)
            self._m_spec_nodes.set(float(self._spec_tree.n_nodes))

    @property
    def trace_count(self) -> int:
        return int(self._m_compiled.value)

    @property
    def model_version(self) -> int:
        return self._version

    def _weights(self):
        """Live (params, state): the engine's own pair after a swap was
        applied, the model's until then (so a freshly built engine still
        follows further ``fit()`` calls on its model)."""
        live = self._live
        if live is not None:
            return live
        return self.model.params, self.model.state

    def swap_weights(self, params, state=None, version: Optional[int] = None,
                     timeout: Optional[float] = 60.0) -> int:
        """Stage a same-shape weight swap and wait for it to apply.

        Continuous batching means slots from different requests share every
        device call, and a generation must run END-TO-END on one model
        version — so the swap is deferred: admission pauses, in-flight
        generations finish on the old weights (bounded by their remaining
        ``max_new_tokens``), and the loop applies the swap at the first
        step boundary with zero live slots, then re-admits. The candidate
        is validated BEFORE staging (``WeightSwapError`` leaves the engine
        untouched), and identical shapes/dtypes mean the single compiled
        step program is reused — zero new XLA compiles."""
        cur_p, cur_s = self._weights()
        if self._raw_sig is not None:
            _validate_sig(self._raw_sig, _tree_signature(params),
                          "decode params")
        else:
            validate_swap(cur_p, params, "decode params")
        if state is not None:
            validate_swap(cur_s, state, "decode state")
        params = jax.tree_util.tree_map(jnp.asarray, params)
        if self.precision != "f32":
            from deeplearning4j_tpu import exec as ex
            execu = getattr(self.model, "_executor", None) \
                or ex.get_executor()
            params = execu.prepare_params(params, self.precision)
            record_weight_bytes(self.id, self.precision, tree_bytes(params))
        state = (cur_s if state is None
                 else jax.tree_util.tree_map(jnp.asarray, state))
        applied = threading.Event()
        with self._cv:
            self._pending_swap = (params, state, version, applied)
            self._cv.notify_all()
            if self._thread is None or not self._thread.is_alive():
                self._apply_swap_locked()   # no loop running: apply now
        if timeout is not None and not applied.wait(timeout):
            raise TimeoutError(
                f"decode weight swap not applied within {timeout}s "
                f"(in-flight generations still draining)")
        return self._version

    def _apply_swap_locked(self) -> None:
        """Apply the staged swap (caller holds ``self._cv``, no live
        slots)."""
        params, state, version, applied = self._pending_swap
        self._pending_swap = None
        self._live = (params, state)
        if self._prefix is not None:
            # cached KV was computed under the OLD weights — reusing it
            # across a swap would splice two model versions into one stream
            self._prefix.clear()
        self._version = (int(version) if version is not None
                         else self._version + 1)
        self._m_version.set(float(self._version))
        self._m_swaps.inc()
        applied.set()

    @property
    def saturated(self) -> bool:
        """All S slots busy: a new /generate would queue behind a full
        batch. /healthz reports ``degraded`` in this state so a router can
        steer prefill-heavy work to replicas with free slots."""
        with self._cv:
            return (self.slots > 0
                    and all(r is not None for r in self._slot_reqs))

    @property
    def kv_exhausted(self) -> bool:
        """Paged engines: the request at the queue head could not claim
        blocks at the last admission pass (clears as blocks release).
        /healthz reports ``degraded`` with the pool occupancy."""
        if self._pool is None:
            return False
        with self._cv:
            return self._kv_blocked

    def kv_pool_info(self) -> Optional[dict]:
        """Pool occupancy snapshot for /healthz and stats (None = dense)."""
        if self._pool is None:
            return None
        info = {"blocks": self._pool.usable,
                "blocks_free": self._pool.free_count,
                "blocks_in_use": self._pool.in_use,
                "blocks_cached": self._pool.cached_count,
                "block_size": self.kv_block_size,
                "high_water": self._pool.high_water}
        if self._host_tier is not None:
            info["host_tier"] = self._host_tier.stats()
        return info

    # ------------------------------------------------------------- the step
    def _step_impl(self, params, state, dstate, tokens, pos, reset, active,
                   seeds, temps, topk, btab=None):
        """ONE iteration for all S slots. All arguments are (S,)-shaped, so
        every call shares a single XLA program; scheduling decisions ride in
        as data (masks), never as shapes. ``btab`` (paged engines) is the
        (S, max_blocks) page table — also data, same program shape."""
        from deeplearning4j_tpu.exec.programs import is_registering
        if not is_registering():
            self._m_compiled.inc()   # traced-only: exact compiled-program count
        # dequant-on-the-fly (identity on the f32 path): int8/fp8 weights
        # stream from HBM at quantized width every step — the decode step
        # is weight-bandwidth-bound, so this is where low precision pays
        params = dequantize_tree(params)
        S = self.slots

        def wipe(a):
            r = reset.reshape((S,) + (1,) * (a.ndim - 1))
            return jnp.where(r, jnp.zeros_like(a), a)

        # re-claimed slots start from zero state INSIDE the step — claiming
        # a slot never needs a second program, and stale carries can't leak.
        # Paged engines never wipe the pool: blocks are recycled by the
        # host-side refcounts, and a reset slot's table points at fresh ones.
        tmap = (jax.tree_util.tree_map if btab is None else map_slot_leaves)
        dstate = tmap(wipe, dstate)
        x = jax.nn.one_hot(tokens, self.vocab, dtype=jnp.float32)[:, None, :]
        if btab is None:
            y, new_d = self.model.decode_step(params, state, dstate, x, pos)
        else:
            y, new_d = self.model.decode_step(params, state, dstate, x, pos,
                                              block_tables=btab)

        # ONE sampling rule for the whole codebase: generate_naive and the
        # speculative verify program (serving/spec/) call the same oracle,
        # so every path emits bitwise-identical tokens for the same
        # (distribution, seed, position). log(probs) is monotone, so
        # top-k filtering and argmax are equivalent on either scale.
        next_tok = oracle_tokens(jnp.log(y[:, 0, :]), seeds, pos, temps, topk)
        next_tok = jnp.where(active, next_tok, 0)

        def freeze(new, old):
            a = active.reshape((S,) + (1,) * (new.ndim - 1))
            return jnp.where(a, new, old)

        # inactive slots keep their state bit-identical (numerically inert)
        new_d = tmap(freeze, new_d, dstate)
        return next_tok, new_d

    def _step_impl_paged(self, params, state, dstate, btab, tokens, pos,
                         reset, active, seeds, temps, topk):
        """Paged step: the page table is a positional arg (donation-friendly
        ordering: state right after params/state, (S,)-data after)."""
        return self._step_impl(params, state, dstate, tokens, pos, reset,
                               active, seeds, temps, topk, btab=btab)

    def _prefill_impl(self, params, state, dstate, btab, tokens, start, n,
                      reset):
        """Chunked prefill for all S slots in ONE call: slot i consumes
        ``n[i]`` prompt tokens ``tokens[i, :n[i]]`` at positions
        ``start[i]..start[i]+n[i]-1``. ``n == 0`` rows are inert: their KV
        writes land in the scratch block (all-zero table rows) and their
        state rows are frozen. One fixed (S, chunk_tokens) shape → one XLA
        program regardless of how many slots are mid-prefill."""
        from deeplearning4j_tpu.exec.programs import is_registering
        if not is_registering():
            self._m_kv_programs.inc()
        params = dequantize_tree(params)
        S = self.slots

        def wipe(a):
            r = reset.reshape((S,) + (1,) * (a.ndim - 1))
            return jnp.where(r, jnp.zeros_like(a), a)

        # a fresh slot's FIRST device call may be a prefill chunk, so the
        # reset wipe lives here too (same rule as the step)
        dstate = map_slot_leaves(wipe, dstate)
        x = jax.nn.one_hot(tokens, self.vocab, dtype=jnp.float32)
        _, new_d = self.model.prefill_chunk(params, state, dstate, x, start,
                                            n, block_tables=btab)
        live = n > 0

        def freeze(new, old):
            a = live.reshape((S,) + (1,) * (new.ndim - 1))
            return jnp.where(a, new, old)

        return map_slot_leaves(freeze, new_d, dstate)

    def _cow_impl(self, dstate, src, dst):
        """Copy-on-write: clone pool block ``src`` into ``dst`` (both (1,)
        int32) across every pool leaf. Runs when a request claims a
        partially-matching cached prefix block and will overwrite its tail."""
        from deeplearning4j_tpu.exec.programs import is_registering
        if not is_registering():
            self._m_kv_programs.inc()
        return map_pool_leaves(lambda a: a.at[dst].set(a[src]), dstate)

    # ------------------------------------------------------------ lifecycle
    def _ensure_dstate(self):
        if self._dstate is None:
            if self.kv == "paged":
                self._dstate = self.model.init_decode_state(
                    self.slots, self.max_len,
                    kv={"num_blocks": self._pool.num_blocks,
                        "block_size": self.kv_block_size})
            else:
                self._dstate = self.model.init_decode_state(self.slots,
                                                            self.max_len)
        if self._draft is not None:
            self._draft.ensure_state()

    def start(self) -> "DecodeEngine":
        self._ensure_dstate()
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        err = BatcherStoppedError("decode engine stopped")
        with self._cv:
            while self._kv_ops:
                _fn, fut = self._kv_ops.popleft()
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(err)
            if self._pending_restores:
                # land claimed-but-pending tier promotions so evictable
                # restored blocks hold real content across a restart
                pend, self._pending_restores = self._pending_restores, {}
                self._apply_host_rows(list(pend.items()))
            if self._pending_swap is not None:
                # a swap staged against a stopping engine still applies (and
                # unblocks its waiter) — a restart serves the new weights
                self._apply_swap_locked()
            pending = list(self._queue)
            self._queue.clear()
            live = [r for r in self._slot_reqs if r is not None]
            self._slot_reqs = [None] * self.slots
            if self._pool is not None:
                # aborted streams never publish prefix blocks (their KV is
                # incomplete); everything they claimed goes back to the pool
                for r in live:
                    for b in r.kv_blocks:
                        self._pool.decref(b)
                    r.kv_blocks = []
                for src, _dst in self._pending_cows:
                    self._pool.decref(src)   # dst was freed via r.kv_blocks
                self._pending_cows = []
                self._tables[:] = 0
                self._kv_blocked = False
        for r in pending + live:
            if not r.future.done():
                self._journal_terminal(r, "error")
                r.future.set_exception(err)

    def warmup(self, aot: Optional[str] = None):
        """Compile the (single) decode-step program through the persistent
        compile cache before the first request — runs one all-inactive step
        so a fresh process pays ~0 compile on its first ``generate``.

        ``aot``: path to an AOT artifact (exec/aot.py). Every program found
        there — the step, the paged prefill/copy-on-write side programs,
        the spec draft/verify pair — is deserialized in milliseconds
        instead of retraced; its inert warmup call below doubles as the
        validation run. ``trace_count`` stays 0 for restored programs
        (restores count in ``dl4jtpu_aot_restores_total``). Any miss falls
        back to trace-and-save, merging the fresh executable back into the
        artifact."""
        from deeplearning4j_tpu.util.compile_cache import setup_compile_cache
        setup_compile_cache()
        self._ensure_dstate()
        if self._thread is not None and self._thread.is_alive():
            return self.warmup_seconds    # loop thread owns the state now
        bundle = None
        restored = {}
        if aot is not None:
            from deeplearning4j_tpu.exec import aot as aot_mod
            p0, s0 = self._weights()
            sig = aot_mod.model_signature(p0, s0)
            bundle, _reason = aot_mod.open_bundle(aot, sig, self.precision)
            if bundle is None:
                bundle = aot_mod.AotBundle(sig, self.precision)
            originals = {k: p for k, p in self._aot_programs().items()}
            for kind in originals:
                prog = bundle.restore(self._aot_key(kind), engine=self.id)
                if prog is not None:
                    restored[kind] = prog
            self._swap_programs(restored)
        try:
            self._warmup_run()
        except Exception:
            if not restored:
                raise
            # a restored executable failed its validation run (drift the
            # artifact envelope could not catch): drop back to the traced
            # programs wholesale; the failed call may have consumed the
            # donated state trees, so rebuild them before retracing
            from deeplearning4j_tpu.exec.aot import note_miss
            note_miss("corrupt")
            self._swap_programs(originals)
            restored = {}
            self._dstate = None
            if self._draft is not None:
                self._draft._tree = None
            self._ensure_dstate()
            self._warmup_run()
        if bundle is not None and self._aot_export(bundle, restored):
            bundle.save(aot)
        return self.warmup_seconds

    def _warmup_run(self):
        S = self.slots
        z = np.zeros(S, np.int32)
        f = np.zeros(S, bool)
        t0 = time.perf_counter()
        params, state = self._weights()
        c0 = self._m_compiled.value
        step_args = (z, z, f, f, np.zeros(S, np.uint32),
                     np.zeros(S, np.float32), z)
        if self.kv == "paged":
            step_args = (np.zeros((S, self.kv_max_blocks), np.int32),
                         ) + step_args
        tok, self._dstate = self._step(params, state, self._dstate,
                                       *step_args)
        jax.block_until_ready(tok)
        # the paged side programs compile here too — a no-op chunk (every
        # n == 0) and a scratch self-copy leave the state bitwise intact
        if self._prefill is not None:
            self._dstate = self._prefill(
                params, state, self._dstate,
                np.zeros((S, self.kv_max_blocks), np.int32),
                np.zeros((S, self.chunk_tokens), np.int32), z, z, f)
        if self._cow is not None:
            self._dstate = self._cow(self._dstate, np.zeros(1, np.int32),
                                     np.zeros(1, np.int32))
        if self._spec is not None:
            # the draft and verify programs compile here too: an
            # all-inert draft tick and an all-inert verify (n_in == 0
            # everywhere) leave both state trees bitwise intact
            zk = np.zeros((S, self._spec_k), np.int32)
            zn = np.zeros((S, self._spec_tree.n_nodes), np.int32)
            u, fl = np.zeros(S, np.uint32), np.zeros(S, np.float32)
            self._draft.step(zk, z, z, z, z, f, u, fl, z)
            vargs = (zn, z, z, f, u, fl, z)
            if self.kv == "paged":
                vargs = (np.zeros((S, self.kv_max_blocks), np.int32),
                         ) + vargs
            *_, self._dstate = self._verifier.run(
                params, state, self._dstate, *vargs)
        jax.block_until_ready(self._dstate)
        self.warmup_seconds = time.perf_counter() - t0
        if self._m_compiled.value > c0:
            self._register_program(params, state, step_args,
                                   self.warmup_seconds)
        return self.warmup_seconds

    # ---------------------------------------------------------------- AOT
    def _aot_programs(self) -> dict:
        """The engine's hot programs by artifact kind (the current
        callables — traced jits before a restore, Compiled after)."""
        progs = {"step": self._step}
        if self._prefill is not None:
            progs["prefill"] = self._prefill
        if self._cow is not None:
            progs["cow"] = self._cow
        if self._draft is not None:
            progs["draft"] = self._draft._run
            progs["verify"] = self._verifier._jit
        return progs

    def _swap_programs(self, progs: dict) -> None:
        if "step" in progs:
            self._step = progs["step"]
        if "prefill" in progs:
            self._prefill = progs["prefill"]
        if "cow" in progs:
            self._cow = progs["cow"]
        if self._draft is not None:
            if "draft" in progs:
                self._draft._run = progs["draft"]
            if "verify" in progs:
                self._verifier._jit = progs["verify"]

    def _aot_key(self, kind: str) -> str:
        """Artifact key of one decode program: every shape-determining
        knob is in the key, so a config change is a key miss (retrace),
        never a stale restore."""
        parts = [f"decode:{kind}", f"S{self.slots}", f"L{self.max_len}",
                 f"kv={self.kv}"]
        if self.kv == "paged":
            parts.append(f"bs{self.kv_block_size}"
                         f":nb{self._pool.num_blocks}")
        if kind == "prefill":
            parts.append(f"c{self.chunk_tokens}")
        if kind in ("draft", "verify"):
            # the tree shape sizes both programs (draft scan width is
            # d+1, verify window is the node count)
            parts.append(
                "t" + ",".join(str(k) for k in self._spec_tree.kvec))
        if kind == "draft":
            from deeplearning4j_tpu.exec import aot as aot_mod
            dp, ds = self._draft._weights()
            parts.append(aot_mod.model_signature(dp, ds)[:12])
        return ":".join(parts)

    def _aot_export(self, bundle, restored: dict) -> int:
        """Serialize every program NOT restored into ``bundle`` (the
        trace-and-save half); returns how many were added."""
        from deeplearning4j_tpu.exec import aot as aot_mod
        S = self.slots
        params, state = self._weights()
        z = np.zeros(S, np.int32)
        f = np.zeros(S, bool)
        u, fl = np.zeros(S, np.uint32), np.zeros(S, np.float32)
        added = 0

        def put(kind, fn, args):
            nonlocal added
            if kind in restored:
                return                  # already in the artifact
            bundle.add_compiled(self._aot_key(kind),
                                aot_mod.export_compiled(fn, args))
            added += 1

        step_args = (z, z, f, f, u, fl, z)
        if self.kv == "paged":
            step_args = (np.zeros((S, self.kv_max_blocks), np.int32),
                         ) + step_args
        put("step", self._step, (params, state, self._dstate) + step_args)
        if self._prefill is not None:
            put("prefill", self._prefill,
                (params, state, self._dstate,
                 np.zeros((S, self.kv_max_blocks), np.int32),
                 np.zeros((S, self.chunk_tokens), np.int32), z, z, f))
        if self._cow is not None:
            put("cow", self._cow,
                (self._dstate, np.zeros(1, np.int32), np.zeros(1, np.int32)))
        if self._draft is not None:
            zk = np.zeros((S, self._spec_k), np.int32)
            zn = np.zeros((S, self._spec_tree.n_nodes), np.int32)
            dp, ds = self._draft._weights()
            put("draft", self._draft._run,
                (dp, ds, self._draft._tree, zk, z, z, z, z, f, u, fl, z))
            vargs = (zn, z, z, f, u, fl, z)
            if self.kv == "paged":
                vargs = (np.zeros((S, self.kv_max_blocks), np.int32),
                         ) + vargs
            put("verify", self._verifier._jit,
                (params, state, self._dstate) + vargs)
        return added

    def _register_program(self, params, state, step_args, wall):
        """Record the (single) decode-step program's cost/memory analysis
        in the process program registry (``GET /programs``, MFU gauges).
        Uses the post-step ``self._dstate`` — same shapes as the donated
        input state."""
        from deeplearning4j_tpu.exec.programs import get_programs
        get_programs().record(self.id, "step", self._step,
                              (params, state, self._dstate) + tuple(step_args),
                              compile_seconds=wall)

    # ------------------------------------------------------------ scheduler
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               seed: int = 0, temperature: float = 0.0,
               top_k: int = 0, request_id: Optional[str] = None,
               tenant: str = "default", priority: str = "normal") -> Future:
        """Enqueue one generation request; returns a Future resolving to
        ``{"tokens": [...], "prompt_len": int}``. ``request_id`` /
        ``tenant`` / ``priority`` ride into the request's wide-event
        journal record (and histogram exemplars)."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must contain at least one token id")
        if not all(0 <= t < self.vocab for t in prompt):
            raise ValueError(f"token ids must be in [0, {self.vocab})")
        if len(prompt) + int(max_new_tokens) > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens})"
                f" exceeds engine capacity max_len={self.max_len}")
        if self._pool is not None:
            need = blocks_for_span(len(prompt) + int(max_new_tokens) - 1,
                                   self.kv_block_size)
            if need > self._pool.usable:
                raise ValueError(
                    f"request needs {need} KV blocks "
                    f"(block_size={self.kv_block_size}) but the pool holds "
                    f"{self._pool.usable} — it could never be admitted")
        if self._stop.is_set() and self._thread is not None:
            raise BatcherStoppedError("decode engine stopped")
        fut = Future()
        ctx = get_context()
        req = _Request(prompt, max_new_tokens, seed, temperature, top_k, fut,
                       rid=request_id, tenant=tenant, priority=priority,
                       trace_id=ctx.trace_id if ctx is not None else None)
        with self._cv:
            if len(self._queue) >= self.max_queue:
                # a rejected request still leaves exactly one terminal
                # wide event — the journal never under-counts sheds
                self._journal_terminal(req, "shed")
                raise ServerOverloadedError(
                    f"decode queue full ({self.max_queue})")
            self._queue.append(req)
            self._cv.notify_all()
        return fut

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 32,
                 seed: int = 0, temperature: float = 0.0,
                 top_k: int = 0, timeout: Optional[float] = None,
                 request_id: Optional[str] = None, tenant: str = "default",
                 priority: str = "normal") -> dict:
        """Blocking ``submit`` — the one-call API the HTTP endpoint uses."""
        return self.submit(prompt, max_new_tokens, seed, temperature,
                           top_k, request_id=request_id, tenant=tenant,
                           priority=priority).result(timeout=timeout)

    def _admit_locked(self):
        if self._pending_swap is not None:
            return          # admission pauses so live slots can drain
        blocked = False
        for i in range(self.slots):
            if not self._queue:
                break
            if self._slot_reqs[i] is not None:
                continue
            r = self._queue[0]
            if self._pool is not None:
                try:
                    self._claim_kv(r, i)
                except PoolExhaustedError:
                    # head-of-line blocking is deliberate: the request at
                    # the queue head admits as soon as blocks free up (no
                    # starvation of long prompts by short ones)
                    if not self._kv_blocked:
                        self._m_kv_exhausted.inc()
                    blocked = True
                    break
            self._queue.popleft()
            self._slot_reqs[i] = r
            r.t_admit = time.perf_counter()
            self._m_queue.observe(r.t_admit - r.t_start, exemplar=r.rid)
        if self._pool is not None:
            self._kv_blocked = blocked

    def _claim_kv(self, r, slot):
        """Claim pool blocks + build the page-table row for one admitted
        request (loop thread, under ``self._cv``). Prefix-cache hits claim
        cached blocks read-only (refcount++) and skip their prefill span;
        a partial tail match is claimed via copy-on-write. All-or-nothing:
        on exhaustion every claimed ref is returned and the request stays
        queued."""
        bs = self.kv_block_size
        plen = len(r.prompt)
        # KV positions written: 0 .. plen + max_new - 2 (the final sampled
        # token is returned, never fed back)
        need = blocks_for_span(plen + r.max_new - 1, bs)
        shared, cow, skip = [], None, 0
        if self._prefix is not None:
            r0 = (self._m_host_restores.value
                  if self._host_tier is not None else 0)
            shared, cow, skip = self._prefix.match(r.prompt)
            if self._host_tier is not None:
                # match runs serially on the loop thread, so the counter
                # delta is exactly this request's tier promotions
                r.host_restores = int(self._m_host_restores.value - r0)
        try:
            fresh = self._pool.alloc(need - len(shared))
        except PoolExhaustedError:
            for b in shared:
                self._pool.decref(b)
            if cow is not None:
                self._pool.decref(cow[0])
            raise
        if cow is not None:
            # clone the partially-matching cached block into our first
            # fresh block; the copy program runs before this slot's first
            # prefill/step, and the source ref is dropped after the copy
            self._pending_cows.append((cow[0], fresh[0]))
        if skip:
            self._m_prefix_hits.inc()
            self._m_prefix_saved.inc(skip)
        r.kv_blocks = shared + fresh
        r.cursor = skip                  # prefill resumes past the reuse
        r.prefix_hit = skip
        row = self._tables[slot]
        row[:] = 0
        row[:need] = r.kv_blocks

    def _release_kv(self, slot, r):
        """Return a finished request's blocks to the pool (loop thread).
        Publication into the prefix cache happens FIRST so blocks whose
        refcount drops to zero park in the evictable LRU instead of the
        free list. This is the full-release path slot re-claim depends on:
        occupancy returns to baseline once nothing references the blocks."""
        if not r.kv_blocks:
            return
        if self._prefix is not None:
            self._prefix.insert(r.prompt, r.kv_blocks)
        for b in r.kv_blocks:
            self._pool.decref(b)
        r.kv_blocks = []
        self._tables[slot][:] = 0

    # ------------------------------------------------------ wide events
    def _journal_terminal(self, r, outcome, kv_peak: int = 0):
        """Append the request's ONE terminal wide event (completions and
        rejections alike). Pure host-side bookkeeping — no device work."""
        now = time.perf_counter()
        phases = {}
        if r.t_admit is not None:
            phases["queue"] = r.t_admit - r.t_start
            if r.t_first is not None:
                phases["prefill"] = r.t_first - r.t_admit
                phases["decode"] = (r.t_last or r.t_first) - r.t_first
        else:
            phases["queue"] = now - r.t_start
        if r.verify_s:
            phases["verify"] = r.verify_s
        rec = new_record(
            r.rid, "decode",
            trace_id=r.trace_id, outcome=outcome,
            tenant=r.tenant, priority=r.priority,
            engine=self.id, model_version=self._version,
            tokens_in=len(r.prompt), tokens_out=len(r.generated),
            wall_seconds=(r.t_last or now) - r.t_start,
            ttft_seconds=(r.t_first - r.t_start
                          if r.t_first is not None else None),
            first_prefill_chunk_seconds=(r.t_prefill0 - r.t_start
                                         if r.t_prefill0 is not None
                                         else None),
            phases=phases)
        if self._spec is not None:
            rec["spec"] = {"drafted": r.drafted, "accepted": r.accepted}
        if self._pool is not None:
            rec["kv"] = {"peak_blocks": kv_peak,
                         "prefix_hit_depth": r.prefix_hit,
                         "host_restores": r.host_restores}
        self.journal.append(rec)

    def _finish(self, slot, r, outcome):
        """Terminal accounting for one completed stream (loop thread):
        KV peak is captured BEFORE the release clears the block list,
        the slot is freed, the wide event lands, the future resolves."""
        kv_peak = len(r.kv_blocks)
        if self._pool is not None:
            self._release_kv(slot, r)
        with self._cv:
            self._slot_reqs[slot] = None   # freed; wiped on re-claim
        self._m_requests.inc()
        self._journal_terminal(r, outcome, kv_peak=kv_peak)
        r.future.set_result({"tokens": r.generated,
                             "prompt_len": len(r.prompt)})

    # ----------------------------------------- host-side block movement
    # Migration, spill, and restore move KV as HOST bytes: one numpy
    # gather/scatter per pool leaf with a jnp.asarray round-trip back into
    # the (re-donated) decode-state tree. No jitted gather/scatter program
    # exists for any of it — the compile-count pins (one step program, ≤2
    # kv side programs) are untouched by design.

    def _pool_leaf_items(self):
        """``[(key, leaf)]`` for the pool leaves of the decode state,
        with tree-path keys stable across engines of the same model (the
        migration wire format's leaf identity)."""
        from deeplearning4j_tpu.serving.kv import is_pool_path
        flat, _ = jax.tree_util.tree_flatten_with_path(self._dstate)
        return [(jax.tree_util.keystr(path), leaf)
                for path, leaf in flat if is_pool_path(path)]

    def _gather_rows(self, bids):
        """Per-leaf host gather of the given blocks: key -> ``(n, bs, H,
        Dh)`` numpy array."""
        idx = np.asarray(bids, np.int64)
        return {k: np.asarray(leaf)[idx]
                for k, leaf in self._pool_leaf_items()}

    def _apply_host_rows(self, writes):
        """Scatter ``[(bid, {leaf key: (bs, H, Dh) row})]`` into the pool
        leaves through one host round-trip per touched leaf."""
        if not writes:
            return
        from deeplearning4j_tpu.serving.kv import is_pool_path
        flat, treedef = jax.tree_util.tree_flatten_with_path(self._dstate)
        leaves = [leaf for _, leaf in flat]
        keymap = {jax.tree_util.keystr(path): i
                  for i, (path, _) in enumerate(flat)
                  if is_pool_path(path)}
        arrs = {}
        for bid, rows in writes:
            for key, row in rows.items():
                i = keymap[key]
                if i not in arrs:
                    arrs[i] = np.array(leaves[i])
                arrs[i][bid] = row
        for i, a in arrs.items():
            leaves[i] = jnp.asarray(a)
        self._dstate = jax.tree_util.tree_unflatten(treedef, leaves)

    # ------------------------------------------------- host-tier spill/restore
    def _spill_block(self, chain_hash, parent, tokens, bid):
        """Pool-eviction hook (loop thread, via PrefixCache._drop):
        demote the evicted block's device rows to the host tier. Must
        never raise — an exception here would leak the block mid-alloc —
        so any failure degrades to a plain drop."""
        try:
            if self._pending_restores.pop(bid, None) is not None:
                # the block was claimed from the tier but its data never
                # landed on device; the tier still holds the content
                return
            rows = self._gather_rows([bid])
            self._host_tier.put(chain_hash, parent, tokens,
                                {k: v[0] for k, v in rows.items()})
        except Exception:
            pass

    def _restore_block(self, chain_hash, tokens):
        """Second-chance hook (loop thread, from PrefixCache.match):
        claim a fresh pool block for a tier hit and queue its host→device
        scatter on the pre-step batch. Returns the bid (refcount 1 — the
        claim belongs to the matching request) or None under pool
        pressure, which the cache treats as a plain miss."""
        entry = self._host_tier.get(chain_hash)
        if entry is None:
            return None
        try:
            bid = self._pool.alloc(1)[0]
        except PoolExhaustedError:
            return None
        self._pending_restores[bid] = entry.rows
        self._m_host_restores.inc()
        return bid

    # ------------------------------------------------------------ migration
    def _drain_kv_ops_locked(self):
        """Run queued export/import closures (caller holds ``self._cv``,
        loop thread, step boundary — the only point where the donated
        decode state may be read or rebuilt)."""
        while self._kv_ops:
            fn, fut = self._kv_ops.popleft()
            if fut.set_running_or_notify_cancel():
                try:
                    fut.set_result(fn())
                except BaseException as e:
                    fut.set_exception(e)

    def _run_kv_op(self, fn):
        """Marshal ``fn`` onto the loop thread (or run it inline at a
        safe point when the loop isn't running) and return its result."""
        fut = Future()
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                self._kv_ops.append((fn, fut))
                self._cv.notify_all()
            else:
                self._ensure_dstate()
                if fut.set_running_or_notify_cancel():
                    try:
                        fut.set_result(fn())
                    except BaseException as e:
                        fut.set_exception(e)
        return fut.result(timeout=60.0)

    def _migrate_envelope(self):
        """The validity envelope a payload must match to land here: the
        AOT-bundle discipline (exec/aot.py) applied to KV — same
        architecture (shape/dtype signature of the SERVING weights), same
        serving precision, same block geometry, same vocabulary."""
        from deeplearning4j_tpu.exec import aot as aot_mod
        p, s = self._weights()
        return {"model_sig": aot_mod.model_signature(p, s),
                "precision": self.precision,
                "block_size": self.kv_block_size,
                "vocab": int(self.vocab)}

    def kv_export(self, prompt: Sequence[int]) -> dict:
        """Serialize the cached block chain covering ``prompt``'s full
        blocks into a migration payload (kv/migrate.py) — the
        prefill-replica half of disaggregated serving. The chain must
        already be published (the prefill ran to completion here);
        otherwise ``KVMigrateError(reason='no_chain')``."""
        from deeplearning4j_tpu.serving.kv import KVMigrateError, pack_chain
        from deeplearning4j_tpu.serving.kv.prefix import _ROOT, _chain_hash
        if self._prefix is None:
            raise ValueError(
                "kv_export requires kv='paged' with prefix_cache=True")
        toks = [int(t) for t in prompt]

        def op():
            bs = self.kv_block_size
            bids, chain = [], []
            h = _ROOT
            for j in range(len(toks) // bs):
                blk = toks[j * bs:(j + 1) * bs]
                h = _chain_hash(h, blk)
                bid = self._prefix._by_hash.get(h)
                if bid is None:
                    break
                bids.append(bid)
                chain.extend(blk)
            if not bids:
                raise KVMigrateError(
                    "no cached chain covers this prompt's first block — "
                    "run the prefill to completion here before exporting",
                    reason="no_chain")
            payload = pack_chain(self._gather_rows(bids), chain,
                                 self._migrate_envelope())
            self._m_migrate_exports.inc()
            return payload

        return self._run_kv_op(op)

    def kv_import(self, payload: dict) -> dict:
        """Restore a migrated chain into this engine's pool: validate the
        whole payload against the local envelope (no side effects on any
        mismatch), allocate fresh blocks, scatter the rows host-side, and
        rebind the page-table identity by re-indexing the same token
        chain in the prefix cache — continued decode is then an ordinary
        (bitwise-exact) prefix hit. The decode-replica half."""
        from deeplearning4j_tpu.serving.kv import (KVMigrateError,
                                                   unpack_chain)
        if self._prefix is None:
            raise ValueError(
                "kv_import requires kv='paged' with prefix_cache=True")

        def op():
            leaves = dict(self._pool_leaf_items())
            tokens, rows = unpack_chain(payload, self._migrate_envelope(),
                                        leaves)
            n = len(tokens) // self.kv_block_size
            try:
                bids = self._pool.alloc(n)
            except PoolExhaustedError as e:
                raise KVMigrateError(
                    f"destination pool cannot hold the chain: {e}",
                    reason="exhausted")
            self._apply_host_rows(
                [(bid, {k: rows[k][j] for k in rows})
                 for j, bid in enumerate(bids)])
            added = self._prefix.insert(tokens, bids)
            for b in bids:
                # indexed blocks park in the evictable LRU (cache
                # entries); blocks the chain already had free right back
                self._pool.decref(b)
            self._m_migrate_imports.inc()
            return {"imported_blocks": added,
                    "duplicate_blocks": n - added, "tokens": len(tokens)}

        try:
            return self._run_kv_op(op)
        except KVMigrateError as e:
            self._m_migrate_rejects.labels(
                engine=self.id, reason=e.reason).inc()
            raise

    def _loop(self):
        S = self.slots
        while not self._stop.is_set():
            with self._cv:
                self._drain_kv_ops_locked()
                if (self._pending_swap is not None
                        and all(r is None for r in self._slot_reqs)):
                    # step boundary with no live slots: every in-flight
                    # generation ran end-to-end on the old weights
                    self._apply_swap_locked()
                self._admit_locked()
                live = [(i, r) for i, r in enumerate(self._slot_reqs)
                        if r is not None]
                if not live:
                    self._cv.wait(timeout=0.05)
                    continue
            params, state = self._weights()
            if self._pending_restores:
                # host-tier promotions land BEFORE anything can read the
                # claimed blocks — including the CoW program below, whose
                # source may itself be a just-restored block
                with self._cv:
                    pend, self._pending_restores = self._pending_restores, {}
                self._apply_host_rows(list(pend.items()))
            if self._pending_cows:
                # copy-on-write claims run BEFORE the claimer's first
                # prefill/step can read (or overwrite) the cloned block
                cows, self._pending_cows = self._pending_cows, []
                for src, dst in cows:
                    self._dstate = self._cow(self._dstate,
                                             np.full(1, src, np.int32),
                                             np.full(1, dst, np.int32))
                    self._pool.decref(src)
                    self._m_cow.inc()
            if self.chunk_tokens is not None:
                # chunked prefill rides the same iteration cadence: slots
                # still consuming their prompt advance by up to K positions
                # per iteration while decode-phase slots step one token
                pre = [(i, r) for i, r in live
                       if r.cursor < len(r.prompt) - 1]
                if pre:
                    K = self.chunk_tokens
                    ptok = np.zeros((S, K), np.int32)
                    pstart = np.zeros(S, np.int32)
                    pn = np.zeros(S, np.int32)
                    preset = np.zeros(S, bool)
                    t_chunk = time.perf_counter()
                    for i, r in pre:
                        k = min(K, len(r.prompt) - 1 - r.cursor)
                        ptok[i, :k] = r.prompt[r.cursor:r.cursor + k]
                        pstart[i] = r.cursor
                        pn[i] = k
                        preset[i] = r.fresh
                        r.fresh = False
                        r.cursor += k
                        if r.t_prefill0 is None:
                            r.t_prefill0 = t_chunk
                    with trace.span("decode_prefill", chunks=len(pre)):
                        self._dstate = self._prefill(
                            params, state, self._dstate,
                            jnp.asarray(self._tables), ptok, pstart, pn,
                            preset)
                    self._m_prefill_chunks.inc(len(pre))
                    self._m_prefill_tokens.inc(int(pn.sum()))
                # slots that finished their chunk this iteration join the
                # step below (cursor is now at the last prompt position)
                live = [(i, r) for i, r in live
                        if r.cursor >= len(r.prompt) - 1]
                if not live:
                    continue
            if self._spec is not None:
                self._tick_spec(live, params, state)
                continue
            tokens = np.zeros(S, np.int32)
            pos = np.zeros(S, np.int32)
            reset = np.zeros(S, bool)
            active = np.zeros(S, bool)
            seeds = np.zeros(S, np.uint32)
            temps = np.zeros(S, np.float32)
            topk = np.zeros(S, np.int32)
            for i, r in live:
                active[i] = True
                reset[i] = r.fresh
                r.fresh = False
                p = r.cursor
                tokens[i] = (r.prompt[p] if p < len(r.prompt)
                             else r.generated[-1])
                pos[i] = p
                seeds[i] = r.seed & 0xFFFFFFFF
                temps[i] = r.temperature
                topk[i] = r.top_k
            t0 = time.perf_counter()
            c0 = self._m_compiled.value
            step_args = (tokens, pos, reset, active, seeds, temps, topk)
            if self._pool is not None:
                # inactive slots get an all-zero table row so their masked
                # write lands in the scratch block — a mid-prefill slot's
                # REAL row here would let the step corrupt its block 0
                btab = np.where(active[:, None], self._tables, 0)
                step_args = (jnp.asarray(btab.astype(np.int32)),) + step_args
            with trace.span("decode_step", active=len(live)):
                nt, self._dstate = self._step(params, state, self._dstate,
                                              *step_args)
                nt = np.asarray(nt)
            dt = time.perf_counter() - t0
            if self._m_compiled.value > c0:
                self._register_program(params, state, step_args, dt)
            self._decode_seconds += dt
            self._m_steps.inc()
            self._m_occupancy.set(len(live))
            self._m_token_seconds.observe(dt)
            now = t0 + dt                        # the post-sync host stamp
            done = []
            for i, r in live:
                r.cursor += 1
                if r.cursor < len(r.prompt):
                    if r.t_prefill0 is None:
                        r.t_prefill0 = now
                    continue                     # still prefilling
                tok = int(nt[i])
                r.generated.append(tok)
                self._m_tokens.inc()
                if r.t_first is None:
                    if r.t_prefill0 is None:
                        r.t_prefill0 = now       # 1-token prompt: the
                    r.t_first = now              # prefill WAS this step
                    self._m_ttft.observe(now - r.t_start, exemplar=r.rid)
                else:
                    self._m_itl.observe(now - r.t_last, exemplar=r.rid)
                r.t_last = now
                if ((self.eos_id is not None and tok == self.eos_id)
                        or len(r.generated) >= r.max_new
                        or r.cursor >= self.max_len):
                    outcome = ("eos" if (self.eos_id is not None
                                         and tok == self.eos_id)
                               else "max_new")
                    done.append((i, r, outcome))
            for i, r, outcome in done:
                # full release on eos/length: every claimed block's
                # refcount returns to the pool (prefix-cached blocks
                # park in the evictable LRU, everything else frees)
                self._finish(i, r, outcome)
        self._m_occupancy.set(0)

    # ------------------------------------------------------- speculative tick
    def _tick_spec(self, live, params, state):
        """One speculative scheduler iteration. At most THREE device calls
        regardless of slot mix, each a fixed-shape compiled-once program:

        1. one DRAFT call — prompt catch-up rows (the draft prefills the
           prompt independently, up to k positions per tick) and ready
           generation rows (propose k tokens) share it, masks not shapes;
        2. one target STEP — rows still consuming their prompt through
           the plain path (no chunked prefill) ride the ordinary step
           program with its sampled output ignored;
        3. one VERIFY — every ready row's k-token window in one batched
           multi-position target step; the host appends the oracle's
           emitted prefix (1..k tokens per slot per tick).

        A row is 'ready' once the draft has caught up to the target
        cursor; a fresh slot becomes ready after ceil((plen-1)/k) draft
        ticks, which overlap the target's own prefill steps. Catch-up
        feeds the whole known STREAM (prompt + generated), not just the
        prompt: a side-branch acceptance leaves the draft's carries
        behind the emitted stream (its snapshots follow its own spine),
        and the resync path replays the emitted tokens it missed."""
        S, K = self.slots, self._spec_k
        tr = self._spec_tree

        def stok(r, p):
            pl = len(r.prompt)
            return r.prompt[p] if p < pl else r.generated[p - pl]

        catchup, ready, tpre = [], [], []
        for i, r in live:
            plen = len(r.prompt)
            known = plen + len(r.generated)
            if r.cursor < plen - 1:
                tpre.append((i, r))
            if r.draft_cursor < known - 1:
                catchup.append((i, r, known))
            elif r.cursor >= plen - 1 and r.draft_cursor == r.cursor:
                # the window may not outrun the request budget or the KV
                # capacity — same write bound as the plain path
                n_in = min(K, r.max_new - len(r.generated),
                           self.max_len - r.cursor)
                if n_in > 0:
                    ready.append((i, r, n_in))
        dprops = dsides = None
        if catchup or ready:
            given = np.zeros((S, K), np.int32)
            n_given = np.zeros(S, np.int32)
            n_steps = np.zeros(S, np.int32)
            dpos = np.zeros(S, np.int32)
            sel = np.zeros(S, np.int32)
            dreset = np.zeros(S, bool)
            dseeds = np.zeros(S, np.uint32)
            dtemps = np.zeros(S, np.float32)
            dtopk = np.zeros(S, np.int32)
            for i, r, known in catchup:
                m = min(K, known - 1 - r.draft_cursor)
                given[i, :m] = [stok(r, p) for p in
                                range(r.draft_cursor, r.draft_cursor + m)]
                n_given[i] = m
                n_steps[i] = m
                dpos[i] = r.draft_cursor
                sel[i] = r.draft_sel
                dreset[i] = r.draft_fresh
                r.draft_fresh = False
                r.draft_cursor += m
                r.draft_sel = m - 1
            for i, r, n_in in ready:
                p = r.cursor
                given[i, 0] = stok(r, p)
                n_given[i] = 1
                n_steps[i] = n_in
                dpos[i] = p
                sel[i] = r.draft_sel
                dreset[i] = r.draft_fresh
                r.draft_fresh = False
                dseeds[i] = r.seed & 0xFFFFFFFF
                dtemps[i] = r.temperature
                dtopk[i] = r.top_k
            t0 = time.perf_counter()
            with trace.span("spec_draft", rows=len(catchup) + len(ready)):
                dprops, dsides = self._draft.step(given, n_given, n_steps,
                                                  dpos, sel, dreset,
                                                  dseeds, dtemps, dtopk)
            self._m_spec_draft_seconds.observe(time.perf_counter() - t0)
        if tpre:
            # plain-path prompt consumption rides the ordinary step
            # program (the sampled token is ignored mid-prompt, exactly
            # as in the non-speculative loop)
            tokens = np.zeros(S, np.int32)
            pos = np.zeros(S, np.int32)
            reset = np.zeros(S, bool)
            active = np.zeros(S, bool)
            seeds = np.zeros(S, np.uint32)
            temps = np.zeros(S, np.float32)
            topk = np.zeros(S, np.int32)
            for i, r in tpre:
                active[i] = True
                reset[i] = r.fresh
                r.fresh = False
                tokens[i] = r.prompt[r.cursor]
                pos[i] = r.cursor
                seeds[i] = r.seed & 0xFFFFFFFF
                temps[i] = r.temperature
                topk[i] = r.top_k
            t0 = time.perf_counter()
            c0 = self._m_compiled.value
            step_args = (tokens, pos, reset, active, seeds, temps, topk)
            if self._pool is not None:
                btab = np.where(active[:, None], self._tables, 0)
                step_args = (jnp.asarray(btab.astype(np.int32)),) + step_args
            with trace.span("decode_step", active=len(tpre)):
                _, self._dstate = self._step(params, state, self._dstate,
                                             *step_args)
            dt = time.perf_counter() - t0
            if self._m_compiled.value > c0:
                self._register_program(params, state, step_args, dt)
            self._decode_seconds += dt
            self._m_steps.inc()
            for i, r in tpre:
                r.cursor += 1
                if r.t_prefill0 is None:
                    r.t_prefill0 = t0 + dt
        done = []
        if ready:
            vtok = np.zeros((S, tr.n_nodes), np.int32)
            vpos = np.zeros(S, np.int32)
            vn = np.zeros(S, np.int32)
            vreset = np.zeros(S, bool)
            vseeds = np.zeros(S, np.uint32)
            vtemps = np.zeros(S, np.float32)
            vtopk = np.zeros(S, np.int32)
            for i, r, n_in in ready:
                # the slot's token tree: node 0 = the last emitted (or
                # final prompt) token; each depth-d group = the draft's
                # own proposal (the spine continuation, child 0) plus
                # its k_d-1 masked top-logit alternatives — every node
                # is judged against the oracle computed from the
                # target's distribution AT that node
                vtok[i, 0] = given[i, 0]
                for dd in range(1, tr.d + 1):
                    fst, kd = int(tr.first[dd - 1]), tr.kvec[dd - 1]
                    vtok[i, fst] = dprops[i, dd - 1]
                    if kd > 1:
                        vtok[i, fst + 1:fst + kd] = dsides[i, dd - 1,
                                                           :kd - 1]
                vpos[i] = r.cursor
                vn[i] = n_in
                vreset[i] = r.fresh
                r.fresh = False
                vseeds[i] = r.seed & 0xFFFFFFFF
                vtemps[i] = r.temperature
                vtopk[i] = r.top_k
            vargs = (vtok, vpos, vn, vreset, vseeds, vtemps, vtopk)
            if self._pool is not None:
                vlive = vn > 0
                btab = np.where(vlive[:, None], self._tables, 0)
                vargs = (jnp.asarray(btab.astype(np.int32)),) + vargs
            t0 = time.perf_counter()
            with trace.span("spec_verify", rows=len(ready)):
                etoks, acc, emit, sacc, self._dstate = self._verifier.run(
                    params, state, self._dstate, *vargs)
            dt = time.perf_counter() - t0
            now = t0 + dt                       # one stamp per verify run
            self._decode_seconds += dt
            self._m_steps.inc()
            self._m_token_seconds.observe(dt)
            drafted = accepted = 0
            for i, r, n_in in ready:
                # judged proposals: tree depths 1..min(d, n_in-1) plus
                # the budget-capped bonus slot — min(d, n_in) keeps the
                # rate's ceiling at 1.0 for full spine acceptance
                drafted += min(tr.d, n_in)
                accepted += int(acc[i])
                r.drafted += min(tr.d, n_in)
                r.accepted += int(acc[i])
                r.verify_s += dt
                self._m_spec_depth.observe(float(acc[i]))
                p0 = r.cursor
                consumed, finished, fin_eos = 0, False, False
                for j in range(int(emit[i])):
                    tok = int(etoks[i, j])
                    r.generated.append(tok)
                    self._m_tokens.inc()
                    consumed += 1
                    if ((self.eos_id is not None and tok == self.eos_id)
                            or len(r.generated) >= r.max_new
                            or r.cursor + consumed >= self.max_len):
                        finished = True
                        fin_eos = (self.eos_id is not None
                                   and tok == self.eos_id)
                        break
                if consumed:
                    # a verify emits an accepted RUN at one host point:
                    # one ITL sample per accepted token (run wall spread
                    # over the run), TTFT on the stream's first token
                    per = (now - (r.t_last if r.t_last is not None
                                  else r.t_start)) / consumed
                    if r.t_first is None:
                        r.t_first = now
                        self._m_ttft.observe(now - r.t_start,
                                             exemplar=r.rid)
                        n_itl = consumed - 1
                    else:
                        n_itl = consumed
                    for _ in range(n_itl):
                        self._m_itl.observe(per, exemplar=r.rid)
                    r.t_last = now
                r.cursor += consumed
                # draft resync: its carry snapshots follow its OWN spine,
                # valid through the spine-consistent accepted prefix —
                # resume from snapshot js (never past the emitted stream);
                # a side-branch acceptance leaves draft_cursor short and
                # the catch-up path replays the gap next tick
                js = max(0, min(consumed - 1, int(sacc[i])))
                r.draft_cursor = p0 + js + 1
                r.draft_sel = js
                if finished:
                    done.append((i, r, "eos" if fin_eos else "max_new"))
            self._m_spec_drafted.inc(drafted)
            self._m_spec_accepted.inc(accepted)
            tot = self._m_spec_drafted.value
            self._m_spec_rate.set(
                self._m_spec_accepted.value / tot if tot else 0.0)
        self._m_occupancy.set(len(live))
        for i, r, outcome in done:
            self._finish(i, r, outcome)

    # --------------------------------------------------------------- stats
    def _slo_stats(self) -> dict:
        """Request-lifecycle SLO snapshot: percentiles + the per-bucket
        last-exemplar request ids that link a bucket back to its journal
        record (docs/OBSERVABILITY.md "Request lifecycle")."""
        def block(h):
            out = {"count": int(h.count)}
            for q, key in ((0.5, "p50_ms"), (0.99, "p99_ms")):
                p = h.percentile(q)
                out[key] = round(p * 1e3, 4) if p is not None else None
            out["exemplars"] = [
                ["+Inf" if b == float("inf") else b, rid, v]
                for b, rid, v in h.exemplars()]
            return out
        return {"ttft": block(self._m_ttft),
                "itl": block(self._m_itl),
                "queue": block(self._m_queue)}

    def stats(self) -> dict:
        with self._cv:
            occupied = sum(r is not None for r in self._slot_reqs)
            queued = len(self._queue)
        toks = self._m_tokens.value
        kv = None
        if self._pool is not None:
            kv = dict(self.kv_pool_info())
            kv.update({
                "prefix_cache": self._prefix is not None,
                "chunk_tokens": self.chunk_tokens,
                "kv_programs": int(self._m_kv_programs.value),
                "prefix_hits": int(self._m_prefix_hits.value),
                "prefix_tokens_saved": int(self._m_prefix_saved.value),
                "cow_copies": int(self._m_cow.value),
                "prefill_chunks": int(self._m_prefill_chunks.value),
                "prefill_tokens": int(self._m_prefill_tokens.value),
                "exhausted_events": int(self._m_kv_exhausted.value),
                "migrate_exports": int(self._m_migrate_exports.value),
                "migrate_imports": int(self._m_migrate_imports.value),
            })
            if self._prefix is not None:
                # bounded chain-head digest: the prefix-affinity routing
                # signal the router scrapes from /stats
                kv["chain_heads"] = self._prefix.chain_heads()
            if self._host_tier is not None:
                kv["host_restores"] = int(self._m_host_restores.value)
        spec = None
        if self._spec is not None:
            drafted = int(self._m_spec_drafted.value)
            accepted = int(self._m_spec_accepted.value)
            depth = self._m_spec_depth
            spec = {"k": self._spec_tree.d,
                    "tree": list(self._spec_tree.kvec),
                    "tree_nodes": self._spec_tree.n_nodes,
                    "self_draft": self._spec.self_draft,
                    "draft_precision": self._draft.precision,
                    "drafted_tokens": drafted,
                    "accepted_tokens": accepted,
                    "acceptance_rate": (accepted / drafted if drafted
                                        else 0.0),
                    "mean_accepted_depth": (depth.sum / depth.count
                                            if depth.count else 0.0),
                    "verify_programs": self._verifier.programs,
                    "draft_programs": self._draft.programs}
        return {"id": self.id,
                "kv": kv,
                "spec": spec,
                "slots": self.slots,
                "max_len": self.max_len,
                "precision": self.precision,
                "weight_bytes": tree_bytes(self._weights()[0]),
                "model_version": self._version,
                "occupied_slots": occupied,
                "queued_requests": queued,
                "compiled_programs": self.trace_count,
                "steps": int(self._m_steps.value),
                "tokens": int(toks),
                "requests": int(self._m_requests.value),
                "decode_seconds": self._decode_seconds,
                "tokens_per_second": (toks / self._decode_seconds
                                      if self._decode_seconds else 0.0),
                "slo": self._slo_stats(),
                "journal": {"capacity": self.journal.capacity,
                            "records": len(self.journal),
                            "total": self.journal.total,
                            "dropped": self.journal.dropped},
                "warmup_seconds": self.warmup_seconds}


def generate_naive(model, prompt: Sequence[int], max_new_tokens: int,
                   max_len: int, seed: int = 0, temperature: float = 0.0,
                   top_k: int = 0, _cache={}):
    """Baseline generator: re-runs the FULL prefix forward for every token
    (what serving looks like without decode state) — the bench.py decode
    row's comparison point. Pads to a fixed ``max_len`` so it compiles once,
    and samples with the same fold_in(PRNGKey(seed), position) rule as
    DecodeEngine, so greedy outputs match the engine token-for-token."""
    is_graph = hasattr(model.conf, "network_inputs")
    itype = (model.conf.input_types[0] if is_graph else model.conf.input_type)
    vocab = itype.size

    key = (id(model), max_len)
    step = _cache.get(key)
    if step is None:
        def step(params, state, x, last, seed_, temp, tk):
            if is_graph:
                acts, _, _ = model._forward(params, state, [x],
                                            train=False, rng=None)
                probs = acts[model.conf.network_outputs[0]]
            else:
                probs, _, _ = model._forward(params, state, x,
                                             train=False, rng=None)
            # same oracle as DecodeEngine._step_impl and the speculative
            # verify program — one sampling rule, serving/spec/accept.py
            return oracle_token(jnp.log(probs[0, last]), seed_, last,
                                temp, tk)

        step = _cache[key] = jax.jit(step)

    toks = [int(t) for t in prompt]
    if len(toks) + max_new_tokens > max_len:
        raise ValueError("prompt + max_new_tokens exceeds max_len")
    out = []
    x = np.zeros((1, max_len, vocab), np.float32)
    x[0, np.arange(len(toks)), toks] = 1.0
    for _ in range(max_new_tokens):
        last = len(toks) - 1
        tok = int(step(model.params, model.state, jnp.asarray(x),
                       np.int32(last), np.uint32(seed & 0xFFFFFFFF),
                       np.float32(temperature), np.int32(top_k)))
        out.append(tok)
        x[0, len(toks), tok] = 1.0
        toks.append(tok)
    return {"tokens": out, "prompt_len": len(prompt)}
