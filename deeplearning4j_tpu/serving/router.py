"""Failover router: the replica tier in front of N inference servers.

PR 4 made one serving process fault tolerant and PR 5 gave it a
continuous-batching DecodeEngine; this module is the tier above — the
layer that survives a replica dying or browning out MID-STORM without the
client noticing (ROADMAP item 2; the design follows Dean & Barroso, "The
Tail at Scale": replication + hedging is how you keep p99 flat when
individual workers go slow or dead). Everything is stdlib HTTP on the
existing keep-alive ``InferenceClient`` stack.

The router owns four things:

- **An active health model per replica.** Periodic ``GET /healthz`` probes
  plus passive signals from real traffic (connect errors, timeouts, 5xx,
  deadline misses) drive a per-replica state machine::

      healthy → suspect → ejected → recovering → healthy
                   ↑___________________|  (failure while recovering
                                           re-ejects with doubled backoff)

  Ejected replicas are re-probed on an exponential backoff; a successful
  probe re-admits them as ``recovering`` (routable), and the first real
  success heals them. A replica reporting ``draining`` is pulled without
  ejection penalty; ``degraded`` (e.g. ``decode_saturated``) de-prioritizes
  it in selection so prefill-heavy work steers to replicas with headroom.

- **Failover with a shared retry budget.** A failed attempt fails over to
  a different replica only while the token-bucket budget (deposits are a
  fraction of live request volume) has balance — once it is spent the
  client gets a FAST 503 ``retry_budget_exhausted`` instead of a retry
  storm amplifying the brownout. Hedges spend the same budget.

- **Hedged ``/predict``.** If the primary attempt hasn't answered after a
  p95-based delay, a second copy goes to another replica; the first answer
  wins and the loser is cancelled best-effort (its socket is closed and
  the late result discarded).

- **Least-outstanding-requests balancing** with per-tenant quotas
  (``x-tenant`` header) and priority shedding (``x-priority``:
  low|normal|high — low sheds first as the router fills) layered on the
  replicas' existing deadline/429 machinery.

Zero-downtime deploys ride ``rolling_restart()``: one replica at a time is
administratively drained (its own graceful ``stop()`` flushes in-flight
work), restarted by the caller, and re-admitted only after ``/healthz``
reports ok AND a warmup probe has recompiled its bucket ladder.

Topology, tuning knobs and the runbook live in docs/SERVING_TIER.md.
"""

from __future__ import annotations

import itertools
import json
import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.monitor import get_registry, trace
from deeplearning4j_tpu.monitor import tracing
from deeplearning4j_tpu.monitor.reqlog import RequestLog, new_record
from deeplearning4j_tpu.monitor.slo import BurnRateSLO
from deeplearning4j_tpu.serving.client import InferenceClient
from deeplearning4j_tpu.serving.kv.prefix import chain_hashes

__all__ = ["Router", "RetryBudget", "ReplicaState"]


class ReplicaState:
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    EJECTED = "ejected"
    RECOVERING = "recovering"


# numeric encoding for the per-replica state gauge (alerting rules compare
# against these; admin_down is reported on top via its own gauge)
_STATE_VALUE = {ReplicaState.HEALTHY: 0, ReplicaState.SUSPECT: 1,
                ReplicaState.EJECTED: 2, ReplicaState.RECOVERING: 3}

# upstream statuses that mean "this replica failed the request" — eligible
# for failover to a different replica. 504 is NOT here: the request's own
# deadline is spent, retrying delivers a late answer nobody awaits.
_FAILOVER_STATUSES = (429, 500, 502, 503)


class RetryBudget:
    """Token bucket bounding retries+hedges to a fraction of live traffic.

    Every incoming request deposits ``ratio`` tokens (capped at ``cap``);
    every failover attempt or hedge withdraws one. Under a full brownout
    the budget drains in ~``initial`` retries and then refills at
    ``ratio`` per request — so retry load is at most ``ratio`` of offered
    load in steady state, which is what keeps a brownout from becoming a
    self-inflicted storm."""

    def __init__(self, ratio: float = 0.1, initial: float = 5.0,
                 cap: float = 20.0):
        self.ratio = float(ratio)
        self.cap = float(cap)
        self._balance = min(float(initial), self.cap)
        self._lock = threading.Lock()
        reg = get_registry()
        self._m_spent = reg.counter(
            "dl4jtpu_router_retry_budget_spent_total",
            "Failover/hedge attempts paid for from the shared retry "
            "budget.")
        self._m_denied = reg.counter(
            "dl4jtpu_router_retry_budget_denied_total",
            "Failover/hedge attempts refused because the retry budget was "
            "spent (the request then fails fast instead of retrying).")
        reg.gauge(
            "dl4jtpu_router_retry_budget_balance",
            "Current retry-budget token balance.").set_function(
                lambda: self._balance)

    def deposit(self) -> None:
        with self._lock:
            self._balance = min(self.cap, self._balance + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._balance >= 1.0:
                self._balance -= 1.0
                spent = True
            else:
                spent = False
        (self._m_spent if spent else self._m_denied).inc()
        return spent

    @property
    def balance(self) -> float:
        return self._balance


class _Replica:
    """Router-side record for one upstream: health state + live counters."""

    def __init__(self, url: str, timeout: float):
        self.url = url.rstrip("/")
        # retries=1: the router owns failover — the client must surface
        # every upstream failure instead of retrying it in place
        self.client = InferenceClient(self.url, timeout=timeout, retries=1)
        self.probe_client = InferenceClient(self.url,
                                            timeout=min(timeout, 5.0),
                                            retries=1)
        self.state = ReplicaState.HEALTHY
        self.consecutive_failures = 0
        self.outstanding = 0
        self.degraded = False
        self.draining = False
        self.admin_down = False            # rolling restart holds this
        self.ejected_until = 0.0
        self.backoff = 0.0
        self.lock = threading.Lock()
        # disaggregation state learned from /stats (refresh_affinity):
        # the replica's declared role and its advertised KV chain heads —
        # the prefix-affinity routing signal. Stale values only cost a
        # fallback to plain least-outstanding, never correctness.
        self.role = "mixed"
        self.chain_heads: frozenset = frozenset()
        self.kv_block_size: Optional[int] = None

    def routable(self) -> bool:
        return (self.state != ReplicaState.EJECTED
                and not self.admin_down and not self.draining)


class _Attempt:
    """One upstream try of one request (primary, failover, or hedge)."""

    __slots__ = ("replica", "rid", "cancelled", "conn")

    def __init__(self, replica: _Replica, rid: str):
        self.replica = replica
        self.rid = rid
        self.cancelled = threading.Event()
        self.conn = None

    def cancel(self):
        """Best-effort: close the in-flight socket so the losing half of a
        hedged pair stops consuming its replica, and flag the attempt so
        the resulting socket error is discarded instead of counting as a
        passive failure (we caused it)."""
        self.cancelled.set()
        conn = self.conn
        if conn is not None and conn.sock is not None:
            try:
                conn.sock.close()
            except OSError:
                pass


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    def _reply(self, status: int, body: bytes, rid: Optional[str] = None,
               extra_headers=None):
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if rid:
            self.send_header("x-request-id", rid)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        router = self.server.router
        path = urlparse(self.path).path
        if path == "/healthz":
            info = router.health_info()
            self._reply(503 if info["status"] == "draining" else 200,
                        json.dumps(info).encode())
        elif path == "/stats":
            self._reply(200, json.dumps(router.stats()).encode())
        elif path == "/trace":
            # the router process's span ring buffer; merged with every
            # replica's by monitor/collect.collect_fleet_trace
            self._reply(200, json.dumps(trace.export()).encode())
        elif path == "/requests":
            # the router's wide-event annotation journal; merged with
            # every replica's by monitor/collect.collect_requests
            q = parse_qs(urlparse(self.path).query)
            n = q.get("n", [None])[0]
            try:
                n = None if n is None else int(n)
            except ValueError:
                self._reply(400, json.dumps(
                    {"error": {"type": "bad_request",
                               "message": f"n must be an integer, "
                                          f"got {n!r}"}}).encode())
                return
            self._reply(200,
                        json.dumps(router.journal.snapshot(n)).encode())
        elif path == "/metrics":
            data = get_registry().render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        else:
            self._reply(404, json.dumps(
                {"error": {"type": "not_found",
                           "message": f"no such path: {path}"}}).encode())

    def do_POST(self):
        router = self.server.router
        path = urlparse(self.path).path
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        if path not in ("/predict", "/generate", "/warmup"):
            self._reply(404, json.dumps(
                {"error": {"type": "not_found",
                           "message": f"no such path: {path}"}}).encode())
            return
        status, out, rid, extra = router.handle(
            path, body,
            tenant=self.headers.get("x-tenant", "default"),
            priority=self.headers.get("x-priority", "normal"),
            request_id=self.headers.get("x-request-id"))
        self._reply(status, out, rid, extra)


class Router:
    """HTTP failover router over N replica InferenceServers.

        router = Router(["http://127.0.0.1:9301", ...], port=0).start()
        out = InferenceClient(f"http://127.0.0.1:{router.port}").predict(x)

    Health/hedging/budget knobs are documented in docs/SERVING_TIER.md.
    ``clock``/``sleep`` are injectable for the health model ONLY (probe
    cadence, ejection backoff) so tests drive state transitions without
    real waiting; the request path uses wall time.
    """

    _ids = itertools.count()

    def __init__(self, upstreams: Sequence[str], port: int = 0,
                 host: str = "127.0.0.1",
                 probe_interval: float = 1.0,
                 eject_after: int = 3,
                 probe_backoff_base: float = 0.5,
                 probe_backoff_max: float = 30.0,
                 retry_budget: Optional[RetryBudget] = None,
                 hedge: bool = True,
                 hedge_delay_ms: Optional[float] = None,
                 hedge_floor_ms: float = 10.0,
                 default_deadline_ms: Optional[float] = None,
                 upstream_timeout: float = 30.0,
                 tenant_quota: Optional[int] = None,
                 max_outstanding: Optional[int] = None,
                 hold_for_capacity_s: float = 0.0,
                 wake_hook: Optional[Callable[[], None]] = None,
                 prefix_affinity: bool = True,
                 affinity_max_chain: int = 32,
                 affinity_slack: int = 2,
                 journal_capacity: int = 512,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        if not upstreams and hold_for_capacity_s <= 0:
            # scale-to-zero tiers (hold_for_capacity_s > 0) may boot with
            # an empty replica set: the autoscaler adds the first replica
            # when the wake hook fires
            raise ValueError("router needs at least one upstream replica")
        self.id = f"router{next(Router._ids)}"
        self._clock = clock
        self._sleep = sleep
        # None disables the probe thread: tests with fake clocks call
        # probe_once() by hand instead of racing a background sweep
        self.probe_interval = (None if probe_interval is None
                               else float(probe_interval))
        self.eject_after = int(eject_after)
        self.probe_backoff_base = float(probe_backoff_base)
        self.probe_backoff_max = float(probe_backoff_max)
        self.budget = retry_budget or RetryBudget()
        self.hedge_enabled = bool(hedge)
        self.hedge_delay_ms = hedge_delay_ms
        self.hedge_floor_ms = float(hedge_floor_ms)
        self.default_deadline_ms = default_deadline_ms
        self.upstream_timeout = float(upstream_timeout)
        self.tenant_quota = tenant_quota
        self.max_outstanding = max_outstanding
        self.hold_for_capacity_s = float(hold_for_capacity_s)
        self.wake_hook = wake_hook
        # prefix-affinity routing (docs/SERVING_TIER.md "Disaggregation"):
        # /generate primaries prefer the replica already advertising this
        # prompt's KV chain heads — bounded by ``affinity_slack`` extra
        # outstanding requests so affinity never starves load balancing,
        # and always layered BENEATH the health state machine.
        self.prefix_affinity = bool(prefix_affinity)
        self.affinity_max_chain = int(affinity_max_chain)
        self.affinity_slack = int(affinity_slack)
        self._replicas: Dict[str, _Replica] = {}
        # router-side wide events: one annotation record per routed
        # request (attempts, hedge winner, affinity hit) that the fleet
        # collector joins to the replica records by base request id
        self.journal = RequestLog(journal_capacity)
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._rid_counter = itertools.count(1)
        self._rid_prefix = f"{os.getpid():x}"
        self._tenant_outstanding: Dict[str, int] = {}
        self._total_outstanding = 0
        self._pool = ThreadPoolExecutor(max_workers=32,
                                        thread_name_prefix=self.id)
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._httpd = None
        self.port: Optional[int] = None
        self._host = host
        self._port_req = port

        reg = get_registry()
        self._m_requests = reg.counter(
            "dl4jtpu_router_requests_total",
            "Requests handled by the router. outcome: ok | failed_over "
            "(ok after ≥1 failover) | hedge_win | shed | error.",
            ("router", "path", "outcome"))
        self._m_attempts = reg.counter(
            "dl4jtpu_router_upstream_attempts_total",
            "Individual upstream tries (primary + failover + hedge).",
            ("router", "replica"))
        self._m_failures = reg.counter(
            "dl4jtpu_router_upstream_failures_total",
            "Passive failure signals per replica. kind: connect | timeout "
            "| 5xx | overloaded | draining | deadline_miss | probe.",
            ("router", "replica", "kind"))
        self._m_ejections = reg.counter(
            "dl4jtpu_router_ejections_total",
            "Replica ejections (consecutive passive failures crossed the "
            "threshold, or a recovering replica failed again).",
            ("router", "replica"))
        self._m_readmissions = reg.counter(
            "dl4jtpu_router_readmissions_total",
            "Replicas re-admitted to rotation: a probe succeeded after "
            "ejection, or a rolling restart completed its health gate.",
            ("router", "replica"))
        self._m_hedges = reg.counter(
            "dl4jtpu_router_hedges_total",
            "Hedged /predict attempts. outcome: fired | won (hedge beat "
            "the primary) | cancelled (primary won, hedge discarded).",
            ("router", "outcome"))
        self._m_sheds = reg.counter(
            "dl4jtpu_router_sheds_total",
            "Requests shed at the router before any upstream attempt. "
            "reason: tenant_quota | priority | no_replicas | deadline.",
            ("router", "reason"))
        self._m_holds = reg.counter(
            "dl4jtpu_router_capacity_holds_total",
            "Requests held at the router because no replica was routable "
            "(scale-to-zero wake path). outcome: served (capacity arrived "
            "within hold_for_capacity_s) | timeout (shed after the hold).",
            ("router", "outcome"))
        self._m_probes = reg.counter(
            "dl4jtpu_router_probes_total",
            "Active /healthz probes. result: ok | degraded | draining | "
            "error.", ("router", "replica", "result"))
        self._m_affinity = reg.counter(
            "dl4jtpu_router_affinity_requests_total",
            "Prefix-affinity decisions on /generate primary picks. "
            "outcome: hit (routed to a replica advertising the prompt's "
            "chain heads) | miss (no eligible replica covered the prefix; "
            "fell back to least-outstanding).", ("router", "outcome"))
        self._m_aff_refreshes = reg.counter(
            "dl4jtpu_router_affinity_refreshes_total",
            "Per-replica chain-head/role refreshes pulled from /stats "
            "(piggybacked on the probe sweep).", ("router",))
        self._m_latency = reg.histogram(
            "dl4jtpu_router_upstream_latency_seconds",
            "Latency of successful upstream attempts (feeds the p95 hedge "
            "delay).", ("router", "path"))
        self._m_state = reg.gauge(
            "dl4jtpu_router_replica_state",
            "Replica health state: 0 healthy, 1 suspect, 2 ejected, "
            "3 recovering.", ("router", "replica"))
        self._m_admin = reg.gauge(
            "dl4jtpu_router_replica_admin_down",
            "1 while a replica is administratively held out of rotation "
            "(rolling restart).", ("router", "replica"))
        self._m_outstanding = reg.gauge(
            "dl4jtpu_router_replica_outstanding",
            "In-flight upstream requests per replica (the "
            "least-outstanding balancing signal).", ("router", "replica"))
        # availability SLO over routed /predict + /generate: only the
        # ``error`` outcome (every replica failed / budget spent / router
        # deadline) burns budget — sheds are policy, failovers and hedge
        # wins answered the client fine. Shares the router's injectable
        # clock so fake-clock tests drive the burn windows directly.
        sli, bad = [], []
        for p in ("/predict", "/generate"):
            for oc in ("ok", "failed_over", "hedge_win", "shed", "error"):
                child = self._m_requests.labels(router=self.id, path=p,
                                                outcome=oc)
                sli.append(child)
                if oc == "error":
                    bad.append(child)
        self.slo = BurnRateSLO(
            f"router_availability:{self.id}",
            bad_fn=lambda: sum(c.value for c in bad),
            total_fn=lambda: sum(c.value for c in sli),
            objective=0.99, clock=clock)
        for url in upstreams:
            self._add_replica(url)

    # ----------------------------------------------------------- replica set
    def _add_replica(self, url: str) -> None:
        rep = _Replica(url, timeout=self.upstream_timeout)
        self._replicas[rep.url] = rep
        lab = {"router": self.id, "replica": rep.url}
        self._m_state.labels(**lab).set_function(
            lambda r=rep: _STATE_VALUE[r.state])
        self._m_admin.labels(**lab).set_function(
            lambda r=rep: 1.0 if r.admin_down else 0.0)
        self._m_outstanding.labels(**lab).set_function(
            lambda r=rep: float(r.outstanding))

    @property
    def replicas(self) -> Dict[str, _Replica]:
        return self._replicas

    def add_upstream(self, url: str) -> None:
        """Admit a replica into rotation at runtime (the autoscaler's
        scale-up path — callers gate on the replica being warm/healthy
        BEFORE adding it; the router starts routing immediately).
        Re-adding a known URL resets its health state."""
        with self._lock:
            self._add_replica(url)

    def remove_upstream(self, url: str, drain_timeout: float = 30.0) -> bool:
        """Drain + remove a replica from rotation (the autoscaler's
        scale-down path): ``admin_down`` diverts new traffic, in-flight
        requests get ``drain_timeout`` to finish, then the record and its
        clients go away. Returns False for an unknown URL. Stopping the
        actual process is the caller's job — the router only routes."""
        url = url.rstrip("/")
        with self._lock:
            rep = self._replicas.get(url)
        if rep is None:
            return False
        rep.admin_down = True
        deadline = time.monotonic() + drain_timeout
        while rep.outstanding > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        with self._lock:
            self._replicas.pop(url, None)
        for c in (rep.client, rep.probe_client):
            try:
                c.close()
            except Exception:   # noqa: BLE001 — removal must not raise
                pass
        return True

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "Router":
        self._stop.clear()
        if self.probe_interval is not None and (
                self._probe_thread is None
                or not self._probe_thread.is_alive()):
            self._probe_thread = threading.Thread(target=self._probe_loop,
                                                  daemon=True)
            self._probe_thread.start()
        self._httpd = ThreadingHTTPServer((self._host, self._port_req),
                                          _RouterHandler)
        self._httpd.router = self
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        self._pool.shutdown(wait=False)

    # ---------------------------------------------------------- health model
    def _note_failure(self, rep: _Replica, kind: str) -> None:
        self._m_failures.labels(router=self.id, replica=rep.url,
                                kind=kind).inc()
        if kind == "draining":
            # the replica asked to be pulled — no ejection penalty, the
            # probe loop re-admits it the moment /healthz stops draining
            rep.draining = True
            return
        with rep.lock:
            rep.consecutive_failures += 1
            if rep.state == ReplicaState.RECOVERING:
                self._eject_locked(rep)          # relapse: doubled backoff
            elif rep.state == ReplicaState.HEALTHY:
                rep.state = ReplicaState.SUSPECT
            if (rep.state == ReplicaState.SUSPECT
                    and rep.consecutive_failures >= self.eject_after):
                self._eject_locked(rep)

    def _eject_locked(self, rep: _Replica) -> None:
        rep.state = ReplicaState.EJECTED
        rep.backoff = min(self.probe_backoff_max,
                          max(self.probe_backoff_base, rep.backoff * 2.0))
        rep.ejected_until = self._clock() + rep.backoff
        self._m_ejections.labels(router=self.id, replica=rep.url).inc()

    def _note_success(self, rep: _Replica) -> None:
        with rep.lock:
            rep.consecutive_failures = 0
            rep.draining = False
            if rep.state != ReplicaState.HEALTHY:
                # a real request succeeded — stronger evidence than any
                # probe, so it heals even an ejected replica (the panic
                # path below can route to one)
                rep.state = ReplicaState.HEALTHY
                rep.backoff = 0.0

    def probe_once(self) -> None:
        """One active probe sweep (the loop calls this every
        ``probe_interval``; tests call it directly under a fake clock)."""
        alive = []
        for rep in list(self._replicas.values()):
            if rep.admin_down:
                continue
            if (rep.state == ReplicaState.EJECTED
                    and self._clock() < rep.ejected_until):
                continue                         # still backing off
            try:
                info = rep.probe_client.health()
            except Exception:   # noqa: BLE001 — dead replica: any error
                self._m_probes.labels(router=self.id, replica=rep.url,
                                      result="error").inc()
                self._note_failure(rep, "probe")
                continue
            status = info.get("status")
            self._m_probes.labels(router=self.id, replica=rep.url,
                                  result=status or "error").inc()
            if status == "draining":
                rep.draining = True
                continue
            rep.draining = False
            rep.degraded = (status == "degraded")
            if status in ("ok", "degraded"):
                alive.append(rep)
                with rep.lock:
                    if rep.state == ReplicaState.EJECTED:
                        # re-admit provisionally; the first real success
                        # (or the next probe-sweep success) heals it fully
                        rep.state = ReplicaState.RECOVERING
                        rep.consecutive_failures = 0
                        self._m_readmissions.labels(
                            router=self.id, replica=rep.url).inc()
                    elif rep.state == ReplicaState.RECOVERING:
                        rep.state = ReplicaState.HEALTHY
                        rep.backoff = 0.0
            else:
                self._note_failure(rep, "probe")
        self.refresh_affinity(alive)

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:   # noqa: BLE001 — probes must never die
                pass
            self._sleep(self.probe_interval)

    # ------------------------------------------------------- prefix affinity
    def refresh_affinity(self, replicas=None) -> None:
        """Pull each replica's declared role and advertised KV chain heads
        from ``/stats`` (the bounded digest DecodeEngine.stats publishes).
        Rides the probe sweep; tests and benches call it directly. A
        replica whose stats call fails keeps its last-known heads —
        staleness only costs a fallback to least-outstanding."""
        if not self.prefix_affinity:
            return
        if replicas is None:
            replicas = [r for r in self._replicas.values()
                        if not r.admin_down
                        and r.state != ReplicaState.EJECTED]
        for rep in replicas:
            try:
                st = rep.probe_client.stats()
            except Exception:   # noqa: BLE001 — stale heads beat no heads
                continue
            rep.role = str(st.get("role") or "mixed")
            kv = (st.get("decode") or {}).get("kv") or {}
            rep.chain_heads = frozenset(
                str(h) for h in (kv.get("chain_heads") or []))
            rep.kv_block_size = kv.get("block_size")
            self._m_aff_refreshes.labels(router=self.id).inc()

    def _affinity_hint(self, path: str, body: bytes) -> Optional[dict]:
        """Score replicas by how deep their advertised chain heads cover
        this prompt's rolling block-hash chain (the same blake2b chain the
        replicas' PrefixCache publishes, computed router-side). Returns
        ``{url: depth}`` with depth >= 1 for covering replicas, ``{}``
        when nobody covers any prefix (counts as a miss), or None when
        affinity cannot apply — disabled, non-/generate, no advertised
        heads, unparseable body — which bypasses the hit/miss counter."""
        if not self.prefix_affinity or path != "/generate":
            return None
        with self._lock:
            reps = [(r.url, r.chain_heads, r.kv_block_size)
                    for r in self._replicas.values() if r.chain_heads]
        if not reps:
            return None
        try:
            payload = json.loads(body.decode())
            toks = tuple(int(t) for t in payload["tokens"])
        except Exception:   # noqa: BLE001 — replicas answer 400 for junk
            return None
        if not toks:
            return None
        by_bs: Dict[int, List[str]] = {}
        hint: Dict[str, int] = {}
        for url, heads, bs in reps:
            try:
                bs = int(bs)
            except (TypeError, ValueError):
                continue
            if bs <= 0:
                continue
            if bs not in by_bs:
                by_bs[bs] = chain_hashes(toks, bs,
                                         limit=self.affinity_max_chain)
            depth = 0
            for h in by_bs[bs]:
                if h not in heads:
                    break           # a chain hit commits the WHOLE prefix
                depth += 1
            if depth:
                hint[url] = depth
        return hint

    # -------------------------------------------------------------- selection
    def _pick(self, exclude, hint=None,
              want_prefill: bool = False) -> Optional[_Replica]:
        with self._lock:
            cands = [r for r in self._replicas.values()
                     if r.routable() and r.url not in exclude]
            if not cands:
                # panic routing (fail-open): when the health model has
                # ejected every replica, sending traffic to a maybe-dead
                # one beats a certain 503 — and a success heals it
                cands = [r for r in self._replicas.values()
                         if not r.admin_down and r.url not in exclude]
            if not cands:
                return None
            # degraded replicas (decode_saturated, queue_pressure) only
            # take traffic when every healthy one is excluded/ejected
            fresh = [r for r in cands if not r.degraded]
            pool = fresh or cands
            least = min(r.outstanding for r in pool)
            if hint:
                # prefix affinity: prefer the replica already holding the
                # deepest prefix of this prompt's chain — but never one
                # more than ``affinity_slack`` requests busier than the
                # least-loaded candidate. Affinity is a tiebreak UNDER
                # the health/load model, never an override of it.
                aff = [r for r in pool if hint.get(r.url)
                       and r.outstanding <= least + self.affinity_slack]
                if aff:
                    deepest = max(hint[r.url] for r in aff)
                    aff = [r for r in aff if hint[r.url] == deepest]
                    least_a = min(r.outstanding for r in aff)
                    best = [r for r in aff if r.outstanding == least_a]
                    return best[next(self._rr) % len(best)]
            if want_prefill:
                # disaggregated fleet, fresh prompt (no affinity winner):
                # steer the cold prefill away from decode-dedicated
                # replicas when any other kind is available
                pref = [r for r in pool if r.role != "decode"]
                if pref:
                    pool = pref
                    least = min(r.outstanding for r in pool)
            best = [r for r in pool if r.outstanding == least]
            return best[next(self._rr) % len(best)]   # round-robin the tie

    def _hold_for_capacity(self, tried) -> Optional[_Replica]:
        """Scale-to-zero path: with no routable replica, poke the wake hook
        (the autoscaler's kick) and hold the request up to
        ``hold_for_capacity_s`` for capacity to appear — an AOT-restoring
        replica arrives in well under a second, so a short hold converts a
        certain 503 into a served request."""
        if self.hold_for_capacity_s <= 0:
            return None
        if self.wake_hook is not None:
            try:
                self.wake_hook()
            except Exception:   # noqa: BLE001 — a broken hook must not 500
                pass
        deadline = time.perf_counter() + self.hold_for_capacity_s
        while time.perf_counter() < deadline:
            time.sleep(0.05)
            rep = self._pick(tried)
            if rep is not None:
                self._m_holds.labels(router=self.id, outcome="served").inc()
                return rep
        self._m_holds.labels(router=self.id, outcome="timeout").inc()
        return None

    # -------------------------------------------------------------- requests
    def _mint_rid(self, supplied: Optional[str]) -> str:
        if supplied:
            return supplied
        return f"req-{self._rid_prefix}-{next(self._rid_counter):06d}"

    def _err(self, status: int, err_type: str, message: str, rid: str):
        return status, json.dumps(
            {"error": {"type": err_type, "message": message,
                       "request_id": rid}}).encode(), rid, {}

    def _hedge_delay_s(self) -> float:
        if self.hedge_delay_ms is not None:
            return self.hedge_delay_ms / 1000.0
        hist = self._m_latency.labels(router=self.id, path="/predict")
        p95 = hist.percentile(0.95) if hist.count >= 20 else None
        floor = self.hedge_floor_ms / 1000.0
        return max(floor, p95) if p95 is not None else floor

    def _admit(self, tenant: str, priority: str, rid: str):
        """Quota + priority gate. Returns an error triple to send, or None
        to admit (caller must _release)."""
        with self._lock:
            if self.max_outstanding is not None:
                # priority shedding: low gives up headroom first, high may
                # ride into the overflow band — all before any quota math
                n = self._total_outstanding
                cap = self.max_outstanding
                limit = {"low": 0.75 * cap, "high": 1.5 * cap}.get(
                    priority, float(cap))
                if n >= limit:
                    self._m_sheds.labels(router=self.id,
                                         reason="priority").inc()
                    return self._err(
                        429, "overloaded",
                        f"router at capacity ({n} outstanding); "
                        f"{priority}-priority load shed", rid)
            if self.tenant_quota is not None:
                if self._tenant_outstanding.get(tenant, 0) \
                        >= self.tenant_quota:
                    self._m_sheds.labels(router=self.id,
                                         reason="tenant_quota").inc()
                    return self._err(
                        429, "tenant_quota",
                        f"tenant {tenant!r} at quota "
                        f"({self.tenant_quota} outstanding)", rid)
            self._tenant_outstanding[tenant] = \
                self._tenant_outstanding.get(tenant, 0) + 1
            self._total_outstanding += 1
        return None

    def _release(self, tenant: str) -> None:
        with self._lock:
            self._tenant_outstanding[tenant] = max(
                0, self._tenant_outstanding.get(tenant, 1) - 1)
            self._total_outstanding = max(0, self._total_outstanding - 1)

    def handle(self, path: str, body: bytes, tenant: str = "default",
               priority: str = "normal",
               request_id: Optional[str] = None):
        """Route one request; returns ``(status, body_bytes, request_id,
        extra_headers)`` — ``extra_headers`` passes upstream metadata
        (``x-model-version``, the replica's hot-swap weight version) through
        to the client. Exposed directly (not just via HTTP) so tests can
        drive the router without sockets where sockets add nothing."""
        rid = self._mint_rid(request_id)
        t_start = time.perf_counter()
        self.budget.deposit()
        shed = self._admit(tenant, priority, rid)
        if shed is not None:
            # wide event even for a request that never reached an
            # upstream: a shed MUST be attributable in the journal
            self.journal.append(new_record(
                rid, "router", trace_id=rid, outcome="shed",
                tenant=tenant, priority=priority, router=self.id,
                path=path, status=shed[0], attempts=0, attempt_rids=[],
                hedged=False, hedge_winner=None, affinity_hit=None,
                replica=None,
                wall_seconds=time.perf_counter() - t_start))
            return shed
        # the fleet trace root: trace_id = the router-minted request id.
        # Every span below (route here, attempt per upstream try, and —
        # via the x-trace-context header — the winning replica's whole
        # handler/engine chain) carries this id.
        ctx = tracing.TraceContext(rid)
        try:
            with tracing.trace_context(ctx), \
                    trace.span("route", path=path):
                expires = self._expiry(body)
                hedge = self.hedge_enabled and path == "/predict"
                hint = self._affinity_hint(path, body)
                return self._forward(path, body, rid, expires, hedge,
                                     hint=hint, tenant=tenant,
                                     priority=priority, t_start=t_start)
        finally:
            self._release(tenant)

    def _expiry(self, body: bytes) -> Optional[float]:
        deadline_ms = self.default_deadline_ms
        try:
            payload = json.loads(body.decode())
            if isinstance(payload, dict) and "deadline_ms" in payload:
                deadline_ms = float(payload["deadline_ms"])
        except Exception:   # noqa: BLE001 — replicas answer 400 for junk
            pass
        if deadline_ms is None:
            return None
        return time.perf_counter() + deadline_ms / 1000.0

    # ------------------------------------------------------------ forwarding
    def _run_attempt(self, att: _Attempt, path: str, body: bytes,
                     results: "queue.Queue",
                     ctx: Optional[tracing.TraceContext] = None) -> None:
        rep = att.replica
        with rep.lock:
            rep.outstanding += 1
        self._m_attempts.labels(router=self.id, replica=rep.url).inc()
        # the attempt id (rid#aN) becomes the replica-side parent span id,
        # riding the x-trace-context header next to x-request-id
        actx = ctx.child(att.rid) if ctx is not None else None
        req_headers = {"x-request-id": att.rid}
        if actx is not None:
            req_headers["x-trace-context"] = actx.to_header()
        t0 = time.perf_counter()
        try:
            with tracing.trace_context(actx), \
                    trace.span("attempt", rid=att.rid, replica=rep.url):
                att.conn = rep.client._conn()
                status, data, hdrs = rep.client.post_raw(
                    path, body, headers=req_headers,
                    give_up=att.cancelled.is_set)
            results.put((att, status, data, hdrs, None,
                         time.perf_counter() - t0))
        except Exception as e:  # noqa: BLE001 — classified by the waiter
            results.put((att, None, None, None, e,
                         time.perf_counter() - t0))
        finally:
            with rep.lock:
                rep.outstanding -= 1

    def _classify_failure(self, status, exc) -> str:
        if exc is not None:
            if isinstance(exc, TimeoutError):
                return "timeout"
            return "connect"
        if status == 429:
            return "overloaded"
        if status == 503:
            return "draining"
        return "5xx"

    def _forward(self, path: str, body: bytes, rid: str,
                 expires: Optional[float], hedge: bool, hint=None,
                 tenant: str = "default", priority: str = "normal",
                 t_start: Optional[float] = None):
        results: "queue.Queue" = queue.Queue()
        live: List[_Attempt] = []
        tried = set()
        n_attempt = itertools.count()
        t_start = time.perf_counter() if t_start is None else t_start
        attempt_rids: List[str] = []
        aff_hit: Optional[bool] = None
        hedged = False

        ctx = tracing.get_context()

        def launch(rep: _Replica) -> None:
            att = _Attempt(rep, f"{rid}#a{next(n_attempt)}")
            attempt_rids.append(att.rid)
            tried.add(rep.url)
            live.append(att)
            self._pool.submit(self._run_attempt, att, path, body, results,
                              ctx)

        def outcome(tag: str):
            self._m_requests.labels(router=self.id, path=path,
                                    outcome=tag).inc()

        def journal(tag: str, status, replica=None, winner=None):
            # the router's half of the wide event: per-attempt fan-out the
            # replica journals can't see, joined fleet-wide by base rid
            self.journal.append(new_record(
                rid, "router", trace_id=rid, outcome=tag, tenant=tenant,
                priority=priority, router=self.id, path=path,
                status=None if status is None else int(status),
                attempts=len(attempt_rids),
                attempt_rids=list(attempt_rids), hedged=hedged,
                hedge_winner=winner, affinity_hit=aff_hit,
                replica=replica,
                wall_seconds=time.perf_counter() - t_start))

        want_prefill = self.prefix_affinity and path == "/generate"
        primary = self._pick(tried, hint=hint, want_prefill=want_prefill)
        if primary is None:
            # scale-to-zero: hold the request briefly while the autoscaler
            # wakes a replica (AOT restore makes this a sub-second wait)
            primary = self._hold_for_capacity(tried)
        if primary is None:
            outcome("shed")
            self._m_sheds.labels(router=self.id, reason="no_replicas").inc()
            journal("shed", 503)
            return self._err(503, "no_healthy_replicas",
                             "no routable replica", rid)
        if hint is not None:
            # counted on the primary pick only — failover/hedge picks are
            # health decisions, not affinity decisions
            aff_hit = bool(hint.get(primary.url))
            self._m_affinity.labels(
                router=self.id,
                outcome="hit" if aff_hit else "miss").inc()
        launch(primary)
        hedge_at = (time.perf_counter() + self._hedge_delay_s()
                    if hedge else None)
        failed_over = False
        hedged = False

        while True:
            now = time.perf_counter()
            if expires is not None and now >= expires:
                for att in live:
                    att.cancel()
                outcome("error")
                self._m_sheds.labels(router=self.id, reason="deadline").inc()
                journal("deadline", 504)
                return self._err(504, "deadline_exceeded",
                                 "request deadline expired at the router",
                                 rid)
            timeout = None
            if expires is not None:
                timeout = expires - now
            if hedge_at is not None:
                timeout = min(timeout, hedge_at - now) \
                    if timeout is not None else hedge_at - now
            try:
                att, status, data, hdrs, exc, dt = results.get(
                    timeout=max(0.001, timeout) if timeout is not None
                    else None)
            except queue.Empty:
                if hedge_at is not None and time.perf_counter() >= hedge_at:
                    hedge_at = None
                    rep2 = self._pick(tried)
                    if rep2 is not None and self.budget.try_spend():
                        hedged = True
                        self._m_hedges.labels(router=self.id,
                                              outcome="fired").inc()
                        launch(rep2)
                continue

            live.remove(att)
            if att.cancelled.is_set():
                continue                    # the loser we cancelled
            rep = att.replica
            is_failure = (exc is not None or status is None
                          or status in _FAILOVER_STATUSES)
            if not is_failure:
                if status == 504:
                    # the replica spent the request's deadline: passive
                    # signal, but the answer goes back as-is (no retry)
                    self._note_failure(rep, "deadline_miss")
                else:
                    self._note_success(rep)
                    self._m_latency.labels(router=self.id,
                                           path=path).observe(dt)
                for other in live:
                    other.cancel()
                    self._m_hedges.labels(router=self.id,
                                          outcome="cancelled").inc()
                if hedged and not att.rid.endswith("#a0"):
                    self._m_hedges.labels(router=self.id,
                                          outcome="won").inc()
                    tag = "hedge_win"
                elif failed_over:
                    tag = "failed_over"
                else:
                    tag = "ok"
                outcome(tag)
                journal(tag, status, replica=rep.url,
                        winner=att.rid if tag == "hedge_win" else None)
                extra = {}
                mv = next((v for k, v in (hdrs or {}).items()
                           if k.lower() == "x-model-version"), None)
                if mv is not None:
                    extra["x-model-version"] = mv
                return status, data, rid, extra

            self._note_failure(rep, self._classify_failure(status, exc))
            if live:
                continue                    # a sibling attempt may still win
            if expires is not None and time.perf_counter() >= expires:
                continue                    # top of loop answers 504
            nxt = self._pick(tried)
            if nxt is None:
                outcome("error")
                journal("error", 502)
                return self._err(
                    502, "upstream_failed",
                    "every routable replica failed this request "
                    f"(last: {exc or status})", rid)
            if not self.budget.try_spend():
                outcome("error")
                journal("error", 503)
                return self._err(
                    503, "retry_budget_exhausted",
                    "upstream failed and the shared retry budget is "
                    "spent; failing fast instead of retrying", rid)
            failed_over = True
            launch(nxt)

    # -------------------------------------------------------- rolling restart
    def rolling_restart(self, restarter: Callable[[str], None],
                        drain_timeout: float = 30.0,
                        ready_timeout: float = 180.0,
                        warmup_shape=None,
                        warmup_max_batch: Optional[int] = None) -> None:
        """Zero-downtime deploy: one replica at a time —

        1. hold it out of rotation (``admin_down``; new traffic avoids it),
        2. wait for its in-flight requests to finish,
        3. ``restarter(url)`` stops + restarts the actual process (the
           replica's own graceful ``stop()`` drains its queues),
        4. re-admit only after ``/healthz`` answers ok AND (when
           ``warmup_shape`` is given) a warmup probe recompiled its bucket
           ladder — a replica is never handed traffic it would cold-compile
           against.
        """
        for url, rep in list(self._replicas.items()):
            rep.admin_down = True
            try:
                deadline = time.monotonic() + drain_timeout
                while rep.outstanding > 0 and time.monotonic() < deadline:
                    time.sleep(0.01)
                restarter(url)
                self._await_ready(rep, ready_timeout, warmup_shape,
                                  warmup_max_batch)
            finally:
                rep.admin_down = False
            with rep.lock:
                rep.state = ReplicaState.HEALTHY
                rep.consecutive_failures = 0
                rep.backoff = 0.0
                rep.draining = False
            self._m_readmissions.labels(router=self.id, replica=url).inc()

    def _await_ready(self, rep: _Replica, ready_timeout: float,
                     warmup_shape, warmup_max_batch) -> None:
        deadline = time.monotonic() + ready_timeout
        while True:
            try:
                if rep.probe_client.health().get("status") == "ok":
                    break
            except Exception:   # noqa: BLE001 — still restarting
                pass
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"replica {rep.url} did not become healthy within "
                    f"{ready_timeout}s after restart")
            time.sleep(0.05)
        if warmup_shape is not None:
            rep.client.close()      # the pre-restart socket is stale
            rep.client.warmup(warmup_shape, max_batch=warmup_max_batch)

    # ------------------------------------------------------------------ info
    def health_info(self) -> dict:
        with self._lock:
            snapshot = list(self._replicas.items())
        states = {url: r.state for url, r in snapshot}
        routable = sum(1 for _, r in snapshot if r.routable())
        if self._stop.is_set():
            return {"status": "draining"}
        if routable == 0:
            return {"status": "degraded", "reason": "no_routable_replicas"}
        if routable < len(states):
            return {"status": "degraded", "reason": "replicas_out"}
        try:
            slo = self.slo.evaluate()
        except Exception:       # noqa: BLE001 — SLO math can't break health
            slo = None
        if slo is not None and slo.fast_burn:
            return {"status": "degraded", "reason": "slo_fast_burn",
                    "slo": slo.as_dict()}
        return {"status": "ok"}

    def stats(self) -> dict:
        reps = {}
        with self._lock:
            snapshot = list(self._replicas.items())
        for url, r in snapshot:
            reps[url] = {"state": r.state,
                         "outstanding": r.outstanding,
                         "consecutive_failures": r.consecutive_failures,
                         "degraded": r.degraded,
                         "draining": r.draining,
                         "admin_down": r.admin_down,
                         "probe_backoff_s": r.backoff,
                         "role": r.role,
                         "affinity_heads": len(r.chain_heads)}
        return {"id": self.id,
                "replicas": reps,
                "retry_budget_balance": round(self.budget.balance, 3),
                "hedge_delay_ms": round(self._hedge_delay_s() * 1e3, 2),
                "total_outstanding": self._total_outstanding,
                "tenants": dict(self._tenant_outstanding),
                "journal": {"capacity": self.journal.capacity,
                            "records": len(self.journal),
                            "total": self.journal.total,
                            "dropped": self.journal.dropped}}
