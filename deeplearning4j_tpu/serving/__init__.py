"""High-throughput inference serving.

The training path compiles ONE program per network and amortizes dispatch
(fit_scan); this package does the same for inference: a shape-bucketed
execution engine so a handful of compiled XLA programs cover every request
size, a dynamic micro-batcher that coalesces concurrent requests into one
device call, and an HTTP endpoint in the knn_server style. The reference has
no serving layer at all — its ``output()`` dispatches per-op over JNI
(MultiLayerNetwork.java:1947) — so this is where the XLA-native build wins.

Above the single server sits the replicated tier (``router``/``replica``):
a failover router with per-replica health state machines, hedged requests,
a shared retry budget, tenant quotas, and health-gated rolling restarts.

See docs/SERVING.md for the design and wire format, docs/DECODING.md for
/generate, and docs/SERVING_TIER.md for the replicated tier.
"""

from deeplearning4j_tpu.serving.engine import (
    InferenceEngine, bucket_ladder, bucket_for)
from deeplearning4j_tpu.serving.batcher import MicroBatcher
from deeplearning4j_tpu.serving.decode import DecodeEngine, generate_naive
from deeplearning4j_tpu.serving.kv import (BlockPool, HostKVTier,
                                           KVMigrateError,
                                           PoolExhaustedError, PrefixCache)
from deeplearning4j_tpu.serving.server import InferenceServer
from deeplearning4j_tpu.serving.client import InferenceClient
from deeplearning4j_tpu.serving.router import RetryBudget, Router
from deeplearning4j_tpu.serving.replica import (
    InProcessReplica, ReplicaProcess)
from deeplearning4j_tpu.serving.autoscale import Autoscaler

__all__ = [
    "InferenceEngine", "MicroBatcher", "InferenceServer", "InferenceClient",
    "DecodeEngine", "generate_naive", "bucket_ladder", "bucket_for",
    "BlockPool", "PoolExhaustedError", "PrefixCache", "HostKVTier",
    "KVMigrateError",
    "Router", "RetryBudget", "ReplicaProcess", "InProcessReplica",
    "Autoscaler",
]
