"""Prefix cache: completed prefill blocks keyed by rolling token-hash
chains, shared read-only with copy-on-write at the first divergent block.

Fan-out traffic (one system prompt, many continuations) pays prefill once:
when a request completes, every pool block whose ``block_size`` positions
hold only PROMPT tokens is published under the rolling hash of the token
chain from position 0 to its end. A later request walks the same chain —
full block by full block — and claims each hit read-only (refcount++);
prefill is skipped for the shared span. Because a block's key commits the
ENTIRE prefix up to it (not just its own tokens), a chain hit guarantees
positional KV equality: the cached rows are bitwise what this request's
own prefill would have written under the same weights.

Where the chain breaks, a cached sibling block may still share a partial
run of tokens; that block is claimed by COPY-on-write — the engine copies
it into a freshly allocated block on device and the request overwrites
from the first divergent position — so a 63/64-token near-miss still
skips most of a block's prefill without ever mutating shared content.

Single-threaded like the pool: only the engine's scheduler calls in.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.serving.kv.pool import BlockPool

_ROOT = b"kv-prefix-root"


def _chain_hash(parent: bytes, tokens: Sequence[int]) -> bytes:
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(b"|")
    h.update(b",".join(str(int(t)).encode() for t in tokens))
    return h.digest()


def chain_hashes(tokens: Sequence[int], block_size: int,
                 limit: Optional[int] = None) -> List[str]:
    """Hex chain hashes for the claimable full blocks of ``tokens`` —
    ``(len - 1) // block_size`` of them, mirroring ``match``'s cap. This
    is the router-side half of prefix affinity: the router hashes a
    prompt with each replica's block size and compares against the
    chain-head digests replicas publish in /stats, without ever seeing a
    KV byte."""
    n = (len(tokens) - 1) // block_size
    if limit is not None:
        n = min(n, limit)
    out: List[str] = []
    h = _ROOT
    for j in range(n):
        h = _chain_hash(h, tokens[j * block_size:(j + 1) * block_size])
        out.append(h.hex())
    return out


class PrefixCache:
    """Rolling-hash-chain index over cached pool blocks.

    ``match`` walks a prompt's full blocks along the chain, claiming every
    hit (incref — revives evictable blocks), and returns the first
    divergent block's best partial candidate for CoW. ``insert`` publishes
    a finished request's full prompt blocks. Eviction from the pool calls
    back into ``_drop`` so the index never points at a recycled block.
    """

    def __init__(self, pool: BlockPool, tier=None):
        self.pool = pool
        self._by_hash: Dict[bytes, int] = {}        # chain hash -> bid
        self._by_bid: Dict[int, bytes] = {}
        # parent hash -> [(bid, tokens)]: partial-match candidates for the
        # block after a matched chain (copy-on-write sources)
        self._children: Dict[bytes, List[Tuple[int, Tuple[int, ...]]]] = {}
        self._child_of: Dict[int, bytes] = {}
        # host tier (kv/hosttier.py) plus the engine-owned data movers:
        # spill_fn(hash, parent, tokens, bid) gathers a block's device
        # rows into the tier on eviction; restore_fn(hash, tokens) claims
        # a fresh pool block for a tier hit (returning its bid, or None
        # when the pool can't even spare one) and queues the host→device
        # scatter on the engine's pre-step batch
        self.tier = tier
        self.spill_fn = None
        self.restore_fn = None
        self._spill_enabled = True
        pool.on_evict = self._drop

    def __len__(self) -> int:
        return len(self._by_hash)

    # ---------------------------------------------------------------- lookup
    def match(self, prompt: Sequence[int]
              ) -> Tuple[List[int], Optional[Tuple[int, int]], int]:
        """Claim the longest cached chain for ``prompt``.

        Returns ``(shared, cow, skip)``: ``shared`` — claimed (incref'd)
        block ids covering positions ``[0, len(shared)*block_size)``;
        ``cow`` — ``(src_bid, n_match)`` partial candidate for the next
        block (src is incref'd to pin it until the engine's device copy
        runs) or None; ``skip`` — prompt positions whose prefill is
        skipped. Capped at ``len(prompt) - 1``: the final prompt token
        must run through a real step to produce the first output.
        """
        bs = self.pool.block_size
        plen = len(prompt)
        limit = (plen - 1) // bs        # full blocks claimable read-only
        shared: List[int] = []
        h = _ROOT
        for j in range(limit):
            tok = prompt[j * bs:(j + 1) * bs]
            nxt = _chain_hash(h, tok)
            bid = self._by_hash.get(nxt)
            if bid is None:
                # second chance: the chain may continue in the host tier.
                # restore_fn claims a fresh block NOW (refcount 1 — no
                # incref below, the alloc IS this request's claim) and
                # defers the data scatter; on pool pressure it returns
                # None and the walk ends as a plain miss.
                if (self.tier is not None and self.restore_fn is not None
                        and self.tier.has(nxt)):
                    bid = self.restore_fn(
                        nxt, tuple(int(t) for t in tok))
                    if bid is not None:
                        self._index(nxt, bid, tok, h)
                        self.pool.mark_cached(bid)
                        shared.append(bid)
                        h = nxt
                        continue
                break
            self.pool.incref(bid)
            shared.append(bid)
            h = nxt
        skip = len(shared) * bs
        # partial tail: a cached child of the matched chain sharing the
        # first tokens of the next block → copy-on-write candidate
        cow: Optional[Tuple[int, int]] = None
        want = prompt[skip:min(plen - 1, skip + bs)]
        if want:
            best = 0
            for bid, toks in self._children.get(h, ()):
                n = 0
                for a, b in zip(want, toks):
                    if a != b:
                        break
                    n += 1
                if n > best:
                    best, cow = n, (bid, n)
            if cow is not None:
                self.pool.incref(cow[0])
        return shared, cow, skip + (cow[1] if cow else 0)

    # --------------------------------------------------------------- publish
    def insert(self, prompt: Sequence[int], blocks: Sequence[int]) -> int:
        """Publish a finished request's full PROMPT blocks (block ``j`` is
        cacheable iff positions ``[j*bs, (j+1)*bs)`` are all prompt
        tokens). First writer wins: a chain hash already published keeps
        its existing block (the content is identical by construction).
        Returns entries added."""
        bs = self.pool.block_size
        added = 0
        h = _ROOT
        for j in range(len(prompt) // bs):
            nxt = _chain_hash(h, prompt[j * bs:(j + 1) * bs])
            if nxt not in self._by_hash:
                bid = blocks[j]
                if bid in self._by_bid:      # bid already published under
                    h = nxt                  # another chain — keep it
                    continue
                self._index(nxt, bid, prompt[j * bs:(j + 1) * bs], h)
                self.pool.mark_cached(bid)
                added += 1
            h = nxt
        return added

    def _index(self, chain_hash: bytes, bid: int,
               tokens: Sequence[int], parent: bytes) -> None:
        self._by_hash[chain_hash] = bid
        self._by_bid[bid] = chain_hash
        tok = tuple(int(t) for t in tokens)
        self._children.setdefault(parent, []).append((bid, tok))
        self._child_of[bid] = parent

    def chain_heads(self, limit: int = 64) -> List[str]:
        """Bounded digest of published chain hashes (hex, newest last) —
        what a replica advertises in /stats for prefix-affinity routing.
        Bounded because the digest rides on every stats scrape; the
        newest entries are the likeliest to survive LRU anyway."""
        heads = [h.hex() for h in self._by_hash]
        return heads[-limit:] if limit is not None else heads

    # -------------------------------------------------------------- eviction
    def _drop(self, bid: int) -> None:
        """Pool eviction callback: forget every index entry for ``bid``,
        spilling the block to the host tier first when one is attached
        (demotion instead of loss — kv/hosttier.py)."""
        h = self._by_bid.pop(bid, None)
        parent = self._child_of.pop(bid, None)
        tok = None
        if parent is not None:
            kids = self._children.get(parent)
            if kids is not None:
                for b, t in kids:
                    if b == bid:
                        tok = t
                kids[:] = [(b, t) for b, t in kids if b != bid]
                if not kids:
                    del self._children[parent]
        if h is not None:
            self._by_hash.pop(h, None)
            if (self._spill_enabled and self.tier is not None
                    and self.spill_fn is not None and tok is not None):
                self.spill_fn(h, parent if parent is not None else _ROOT,
                              tok, bid)

    def clear(self) -> int:
        """Flush every ref-0 entry through the pool (weight swaps). The
        host tier is purged and spilling is DISABLED for the flush: the
        evicted KV was computed under the old weights, so letting the
        flush demote it would resurrect stale blocks — the exact bug a
        stale chain-head digest then amplifies fleet-wide via affinity
        routing. The advertised digest empties with ``_by_hash``."""
        if self.tier is not None:
            self.tier.purge()
        self._spill_enabled = False
        try:
            return self.pool.flush_cached()
        finally:
            self._spill_enabled = True
