"""Prefix cache: completed prefill blocks keyed by rolling token-hash
chains, shared read-only with copy-on-write at the first divergent block.

Fan-out traffic (one system prompt, many continuations) pays prefill once:
when a request completes, every pool block whose ``block_size`` positions
hold only PROMPT tokens is published under the rolling hash of the token
chain from position 0 to its end. A later request walks the same chain —
full block by full block — and claims each hit read-only (refcount++);
prefill is skipped for the shared span. Because a block's key commits the
ENTIRE prefix up to it (not just its own tokens), a chain hit guarantees
positional KV equality: the cached rows are bitwise what this request's
own prefill would have written under the same weights.

Where the chain breaks, a cached sibling block may still share a partial
run of tokens; that block is claimed by COPY-on-write — the engine copies
it into a freshly allocated block on device and the request overwrites
from the first divergent position — so a 63/64-token near-miss still
skips most of a block's prefill without ever mutating shared content.

Single-threaded like the pool: only the engine's scheduler calls in.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.serving.kv.pool import BlockPool

_ROOT = b"kv-prefix-root"


def _chain_hash(parent: bytes, tokens: Sequence[int]) -> bytes:
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(b"|")
    h.update(b",".join(str(int(t)).encode() for t in tokens))
    return h.digest()


class PrefixCache:
    """Rolling-hash-chain index over cached pool blocks.

    ``match`` walks a prompt's full blocks along the chain, claiming every
    hit (incref — revives evictable blocks), and returns the first
    divergent block's best partial candidate for CoW. ``insert`` publishes
    a finished request's full prompt blocks. Eviction from the pool calls
    back into ``_drop`` so the index never points at a recycled block.
    """

    def __init__(self, pool: BlockPool):
        self.pool = pool
        self._by_hash: Dict[bytes, int] = {}        # chain hash -> bid
        self._by_bid: Dict[int, bytes] = {}
        # parent hash -> [(bid, tokens)]: partial-match candidates for the
        # block after a matched chain (copy-on-write sources)
        self._children: Dict[bytes, List[Tuple[int, Tuple[int, ...]]]] = {}
        self._child_of: Dict[int, bytes] = {}
        pool.on_evict = self._drop

    def __len__(self) -> int:
        return len(self._by_hash)

    # ---------------------------------------------------------------- lookup
    def match(self, prompt: Sequence[int]
              ) -> Tuple[List[int], Optional[Tuple[int, int]], int]:
        """Claim the longest cached chain for ``prompt``.

        Returns ``(shared, cow, skip)``: ``shared`` — claimed (incref'd)
        block ids covering positions ``[0, len(shared)*block_size)``;
        ``cow`` — ``(src_bid, n_match)`` partial candidate for the next
        block (src is incref'd to pin it until the engine's device copy
        runs) or None; ``skip`` — prompt positions whose prefill is
        skipped. Capped at ``len(prompt) - 1``: the final prompt token
        must run through a real step to produce the first output.
        """
        bs = self.pool.block_size
        plen = len(prompt)
        limit = (plen - 1) // bs        # full blocks claimable read-only
        shared: List[int] = []
        h = _ROOT
        for j in range(limit):
            nxt = _chain_hash(h, prompt[j * bs:(j + 1) * bs])
            bid = self._by_hash.get(nxt)
            if bid is None:
                break
            self.pool.incref(bid)
            shared.append(bid)
            h = nxt
        skip = len(shared) * bs
        # partial tail: a cached child of the matched chain sharing the
        # first tokens of the next block → copy-on-write candidate
        cow: Optional[Tuple[int, int]] = None
        want = prompt[skip:min(plen - 1, skip + bs)]
        if want:
            best = 0
            for bid, toks in self._children.get(h, ()):
                n = 0
                for a, b in zip(want, toks):
                    if a != b:
                        break
                    n += 1
                if n > best:
                    best, cow = n, (bid, n)
            if cow is not None:
                self.pool.incref(cow[0])
        return shared, cow, skip + (cow[1] if cow else 0)

    # --------------------------------------------------------------- publish
    def insert(self, prompt: Sequence[int], blocks: Sequence[int]) -> int:
        """Publish a finished request's full PROMPT blocks (block ``j`` is
        cacheable iff positions ``[j*bs, (j+1)*bs)`` are all prompt
        tokens). First writer wins: a chain hash already published keeps
        its existing block (the content is identical by construction).
        Returns entries added."""
        bs = self.pool.block_size
        added = 0
        h = _ROOT
        for j in range(len(prompt) // bs):
            nxt = _chain_hash(h, prompt[j * bs:(j + 1) * bs])
            if nxt not in self._by_hash:
                bid = blocks[j]
                if bid in self._by_bid:      # bid already published under
                    h = nxt                  # another chain — keep it
                    continue
                self._by_hash[nxt] = bid
                self._by_bid[bid] = nxt
                tok = tuple(int(t) for t in prompt[j * bs:(j + 1) * bs])
                self._children.setdefault(h, []).append((bid, tok))
                self._child_of[bid] = h
                self.pool.mark_cached(bid)
                added += 1
            h = nxt
        return added

    # -------------------------------------------------------------- eviction
    def _drop(self, bid: int) -> None:
        """Pool eviction callback: forget every index entry for ``bid``."""
        h = self._by_bid.pop(bid, None)
        if h is not None:
            self._by_hash.pop(h, None)
        parent = self._child_of.pop(bid, None)
        if parent is not None:
            kids = self._children.get(parent)
            if kids is not None:
                kids[:] = [(b, t) for b, t in kids if b != bid]
                if not kids:
                    del self._children[parent]

    def clear(self) -> int:
        """Flush every ref-0 entry through the pool (weight swaps)."""
        return self.pool.flush_cached()
