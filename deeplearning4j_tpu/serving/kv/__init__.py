"""Paged KV-cache subsystem for the continuous-batching decode engine.

Three parts, one execution model:

- ``pool``    — device-resident pool of fixed-size KV blocks + host-side
  refcounted allocator with LRU eviction; per-slot page tables of fixed
  width keep the step program's shape constant (block 0 is the scratch
  block that absorbs masked writes).
- ``prefix``  — completed prefill blocks published under rolling
  prompt-token-hash chains; later requests claim shared spans read-only
  (refcount++) with copy-on-write at the first divergent block.
- ``prefill`` — chunked-prefill planning: long prompts ride the
  iteration-granularity batched cadence ``chunk_tokens`` at a time next
  to live decode slots.
- ``migrate`` — a request's block chain as a transferable value: wire
  format + validity envelope for replica-to-replica KV handoff
  (prefill/decode disaggregation).
- ``hosttier`` — byte-budgeted host-RAM LRU that evicted prefix blocks
  spill into instead of being dropped; ``PrefixCache.match`` restores
  spilled chains on a second-chance hit.

Wiring lives in serving/decode.py (``DecodeEngine(kv="paged", ...)``);
the attention layers' paged step/gather paths are in
nn/layers/attention.py and ops/flash_decode.py. See docs/DECODING.md
("Paged KV") for tuning knobs and the correctness bar.
"""

from deeplearning4j_tpu.serving.kv.pool import (BlockPool,  # noqa: F401
                                                PoolExhaustedError,
                                                SCRATCH_BLOCK, POOL_KEYS,
                                                is_pool_path,
                                                map_slot_leaves,
                                                map_pool_leaves)
from deeplearning4j_tpu.serving.kv.prefix import (PrefixCache,  # noqa: F401
                                                  chain_hashes)
from deeplearning4j_tpu.serving.kv.prefill import (plan_chunks,  # noqa: F401
                                                   blocks_for_span)
from deeplearning4j_tpu.serving.kv.migrate import (KVMigrateError,  # noqa: F401
                                                   pack_chain,
                                                   unpack_chain)
from deeplearning4j_tpu.serving.kv.hosttier import HostKVTier  # noqa: F401

__all__ = [
    "BlockPool", "PoolExhaustedError", "SCRATCH_BLOCK", "POOL_KEYS",
    "is_pool_path", "map_slot_leaves", "map_pool_leaves",
    "PrefixCache", "chain_hashes", "plan_chunks", "blocks_for_span",
    "KVMigrateError", "pack_chain", "unpack_chain", "HostKVTier",
]
