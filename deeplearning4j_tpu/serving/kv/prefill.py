"""Chunked prefill planning (Sarathi-Serve, Agrawal et al., OSDI'24).

A long prompt prefilled one token per batched step occupies its slot for
``len(prompt)`` iterations; prefilled in one full-length forward it would
stall every co-tenant decode slot for the whole prompt. The middle road:
the engine compiles ONE extra ``(S, chunk_tokens)``-shaped prefill
program and feeds each prefilling slot up to ``chunk_tokens`` prompt
positions per iteration, in the same iteration-granularity cadence as
the decode step — a 4k-token prefix never stalls live decode slots past
one chunk, and a slot's decode latency budget bounds the collateral.

The chunk attention math is bitwise-equal to teacher forcing: a chunk
scatters its K rows into the paged cache, gathers the full logical
cache, and runs the same causal-masked softmax/gemm the full forward
runs — per-row gemm equality holds on XLA:CPU exactly as it does for
the 2-row decode trick (docs/DECODING.md). Rows past a slot's ``n`` are
masked: their KV writes land in the reserved scratch block and their
activations are discarded, so a short tail chunk reuses the same
program shape.
"""

from __future__ import annotations

from typing import List, Tuple


def plan_chunks(start: int, end: int, chunk_tokens: int
                ) -> List[Tuple[int, int]]:
    """Split prefill positions ``[start, end)`` into ``(start, n)`` chunks
    of at most ``chunk_tokens`` — the per-iteration feed schedule for one
    slot. Empty when the span is empty."""
    if chunk_tokens < 1:
        raise ValueError(f"chunk_tokens={chunk_tokens} must be >= 1")
    out = []
    p = int(start)
    while p < end:
        n = min(chunk_tokens, end - p)
        out.append((p, n))
        p += n
    return out


def blocks_for_span(span: int, block_size: int) -> int:
    """Physical blocks needed to hold KV for positions ``[0, span)``."""
    return -(-int(span) // int(block_size))
