"""KV block-chain migration: a prefill's cache as a transferable value.

DistServe-style disaggregation (prefill-specialized replicas handing
finished prefills to decode-specialized ones) needs exactly one
primitive: move a request's live block chain — not the whole pool —
between replicas such that continued decode on the destination is
BITWISE what it would have been locally. This module is the wire format;
the engine supplies the device gathers/scatters (decode.py keeps all
block movement host-side, so migration adds zero XLA programs).

A payload carries ``n`` chain blocks as one contiguous row-gather per
pool leaf (``(n, block_size, H, Dh)``, base64 of the raw bytes), the
token chain that keys them, and a validity envelope in the AOT-bundle
tradition (exec/aot.py): ``model_signature`` of the serving weights,
serving precision, block size, and vocab. ``unpack_chain`` validates the
ENTIRE payload — envelope, leaf set, per-leaf dtype/shape, byte counts,
and a whole-payload checksum — before returning anything, so a torn or
mismatched import rejects with the destination pool untouched. Page
tables never travel: physical block ids are meaningless across pools, so
the destination allocates fresh blocks and rebinds the chain by
re-indexing the SAME rolling token hashes (kv/prefix.py) — the continued
decode is then an ordinary prefix-cache hit, bitwise-equal by the chain
construction.
"""

from __future__ import annotations

import base64
import hashlib
from typing import Dict, List, Sequence, Tuple

import numpy as np

FORMAT = "dl4jtpu/kv-migrate/v1"

# envelope fields that must match the destination engine exactly
ENVELOPE_FIELDS = ("model_sig", "precision", "block_size", "vocab")


class KVMigrateError(Exception):
    """Import/export rejected; ``reason`` is a bounded label (format /
    model_sig / precision / block_size / vocab / tokens / leaves / dtype /
    shape / torn / no_chain / exhausted) for the reject counter."""

    def __init__(self, msg: str, reason: str = "format"):
        super().__init__(msg)
        self.reason = reason


def _checksum(leaves: Sequence[Tuple[str, bytes]]) -> str:
    csum = hashlib.blake2b(digest_size=16)
    for key, raw in leaves:
        csum.update(key.encode())
        csum.update(b"|")
        csum.update(raw)
    return csum.hexdigest()


def pack_chain(rows: Dict[str, np.ndarray], tokens: Sequence[int],
               envelope: dict) -> dict:
    """Serialize gathered chain rows (leaf key -> ``(n, bs, H, Dh)``)
    into a JSON-safe payload. ``tokens`` is the chain's full-block token
    prefix (``n * block_size`` of them)."""
    bs = int(envelope["block_size"])
    toks = [int(t) for t in tokens]
    n = len(toks) // bs
    if n < 1 or len(toks) != n * bs:
        raise KVMigrateError(
            f"token chain length {len(toks)} is not a positive multiple "
            f"of block_size {bs}", reason="tokens")
    leaves: List[dict] = []
    raws: List[Tuple[str, bytes]] = []
    for key in sorted(rows):
        a = np.ascontiguousarray(rows[key])
        raw = a.tobytes()
        raws.append((key, raw))
        leaves.append({"path": key, "dtype": str(a.dtype),
                       "shape": list(a.shape),
                       "data": base64.b64encode(raw).decode("ascii")})
    out = dict(envelope)
    out.update({"format": FORMAT, "n_blocks": n, "tokens": toks,
                "leaves": leaves, "checksum": _checksum(raws)})
    return out


def unpack_chain(payload: dict, envelope: dict,
                 pool_leaves: Dict[str, "np.ndarray"]
                 ) -> Tuple[List[int], Dict[str, np.ndarray]]:
    """Validate ``payload`` against the DESTINATION engine's envelope and
    pool leaf specs; return ``(tokens, rows)`` with rows keyed like
    ``pool_leaves``. Raises ``KVMigrateError`` — with no side effects on
    any pool — on every mismatch, malformation, or torn byte."""
    if not isinstance(payload, dict):
        raise KVMigrateError("payload must be a JSON object",
                             reason="format")
    if payload.get("format") != FORMAT:
        raise KVMigrateError(
            f"unknown payload format {payload.get('format')!r} "
            f"(want {FORMAT!r})", reason="format")
    for fld in ENVELOPE_FIELDS:
        if payload.get(fld) != envelope[fld]:
            raise KVMigrateError(
                f"envelope mismatch on {fld}: payload has "
                f"{payload.get(fld)!r}, destination serves "
                f"{envelope[fld]!r}", reason=fld)
    bs = int(envelope["block_size"])
    tokens = payload.get("tokens")
    n = payload.get("n_blocks")
    if (not isinstance(n, int) or n < 1 or not isinstance(tokens, list)
            or len(tokens) != n * bs
            or not all(isinstance(t, int) for t in tokens)):
        raise KVMigrateError(
            f"token chain does not cover n_blocks={n!r} full blocks of "
            f"{bs}", reason="tokens")
    vocab = int(envelope["vocab"])
    if not all(0 <= t < vocab for t in tokens):
        raise KVMigrateError(
            f"token ids out of range for vocab {vocab}", reason="tokens")
    leaves = payload.get("leaves")
    if not isinstance(leaves, list) or not all(
            isinstance(l, dict) for l in leaves):
        raise KVMigrateError("leaves must be a list of objects",
                             reason="leaves")
    got = sorted(str(l.get("path")) for l in leaves)
    want = sorted(pool_leaves)
    if got != want:
        raise KVMigrateError(
            f"pool leaf set mismatch: payload has {got}, destination "
            f"pool has {want}", reason="leaves")
    rows: Dict[str, np.ndarray] = {}
    raws: List[Tuple[str, bytes]] = []
    for leaf in sorted(leaves, key=lambda l: str(l["path"])):
        key = str(leaf["path"])
        dest = pool_leaves[key]
        dtype = np.dtype(dest.dtype)
        if leaf.get("dtype") != str(dtype):
            raise KVMigrateError(
                f"leaf {key}: payload dtype {leaf.get('dtype')!r} != "
                f"destination pool dtype {str(dtype)!r}", reason="dtype")
        shape = tuple(int(s) for s in leaf.get("shape", ()))
        want_shape = (n,) + tuple(dest.shape[1:])
        if shape != want_shape:
            raise KVMigrateError(
                f"leaf {key}: row shape {shape} != destination "
                f"{want_shape}", reason="shape")
        try:
            raw = base64.b64decode(leaf.get("data", ""), validate=True)
        except Exception:
            raise KVMigrateError(
                f"leaf {key}: undecodable block data", reason="torn")
        nbytes = int(np.prod(shape)) * dtype.itemsize
        if len(raw) != nbytes:
            raise KVMigrateError(
                f"leaf {key}: torn payload — {len(raw)} bytes for a "
                f"{shape} {dtype} gather ({nbytes} expected)",
                reason="torn")
        raws.append((key, raw))
        rows[key] = np.frombuffer(raw, dtype=dtype).reshape(shape)
    if _checksum(raws) != payload.get("checksum"):
        raise KVMigrateError("payload checksum mismatch", reason="torn")
    return [int(t) for t in tokens], rows
