"""Device-resident KV block pool: allocation, refcounts, eviction.

The dense DecodeEngine reserves ``max_len`` KV rows per slot for the whole
lifetime of a request — a 16-token completion in a 2048-capacity slot
wastes >99% of its cache, and two requests sharing a system prompt store
it twice. PagedAttention (Kwon et al., SOSP'23) replaces the per-slot
strip with a pool of fixed-size blocks plus a per-slot page table: the
attention layers store KV in ``(num_blocks, block_size, H, Dh)`` pool
arrays that live INSIDE the engine's donated decode-state tree, and every
step gathers a slot's logical cache ``kc = pool[table[slot]]`` before
running the byte-identical dense math (the parity oracle) or the paged
flash kernel (ops/flash_decode.py).

This module is the HOST side: which physical block backs which logical
block of which request. It is single-threaded by design — only the
engine's scheduler loop allocates/frees — so the bookkeeping is plain
lists, no locks. Three block states:

- free       — on the free list, content garbage
- referenced — refcount ≥ 1 holder (a live slot, or a pending
               copy-on-write source)
- cached     — refcount 0 but content is a prefix-cache entry
               (kv/prefix.py); LRU-evictable, revived by a later hit

Block 0 is RESERVED as the scratch block: inactive slots' page tables are
all-zero, so the step program's masked writes for inactive/invalid rows
land in block 0 and never corrupt live data — scheduling stays data, the
program shape never changes.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, List, Optional

from deeplearning4j_tpu.monitor import get_registry

SCRATCH_BLOCK = 0

# decode-state dict keys that hold pool arrays ((num_blocks, block_size,
# H, Dh) — shared across slots) rather than per-slot state. The engine's
# wipe/reset and freeze/active masks are (S,)-shaped and must never touch
# these leaves; block ownership is what isolates slots instead.
POOL_KEYS = ("pk", "pv")


def is_pool_path(path) -> bool:
    """True when a tree path addresses a pool leaf (a dict key in
    ``POOL_KEYS`` anywhere along the path)."""
    return any(getattr(e, "key", None) in POOL_KEYS for e in path)


def map_slot_leaves(fn, tree, *rest):
    """``tree_map(fn, tree, *rest)`` over per-slot leaves only; pool
    leaves pass through from ``tree`` untouched."""
    import jax
    return jax.tree_util.tree_map_with_path(
        lambda p, a, *r: a if is_pool_path(p) else fn(a, *r), tree, *rest)


def map_pool_leaves(fn, tree):
    """``tree_map(fn, tree)`` over pool leaves only; per-slot leaves pass
    through untouched (the engine's copy-on-write program)."""
    import jax
    return jax.tree_util.tree_map_with_path(
        lambda p, a: fn(a) if is_pool_path(p) else a, tree)


class PoolExhaustedError(Exception):
    """No free or evictable block: admission must wait for a release.

    Carries the pool occupancy at raise time so /healthz's
    ``kv_pool_exhausted`` detail can report WHY the pool is stuck —
    all-live (``in_use`` ≈ usable: capacity problem) reads very
    differently from all-cached (eviction/spill problem)."""

    def __init__(self, msg: str, need: int = 0, free: int = 0,
                 in_use: int = 0, cached: int = 0):
        super().__init__(msg)
        self.need = int(need)
        self.free = int(free)
        self.in_use = int(in_use)
        self.cached = int(cached)


class BlockPool:
    """Refcounted allocator over ``num_blocks`` physical KV blocks of
    ``block_size`` token positions each (block 0 reserved as scratch).

    ``alloc`` is all-or-nothing: it evicts LRU cached blocks as needed and
    raises ``PoolExhaustedError`` without side effects when the request
    cannot be satisfied — the engine leaves the request queued and
    /healthz reports ``kv_pool_exhausted``.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 engine: str = "kv"):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks={num_blocks}: need at least 2 (block 0 is the "
                f"reserved scratch block)")
        if block_size < 1:
            raise ValueError(f"block_size={block_size} must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._ref = [0] * self.num_blocks
        self._ref[SCRATCH_BLOCK] = 1          # pinned forever
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._evictable: "OrderedDict[int, bool]" = OrderedDict()  # LRU
        self._cached = set()                  # bids holding cache content
        # prefix-cache hook: called with the bid as its entry is dropped
        self.on_evict: Optional[Callable[[int], None]] = None

        reg = get_registry()
        lab = {"engine": engine}
        self._m_blocks = reg.gauge(
            "dl4jtpu_kv_pool_blocks",
            "Usable KV blocks in the pool (capacity minus the reserved "
            "scratch block).", ("engine",)).labels(**lab)
        self._m_free = reg.gauge(
            "dl4jtpu_kv_pool_blocks_free",
            "KV blocks allocatable right now (free list plus evictable "
            "prefix-cache blocks).", ("engine",)).labels(**lab)
        self._m_evictions = reg.counter(
            "dl4jtpu_kv_pool_evictions_total",
            "Prefix-cache blocks evicted (LRU) to satisfy an allocation.",
            ("engine",)).labels(**lab)
        self._m_high_water = reg.gauge(
            "dl4jtpu_kv_pool_high_water",
            "Most KV blocks ever simultaneously referenced by live "
            "requests (pressure signal: high_water near usable means the "
            "pool, not the cache, is the bottleneck).",
            ("engine",)).labels(**lab)
        self.high_water = 0
        self._m_blocks.set(float(self.usable))
        self._m_free.set(float(self.free_count))

    # ------------------------------------------------------------- accounting
    @property
    def usable(self) -> int:
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        """Blocks allocatable without waiting (free + evictable)."""
        return len(self._free) + len(self._evictable)

    @property
    def in_use(self) -> int:
        """Blocks with a live reference (scratch excluded) — the leak
        test's occupancy measure."""
        return sum(1 for b in range(1, self.num_blocks) if self._ref[b] > 0)

    @property
    def cached_count(self) -> int:
        return len(self._cached)

    def refcount(self, bid: int) -> int:
        return self._ref[bid]

    def is_cached(self, bid: int) -> bool:
        return bid in self._cached

    # ------------------------------------------------------------ allocation
    def alloc(self, n: int) -> List[int]:
        """Claim ``n`` blocks at refcount 1, evicting LRU cached blocks if
        the free list runs short. All-or-nothing."""
        if n > self.free_count:
            raise PoolExhaustedError(
                f"need {n} blocks, {self.free_count} allocatable "
                f"({len(self._free)} free + {len(self._evictable)} "
                f"evictable)", need=n, free=self.free_count,
                in_use=self.in_use, cached=self.cached_count)
        out = []
        for _ in range(n):
            if not self._free:
                self._evict_one()
            bid = self._free.pop()
            self._ref[bid] = 1
            out.append(bid)
        self._m_free.set(float(self.free_count))
        self._note_high_water()
        return out

    def incref(self, bid: int) -> None:
        if bid == SCRATCH_BLOCK:
            raise ValueError("scratch block cannot be claimed")
        if self._ref[bid] == 0:
            # reviving a cached (evictable) block: a prefix hit
            if bid not in self._evictable:
                raise ValueError(f"block {bid} is free; alloc() it instead")
            del self._evictable[bid]
        self._ref[bid] += 1
        self._m_free.set(float(self.free_count))
        self._note_high_water()

    def _note_high_water(self) -> None:
        n = self.in_use
        if n > self.high_water:
            self.high_water = n
            self._m_high_water.set(float(n))

    def decref(self, bid: int) -> None:
        if bid == SCRATCH_BLOCK:
            raise ValueError("scratch block is never released")
        if self._ref[bid] <= 0:
            raise ValueError(f"block {bid} already free")
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            if bid in self._cached:
                self._evictable[bid] = True   # LRU tail: newest entry
            else:
                self._free.append(bid)
        self._m_free.set(float(self.free_count))

    # ---------------------------------------------------------- prefix cache
    def mark_cached(self, bid: int) -> None:
        """Flag a block's content as a prefix-cache entry: when its last
        reference drops it becomes LRU-evictable instead of free."""
        self._cached.add(bid)

    def _evict_one(self) -> None:
        bid, _ = self._evictable.popitem(last=False)   # LRU head
        self._cached.discard(bid)
        if self.on_evict is not None:
            self.on_evict(bid)
        self._free.append(bid)
        self._m_evictions.inc()

    def flush_cached(self) -> int:
        """Drop every ref-0 cache entry (weight swaps: cached KV was
        computed under the old weights). Returns blocks freed."""
        n = 0
        while self._evictable:
            self._evict_one()
            n += 1
        self._m_free.set(float(self.free_count))
        return n
