"""Host-memory KV tier: evicted prefix blocks spill to host RAM.

The device pool is small — ``slots * max_blocks + 1`` blocks of HBM — so
a long tail of conversation histories churns the prefix cache: every LRU
eviction throws away a block that cost a full prefill chunk to compute,
and the next hit on that chain pays the prefill again. This tier turns
eviction into demotion. When the pool evicts a cached block, the engine
gathers its ``(block_size, H, Dh)`` rows per pool leaf into plain host
arrays and parks them here under the block's CHAIN HASH — the same
rolling blake2b key the prefix cache uses, so an entry commits the
entire token prefix and restore is correct by construction. On a later
``PrefixCache.match`` miss the cache takes a second chance against this
tier: the block is re-claimed from the device pool immediately and the
host→device scatter is deferred to the engine's pre-step batch (the same
discipline as pending copy-on-write), so the match path never blocks on
data movement and no new XLA program is ever traced.

The tier is a byte-budgeted LRU keyed by chain hash. It holds host
memory only — no device buffers, no refcounts — so dropping an entry is
always safe: the worst case is a cold prefill, which is exactly what
would have happened without the tier.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.monitor import get_registry


class HostTierEntry:
    """One spilled block: its chain identity plus per-leaf host rows."""

    __slots__ = ("parent", "tokens", "rows", "nbytes")

    def __init__(self, parent: bytes, tokens: Tuple[int, ...],
                 rows: Dict[str, np.ndarray]):
        self.parent = parent
        self.tokens = tokens
        self.rows = rows
        self.nbytes = int(sum(a.nbytes for a in rows.values()))


class HostKVTier:
    """Byte-budgeted LRU of spilled prefix blocks, keyed by chain hash."""

    def __init__(self, byte_budget: int, engine: str = "kv"):
        if byte_budget < 1:
            raise ValueError(f"byte_budget={byte_budget} must be >= 1")
        self.byte_budget = int(byte_budget)
        self._entries: "OrderedDict[bytes, HostTierEntry]" = OrderedDict()
        self._bytes = 0

        reg = get_registry()
        lab = {"engine": engine}
        self._m_blocks = reg.gauge(
            "dl4jtpu_kv_host_tier_blocks",
            "Prefix blocks currently held in the host-memory KV tier.",
            ("engine",)).labels(**lab)
        self._m_bytes = reg.gauge(
            "dl4jtpu_kv_host_tier_bytes",
            "Host memory held by spilled KV blocks (byte-budgeted LRU).",
            ("engine",)).labels(**lab)
        self._m_spills = reg.counter(
            "dl4jtpu_kv_host_spills_total",
            "Evicted prefix blocks demoted to the host tier instead of "
            "dropped.", ("engine",)).labels(**lab)
        self._m_drops = reg.counter(
            "dl4jtpu_kv_host_drops_total",
            "Host-tier entries discarded for good (LRU under the byte "
            "budget, or oversized spills).", ("engine",)).labels(**lab)

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def bytes_used(self) -> int:
        return self._bytes

    def has(self, chain_hash: bytes) -> bool:
        return chain_hash in self._entries

    # --------------------------------------------------------------- demotion
    def put(self, chain_hash: bytes, parent: bytes,
            tokens: Sequence[int], rows: Dict[str, np.ndarray]) -> bool:
        """Spill one evicted block. Idempotent per chain hash (re-spilling
        a restored block just refreshes its LRU position — the content is
        identical by the chain-hash construction). Returns False when the
        entry alone exceeds the whole budget and had to be dropped."""
        old = self._entries.pop(chain_hash, None)
        if old is not None:
            self._bytes -= old.nbytes
        entry = HostTierEntry(parent, tuple(int(t) for t in tokens),
                              {k: np.ascontiguousarray(a)
                               for k, a in rows.items()})
        if entry.nbytes > self.byte_budget:
            self._m_drops.inc()
            self._gauges()
            return False
        while self._entries and self._bytes + entry.nbytes > self.byte_budget:
            _, lru = self._entries.popitem(last=False)
            self._bytes -= lru.nbytes
            self._m_drops.inc()
        self._entries[chain_hash] = entry
        self._bytes += entry.nbytes
        if old is None:
            self._m_spills.inc()
        self._gauges()
        return True

    # -------------------------------------------------------------- promotion
    def get(self, chain_hash: bytes) -> Optional[HostTierEntry]:
        """LRU-touching lookup. The entry STAYS in the tier — restore does
        not consume it, so a restored block evicted again re-spills for
        free; entries only leave via LRU pressure or ``purge``."""
        entry = self._entries.get(chain_hash)
        if entry is not None:
            self._entries.move_to_end(chain_hash)
        return entry

    def purge(self) -> int:
        """Drop everything (weight swaps: spilled KV was computed under
        the old weights). Returns entries dropped."""
        n = len(self._entries)
        if n:
            self._m_drops.inc(float(n))
        self._entries.clear()
        self._bytes = 0
        self._gauges()
        return n

    # ------------------------------------------------------------------ intro
    def stats(self) -> dict:
        return {"blocks": len(self._entries), "bytes": self._bytes,
                "byte_budget": self.byte_budget,
                "spills": int(self._m_spills.value),
                "drops": int(self._m_drops.value)}

    def _gauges(self) -> None:
        self._m_blocks.set(float(len(self._entries)))
        self._m_bytes.set(float(self._bytes))
