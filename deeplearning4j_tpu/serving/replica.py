"""Replica workers for the routed serving tier (docs/SERVING_TIER.md).

A *replica* is one PR-4/PR-5 ``InferenceServer`` (micro-batched /predict,
optional DecodeEngine /generate) that the ``Router`` fronts. This module
supplies the three ways a replica exists:

- ``main()`` — the subprocess entrypoint
  (``python -m deeplearning4j_tpu.serving.replica --model charlstm ...``):
  builds a small deterministic model, serves it, writes its bound port to
  ``--port-file`` so the parent can find an OS-assigned port, drains
  gracefully on SIGTERM, and optionally mounts the chaos surface
  (``--chaos`` → resilience.faults.ServerFaultInjector behind
  ``POST /chaos``).
- ``ReplicaProcess`` — the parent-side handle: Popen + wait_ready() +
  stop() (SIGTERM, graceful) + kill() (SIGKILL, the chaos soak's crash) +
  start() again on the SAME port (restart-in-place for rolling deploys).
- ``InProcessReplica`` — an in-process InferenceServer with the same
  handle shape, for router tests where process isolation adds nothing but
  seconds.

Models are intentionally tiny: replicas must cold-start (including XLA
compiles) in seconds on a CPU test box, because the chaos harness
restarts them mid-test. The persistent compile cache makes second and
later starts near-instant.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["ReplicaProcess", "InProcessReplica", "build_model",
           "build_server", "main"]

# charlstm vocab — small so one decode step is microseconds on CPU
CHAR_VOCAB = 16


def build_model(name: str):
    """Deterministic tiny models (fixed seeds: every replica of a tier has
    bit-identical params, so failover parity is testable)."""
    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.layers import (DenseLayer, LSTM, OutputLayer,
                                              RnnOutputLayer)
    from deeplearning4j_tpu.nn.updaters import Adam
    if name == "mlp":
        conf = (NeuralNetConfiguration.builder().seed(42).updater(Adam(1e-2))
                .weight_init("xavier").list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        return MultiLayerNetwork(conf).init()
    if name == "widemlp":
        # comms-heavy variant of "mlp" (same 4-feature task, ~13 MB of
        # f32 params) — big enough that the elastic bench's gradient
        # exchange dominates a step, which is what the chain-vs-star
        # throughput comparison needs to measure
        conf = (NeuralNetConfiguration.builder().seed(42).updater(Adam(1e-2))
                .weight_init("xavier").list()
                .layer(DenseLayer(n_out=1024, activation="relu"))
                .layer(DenseLayer(n_out=2048, activation="relu"))
                .layer(DenseLayer(n_out=512, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        return MultiLayerNetwork(conf).init()
    if name == "charlstm":
        conf = (NeuralNetConfiguration.builder().seed(42).updater(Adam(1e-2))
                .weight_init("xavier").list()
                .layer(LSTM(n_out=24, activation="tanh"))
                .layer(RnnOutputLayer(n_out=CHAR_VOCAB, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(CHAR_VOCAB))
                .build())
        return MultiLayerNetwork(conf).init()
    if name == "charlstm-draft":
        # the speculative draft for charlstm: same vocabulary, one narrow
        # LSTM — a draft step must cost a fraction of a target step, and
        # the seed differs so draft/target never share weights
        conf = (NeuralNetConfiguration.builder().seed(17).updater(Adam(1e-2))
                .weight_init("xavier").list()
                .layer(LSTM(n_out=8, activation="tanh"))
                .layer(RnnOutputLayer(n_out=CHAR_VOCAB, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(CHAR_VOCAB))
                .build())
        return MultiLayerNetwork(conf).init()
    if name == "tinyattn":
        # attention-only decode state: the model disaggregated-serving
        # tests and benches need — paged KV with prefix_cache works (no
        # recurrent carries), so chains can be cached, migrated between
        # replicas, and spilled to the host tier. Same vocabulary as
        # charlstm so the fleet fixtures reuse their prompt generators.
        from deeplearning4j_tpu.zoo.simple import TinyTransformer
        return TinyTransformer(vocab_size=CHAR_VOCAB, n_layers=2,
                               d_model=32, n_heads=4, max_len=256,
                               seed=42).init()
    raise ValueError(
        f"unknown replica model {name!r} "
        f"(mlp | widemlp | charlstm | charlstm-draft | tinyattn)")


def build_server(model_name: str = "charlstm", port: int = 0,
                 slots: int = 4, max_len: int = 64, max_queue: int = 256,
                 max_latency_ms: float = 2.0, chaos: bool = False,
                 precision: Optional[str] = None, kv: str = "dense",
                 kv_block_size: int = 16, kv_blocks: Optional[int] = None,
                 prefix_cache: bool = False,
                 chunk_tokens: Optional[int] = None,
                 spec_draft: Optional[str] = None, spec_k: int = 4,
                 spec_tree: Optional[str] = None,
                 spec_self_draft: Optional[str] = None,
                 role: str = "mixed",
                 host_kv_bytes: Optional[int] = None,
                 journal_capacity: int = 512):
    """Assemble (but don't start) a replica InferenceServer. ``charlstm``
    serves both /predict and /generate; ``mlp`` is predict-only.
    ``precision`` (None = the executor policy / DL4JTPU_PRECISION) puts
    BOTH engines on the low-precision serving path — boot-time
    ``--checkpoint`` swaps and later /admin/swap deploys arrive in f32
    and quantize behind the validation gate (docs/QUANTIZATION.md).
    ``kv``/``kv_block_size``/``kv_blocks``/``prefix_cache``/
    ``chunk_tokens`` select the paged KV cache for the decode engine
    (docs/DECODING.md "Paged KV"); ``prefix_cache`` defaults off here
    because the stock charlstm carries recurrent decode state, which the
    prefix cache cannot share. ``spec_draft`` names a draft model (e.g.
    ``charlstm-draft``) — or ``spec_self_draft`` reuses the target's own
    weights (``int8``/``fp8``/``early_exit:M``, no extra checkpoint) —
    to switch /generate to speculative decoding: ``spec_k`` tokens per
    tick, or a branching token tree with ``spec_tree`` ("3,2,2" =
    branching factors per depth); output stays bitwise-identical to the
    plain engine (docs/DECODING.md "Tree speculation & self-drafting").
    ``tinyattn`` (attention-only decode state) serves /generate with
    full paged-KV features: prefix_cache, /kv/export + /kv/import
    migration, and — with ``host_kv_bytes`` — the host-memory KV tier.
    ``role`` declares the replica's disaggregation specialization
    (prefill | decode | mixed), advertised via /stats for the router's
    role-aware placement. ``journal_capacity`` bounds the wide-event
    request journals (predict + decode) served at ``GET /requests``."""
    from deeplearning4j_tpu.serving.decode import DecodeEngine
    from deeplearning4j_tpu.serving.engine import InferenceEngine
    from deeplearning4j_tpu.serving.server import InferenceServer
    net = build_model(model_name)
    eng = InferenceEngine(net, precision=precision)
    dec = None
    if model_name in ("charlstm", "tinyattn"):
        spec = None
        if spec_draft is not None or spec_self_draft is not None:
            from deeplearning4j_tpu.serving.spec import (SpecConfig,
                                                         parse_kvec)
            spec = SpecConfig(
                build_model(spec_draft) if spec_draft is not None else None,
                k=spec_k,
                tree=(parse_kvec(spec_tree) if spec_tree is not None
                      else None),
                self_draft=spec_self_draft)
        dec = DecodeEngine(net, slots=slots, max_len=max_len,
                           max_queue=max_queue, precision=precision,
                           kv=kv, kv_block_size=kv_block_size,
                           kv_blocks=kv_blocks, prefix_cache=prefix_cache,
                           chunk_tokens=chunk_tokens,
                           host_kv_bytes=host_kv_bytes, spec=spec,
                           journal_capacity=journal_capacity)
    injector = None
    if chaos:
        from deeplearning4j_tpu.resilience.faults import ServerFaultInjector
        injector = ServerFaultInjector()
    return InferenceServer(net, port=port, max_latency_ms=max_latency_ms,
                           max_queue=max_queue, engine=eng,
                           decode_engine=dec, fault_injector=injector,
                           role=role, journal_capacity=journal_capacity)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        description="dl4jtpu serving replica worker")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--port-file", default=None,
                        help="write the bound port here once listening")
    parser.add_argument("--model", default="charlstm",
                        choices=("mlp", "charlstm", "tinyattn"))
    parser.add_argument("--role", default="mixed",
                        choices=("prefill", "decode", "mixed"),
                        help="disaggregation role advertised in /stats: "
                             "the router prefers prefill/mixed replicas "
                             "for fresh prefills and steers shared-prefix "
                             "fan-out by chain affinity")
    parser.add_argument("--host-kv-bytes", type=int, default=None,
                        help="host-memory KV tier byte budget (paged + "
                             "--prefix-cache only): evicted prefix blocks "
                             "spill to host RAM and restore on later hits")
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--max-len", type=int, default=64)
    parser.add_argument("--max-queue", type=int, default=256)
    parser.add_argument("--max-latency-ms", type=float, default=2.0)
    parser.add_argument("--journal-capacity", type=int, default=512,
                        help="wide-event request journal ring size per "
                             "engine (GET /requests); oldest dropped first")
    parser.add_argument("--chaos", action="store_true",
                        help="mount POST /chaos (test-only fault injection)")
    parser.add_argument("--warmup", action="store_true",
                        help="pre-compile before accepting traffic")
    parser.add_argument("--aot", default=None,
                        help="AOT artifact path (exec/aot.py): restore "
                             "serialized executables instead of retracing, "
                             "trace-and-save on any miss (implies warmup)")
    parser.add_argument("--checkpoint", default=None,
                        help="swap in the weights of this checkpoint zip "
                             "before accepting traffic (restart from a "
                             "promoted online-learning checkpoint)")
    parser.add_argument("--precision", default=None,
                        choices=("f32", "int8", "fp8"),
                        help="serving precision for both engines (default: "
                             "the executor policy / DL4JTPU_PRECISION)")
    parser.add_argument("--trace", action="store_true",
                        help="enable span tracing (also via DL4JTPU_TRACE); "
                             "the ring buffer is served at GET /trace for "
                             "fleet collection")
    parser.add_argument("--kv", default="dense", choices=("dense", "paged"),
                        help="decode KV layout: per-slot dense caches or "
                             "the block-pool paged cache")
    parser.add_argument("--kv-block-size", type=int, default=16,
                        help="tokens per KV block (paged only; must divide "
                             "--max-len)")
    parser.add_argument("--kv-blocks", type=int, default=None,
                        help="KV pool size in blocks (paged only; default "
                             "sizes for full slot occupancy)")
    parser.add_argument("--prefix-cache", action="store_true",
                        help="reuse completed prefill blocks across "
                             "requests sharing a prompt prefix (paged only; "
                             "needs a model with no recurrent decode state)")
    parser.add_argument("--chunk-tokens", type=int, default=None,
                        help="split prefill into chunks of this many tokens "
                             "riding the batched decode cadence (paged only)")
    parser.add_argument("--spec-draft", default=None,
                        choices=("charlstm-draft",),
                        help="speculative decoding: draft model name for "
                             "the decode engine (lossless — output is "
                             "bitwise the non-speculative stream)")
    parser.add_argument("--spec-k", type=int, default=4,
                        help="tokens the draft proposes per tick "
                             "(with --spec-draft)")
    parser.add_argument("--spec-tree", default=None,
                        help="tree speculation: branching factors per "
                             "depth, e.g. '3,2,2' (overrides --spec-k; "
                             "the draft's trajectory is the spine, "
                             "top-logit alternatives fill the branches)")
    parser.add_argument("--spec-self-draft", default=None,
                        help="self-drafting: the target as its own draft "
                             "— 'int8' / 'fp8' (quantized) or "
                             "'early_exit:M' (first M layers + readout); "
                             "replaces --spec-draft, no extra checkpoint")
    args = parser.parse_args(argv)

    # CPU platform before anything touches a backend: replicas are test
    # and bench workers, never the training accelerator's tenant
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from deeplearning4j_tpu.util.compile_cache import setup_compile_cache
    setup_compile_cache()       # restart-in-place must not recompile

    from deeplearning4j_tpu.monitor import trace as _trace
    if args.trace:
        _trace.enable(True)

    srv = build_server(args.model, port=args.port, slots=args.slots,
                       max_len=args.max_len, max_queue=args.max_queue,
                       max_latency_ms=args.max_latency_ms, chaos=args.chaos,
                       precision=args.precision, kv=args.kv,
                       kv_block_size=args.kv_block_size,
                       kv_blocks=args.kv_blocks,
                       prefix_cache=args.prefix_cache,
                       chunk_tokens=args.chunk_tokens,
                       spec_draft=args.spec_draft, spec_k=args.spec_k,
                       spec_tree=args.spec_tree,
                       spec_self_draft=args.spec_self_draft,
                       role=args.role, host_kv_bytes=args.host_kv_bytes,
                       journal_capacity=args.journal_capacity)
    # warmup BEFORE the serve loops start so REPLICA_READY / the port-file
    # handshake mean genuinely ready-to-serve: with --aot this is a
    # millisecond restore, without it the full trace-and-save
    if srv.decode_engine is not None:
        if args.warmup or args.aot:
            srv.decode_engine.warmup(aot=args.aot)
        srv.decode_engine.start()
    if (args.warmup or args.aot) and args.model == "mlp":
        srv.engine.warmup((4,), max_batch=64, aot=args.aot)
    srv.start()
    if args.checkpoint:
        # boot-time deploy of a promoted checkpoint: the replica starts from
        # its deterministic seed weights and swaps (zero extra compiles,
        # same shapes) rather than deserialising a whole different conf
        v = srv.swap_checkpoint(args.checkpoint)
        print(f"REPLICA_SWAPPED version={v} "
              f"checkpoint={args.checkpoint}", flush=True)

    stopping = []

    def _sigterm(signum, frame):
        # graceful drain: in-flight requests finish, /healthz flips to
        # draining, then the process exits 0
        stopping.append(True)

    signal.signal(signal.SIGTERM, _sigterm)

    if args.port_file:
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(srv.port))
        os.replace(tmp, args.port_file)      # atomic: parent never reads ""
    # name this process's track in merged fleet traces
    _trace.set_process_name(f"replica:{args.model}@{srv.port}")
    print(f"REPLICA_READY port={srv.port} pid={os.getpid()} "
          f"model={args.model}", flush=True)

    try:
        while not stopping:
            time.sleep(0.05)
    except KeyboardInterrupt:
        pass
    srv.stop()
    if srv.decode_engine is not None:
        srv.decode_engine.stop()
    print("REPLICA_STOPPED", flush=True)
    return 0


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


class ReplicaProcess:
    """Parent-side handle for a subprocess replica.

        rep = ReplicaProcess(workdir, model="charlstm").start().wait_ready()
        ... rep.url ...
        rep.kill()          # SIGKILL: the crash the router must absorb
        rep.start().wait_ready()   # restart-in-place, same port

    The first ``start()`` lets the OS pick a port (read back through
    ``--port-file``); later starts reuse it so the router's upstream URL
    stays valid across restarts (allow_reuse_address makes the rebind
    race-free)."""

    def __init__(self, workdir: str, model: str = "charlstm",
                 slots: int = 4, max_len: int = 64,
                 chaos: bool = True, warmup: bool = True,
                 name: str = "replica", checkpoint: Optional[str] = None,
                 precision: Optional[str] = None, trace: bool = False,
                 kv: str = "dense", kv_block_size: int = 16,
                 kv_blocks: Optional[int] = None, prefix_cache: bool = False,
                 chunk_tokens: Optional[int] = None,
                 spec_draft: Optional[str] = None, spec_k: int = 4,
                 spec_tree: Optional[str] = None,
                 spec_self_draft: Optional[str] = None,
                 role: str = "mixed",
                 host_kv_bytes: Optional[int] = None,
                 aot: Optional[str] = None,
                 env: Optional[dict] = None):
        self.workdir = workdir
        self.model = model
        self.slots = slots
        self.max_len = max_len
        self.chaos = chaos
        self.warmup = warmup
        self.name = name
        self.precision = precision
        self.kv = kv
        self.kv_block_size = kv_block_size
        self.kv_blocks = kv_blocks
        self.prefix_cache = prefix_cache
        self.chunk_tokens = chunk_tokens
        self.spec_draft = spec_draft
        self.spec_k = spec_k
        self.spec_tree = spec_tree
        self.spec_self_draft = spec_self_draft
        self.role = role
        self.host_kv_bytes = host_kv_bytes
        # span tracing in the child (GET /trace serves its ring buffer)
        self.trace = trace
        # mutable: rolling restarts set this to the latest promoted
        # checkpoint so a restarted replica boots on current weights
        self.checkpoint = checkpoint
        # AOT artifact for instant cold-start; extra child env (the bench
        # isolates compile caches per arm through DL4JTPU_JAX_CACHE)
        self.aot = aot
        self.extra_env = env
        # spawn → port-file → first healthy probe, set by wait_ready()
        self.ready_seconds: Optional[float] = None
        self._t_spawn: Optional[float] = None
        self.port: Optional[int] = None
        self.proc: Optional[subprocess.Popen] = None
        self._log = os.path.join(workdir, f"{name}.log")
        self._port_file = os.path.join(workdir, f"{name}.port")

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "ReplicaProcess":
        if os.path.exists(self._port_file) and self.port is None:
            os.unlink(self._port_file)
        cmd = [sys.executable, "-m", "deeplearning4j_tpu.serving.replica",
               "--model", self.model, "--slots", str(self.slots),
               "--max-len", str(self.max_len),
               "--port", str(self.port or 0),
               "--port-file", self._port_file]
        if self.chaos:
            cmd.append("--chaos")
        if self.warmup:
            cmd.append("--warmup")
        if self.checkpoint:
            cmd.extend(["--checkpoint", os.fspath(self.checkpoint)])
        if self.precision:
            cmd.extend(["--precision", self.precision])
        if self.trace:
            cmd.append("--trace")
        if self.kv != "dense":
            cmd.extend(["--kv", self.kv,
                        "--kv-block-size", str(self.kv_block_size)])
            if self.kv_blocks is not None:
                cmd.extend(["--kv-blocks", str(self.kv_blocks)])
            if self.prefix_cache:
                cmd.append("--prefix-cache")
            if self.chunk_tokens is not None:
                cmd.extend(["--chunk-tokens", str(self.chunk_tokens)])
            if self.host_kv_bytes is not None:
                cmd.extend(["--host-kv-bytes", str(self.host_kv_bytes)])
        if self.role != "mixed":
            cmd.extend(["--role", self.role])
        if self.spec_draft is not None:
            cmd.extend(["--spec-draft", self.spec_draft,
                        "--spec-k", str(self.spec_k)])
        if self.spec_tree is not None:
            cmd.extend(["--spec-tree", self.spec_tree])
        if self.spec_self_draft is not None:
            cmd.extend(["--spec-self-draft", self.spec_self_draft])
        if self.aot:
            cmd.extend(["--aot", os.fspath(self.aot)])
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = (_repo_root() + os.pathsep
                             + env.get("PYTHONPATH", ""))
        if self.extra_env:
            env.update(self.extra_env)
        # log to a FILE: a full stdout pipe would deadlock a replica that
        # nobody is reading, and post-mortems want the log anyway
        self._logf = open(self._log, "ab")
        self._t_spawn = time.monotonic()
        self.proc = subprocess.Popen(cmd, stdout=self._logf,
                                     stderr=subprocess.STDOUT, env=env,
                                     cwd=self.workdir)
        return self

    def wait_ready(self, timeout: float = 180.0) -> "ReplicaProcess":
        """Block until the replica's /healthz answers ok (covers the
        port-file handshake AND warmup compiles)."""
        from deeplearning4j_tpu.serving.client import InferenceClient
        deadline = time.monotonic() + timeout
        while self.port is None:
            if os.path.exists(self._port_file):
                with open(self._port_file) as f:
                    text = f.read().strip()
                if text:
                    self.port = int(text)
                    break
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {self.name} exited rc={self.proc.returncode} "
                    f"before binding; see {self._log}")
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"replica {self.name} never wrote {self._port_file}")
            time.sleep(0.05)
        cli = InferenceClient(self.url, timeout=5.0, retries=1)
        try:
            while True:
                try:
                    if cli.health().get("status") == "ok":
                        self._note_ready()
                        return self
                except Exception:   # noqa: BLE001 — still booting
                    pass
                if self.proc is not None and self.proc.poll() is not None:
                    raise RuntimeError(
                        f"replica {self.name} exited rc="
                        f"{self.proc.returncode} during boot; "
                        f"see {self._log}")
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"replica {self.name} on port {self.port} never "
                        f"became healthy")
                time.sleep(0.05)
        finally:
            cli.close()

    def _note_ready(self) -> None:
        """Record spawn → first healthy probe: the per-replica cold-start
        the autoscaler amortizes (``dl4jtpu_replica_ready_seconds``)."""
        if self._t_spawn is None:
            return
        self.ready_seconds = time.monotonic() - self._t_spawn
        self._t_spawn = None
        try:
            from deeplearning4j_tpu.monitor import get_registry
            get_registry().histogram(
                "dl4jtpu_replica_ready_seconds",
                "Wall seconds from process spawn through the port-file "
                "handshake to the first healthy /healthz probe — the "
                "cold-start the AOT artifact shrinks.",
                ("replica",)).labels(replica=self.name).observe(
                    self.ready_seconds)
        except Exception:   # noqa: BLE001 — telemetry must not fail boot
            pass

    def stop(self, timeout: float = 30.0) -> None:
        """SIGTERM → graceful drain → exit 0."""
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.send_signal(signal.SIGTERM)
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=10)
        self._close_log()

    def kill(self) -> None:
        """SIGKILL: no drain, no flushed sockets — the genuine crash."""
        if self.proc is None:
            return
        try:
            os.kill(self.proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        self.proc.wait(timeout=10)
        self._close_log()

    def _close_log(self) -> None:
        logf = getattr(self, "_logf", None)
        if logf is not None:
            try:
                logf.close()
            except OSError:
                pass
            self._logf = None


class InProcessReplica:
    """Same handle shape as ReplicaProcess, backed by an in-process
    InferenceServer — for router tests where subprocess isolation adds
    only wall-clock. NOTE: in-process replicas share the process-global
    metrics registry with the router; series stay distinguishable through
    their labels.

    ``restart()`` stops the server (graceful drain) and starts a fresh one
    on the SAME port — the restarter hook ``Router.rolling_restart`` wants.
    """

    def __init__(self, model: str = "mlp", chaos: bool = True, **server_kw):
        self.model = model
        self.chaos = chaos
        self.server_kw = server_kw
        self.srv = None
        self.port: Optional[int] = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def fault_injector(self):
        return self.srv.fault_injector if self.srv else None

    def start(self) -> "InProcessReplica":
        self.srv = build_server(self.model, port=self.port or 0,
                                chaos=self.chaos, **self.server_kw)
        if self.srv.decode_engine is not None:
            self.srv.decode_engine.start()
        self.srv.start()
        self.port = self.srv.port
        return self

    def wait_ready(self, timeout: float = 180.0) -> "InProcessReplica":
        """No-op for handle parity: start() returns already listening."""
        return self

    def stop(self) -> None:
        if self.srv is not None:
            srv, self.srv = self.srv, None
            srv.stop()
            if srv.decode_engine is not None:
                srv.decode_engine.stop()

    def restart(self) -> None:
        self.stop()
        self.start()


if __name__ == "__main__":
    sys.exit(main())
