"""Early stopping.

Parity surface: reference earlystopping/ — EarlyStoppingConfiguration
(builder), epoch + iteration termination conditions, score calculators,
model savers (LocalFileModelSaver/InMemoryModelSaver), and
BaseEarlyStoppingTrainer.fit (trainer/BaseEarlyStoppingTrainer.java:76:
per-epoch train → score → track best → save → check conditions →
EarlyStoppingResult).
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Optional, List, Any


# ------------------------------------------------------- termination conditions

class EpochTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no (sufficient) improvement."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement=0.0):
        self.max_no_improve = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self._best = math.inf
        self._since = 0

    def initialize(self):
        self._best = math.inf
        self._since = 0

    def terminate(self, epoch, score):
        if score < self._best - self.min_improvement:
            self._best = score
            self._since = 0
        else:
            self._since += 1
        return self._since >= self.max_no_improve


class MaxTimeTerminationCondition(IterationTerminationCondition,
                                  EpochTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = None

    def initialize(self):
        self._start = time.perf_counter()

    def terminate(self, *args):
        if self._start is None:
            self._start = time.perf_counter()
        return (time.perf_counter() - self._start) > self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort if score exceeds a bound (divergence guard)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, last_score):
        return last_score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, last_score):
        return math.isnan(last_score) or math.isinf(last_score)


# ------------------------------------------------------------ score calculators

class DataSetLossCalculator:
    """Average model loss over a dataset iterator
    (parity: scorecalc/DataSetLossCalculator)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        for ds in self.iterator:
            total += model.score(ds) * ds.num_examples()
            n += ds.num_examples()
        return total / n if (self.average and n) else total


class ClassificationScoreCalculator:
    """1 - accuracy (so lower is better, matching the loss convention)."""

    def __init__(self, iterator):
        self.iterator = iterator

    def calculate_score(self, model) -> float:
        ev = model.evaluate(self.iterator)
        return 1.0 - ev.accuracy()


# -------------------------------------------------------------------- savers

class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, model, score):
        import io
        from deeplearning4j_tpu.util.model_serializer import write_model
        buf = io.BytesIO()
        write_model(model, buf)
        self._best = buf.getvalue()

    def save_latest_model(self, model, score):
        import io
        from deeplearning4j_tpu.util.model_serializer import write_model
        buf = io.BytesIO()
        write_model(model, buf)
        self._latest = buf.getvalue()

    def get_best_model(self):
        import io
        from deeplearning4j_tpu.util.model_serializer import guess_model
        return None if self._best is None else guess_model(io.BytesIO(self._best))

    def get_latest_model(self):
        import io
        from deeplearning4j_tpu.util.model_serializer import guess_model
        return None if self._latest is None else guess_model(io.BytesIO(self._latest))


class LocalFileModelSaver:
    def __init__(self, directory: str):
        import pathlib
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)

    def save_best_model(self, model, score):
        model.save(str(self.dir / "bestModel.zip"))

    def save_latest_model(self, model, score):
        model.save(str(self.dir / "latestModel.zip"))

    def get_best_model(self):
        from deeplearning4j_tpu.util.model_serializer import guess_model
        p = self.dir / "bestModel.zip"
        return guess_model(str(p)) if p.exists() else None

    def get_latest_model(self):
        from deeplearning4j_tpu.util.model_serializer import guess_model
        p = self.dir / "latestModel.zip"
        return guess_model(str(p)) if p.exists() else None


# ------------------------------------------------------------- config + result

@dataclass
class EarlyStoppingConfiguration:
    score_calculator: Any = None
    epoch_termination_conditions: List[EpochTerminationCondition] = field(
        default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = \
        field(default_factory=list)
    model_saver: Any = None
    save_last_model: bool = False
    evaluate_every_n_epochs: int = 1


@dataclass
class EarlyStoppingResult:
    termination_reason: str = ""
    termination_details: str = ""
    score_vs_epoch: dict = field(default_factory=dict)
    best_model_epoch: int = -1
    best_model_score: float = math.inf
    total_epochs: int = 0
    best_model: Any = None


class EarlyStoppingTrainer:
    """Parity: trainer/BaseEarlyStoppingTrainer.java:76 fit loop."""

    def __init__(self, config: EarlyStoppingConfiguration, model, train_data):
        self.config = config
        self.model = model
        self.train_data = train_data

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        result = EarlyStoppingResult()
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()

        epoch = 0
        while True:
            if hasattr(self.train_data, "reset"):
                self.train_data.reset()
            aborted = False
            for batch in self.train_data:
                if isinstance(batch, tuple):
                    from deeplearning4j_tpu.data.dataset import DataSet
                    batch = DataSet(*batch)
                self.model._fit_batch(batch)
                last = self.model.get_score()
                for c in cfg.iteration_termination_conditions:
                    if c.terminate(last):
                        result.termination_reason = "IterationTerminationCondition"
                        result.termination_details = type(c).__name__
                        aborted = True
                        break
                if aborted:
                    break
            if aborted:
                break

            if cfg.score_calculator is not None and \
                    epoch % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator.calculate_score(self.model)
                result.score_vs_epoch[epoch] = score
                if score < result.best_model_score:
                    result.best_model_score = score
                    result.best_model_epoch = epoch
                    if cfg.model_saver is not None:
                        cfg.model_saver.save_best_model(self.model, score)
                if cfg.save_last_model and cfg.model_saver is not None:
                    cfg.model_saver.save_latest_model(self.model, score)
            else:
                score = self.model.get_score()

            stop = False
            for c in cfg.epoch_termination_conditions:
                if c.terminate(epoch, score):
                    result.termination_reason = "EpochTerminationCondition"
                    result.termination_details = type(c).__name__
                    stop = True
                    break
            epoch += 1
            if stop:
                break

        result.total_epochs = epoch
        if cfg.model_saver is not None:
            result.best_model = cfg.model_saver.get_best_model()
        if result.best_model is None:
            result.best_model = self.model
        return result
