from deeplearning4j_tpu.earlystopping.early_stopping import (
    EarlyStoppingConfiguration, EarlyStoppingResult, EarlyStoppingTrainer,
    MaxEpochsTerminationCondition, MaxTimeTerminationCondition,
    ScoreImprovementEpochTerminationCondition, MaxScoreIterationTerminationCondition,
    InvalidScoreIterationTerminationCondition,
    DataSetLossCalculator, ClassificationScoreCalculator,
    LocalFileModelSaver, InMemoryModelSaver,
)

__all__ = [
    "EarlyStoppingConfiguration", "EarlyStoppingResult", "EarlyStoppingTrainer",
    "MaxEpochsTerminationCondition", "MaxTimeTerminationCondition",
    "ScoreImprovementEpochTerminationCondition",
    "MaxScoreIterationTerminationCondition",
    "InvalidScoreIterationTerminationCondition",
    "DataSetLossCalculator", "ClassificationScoreCalculator",
    "LocalFileModelSaver", "InMemoryModelSaver",
]
