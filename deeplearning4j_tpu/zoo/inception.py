"""Inception-family zoo models as ComputationGraphs.

Parity surface:
- GoogLeNet (Inception v1)       — reference zoo/model/GoogLeNet.java
- InceptionResNetV1              — zoo/model/InceptionResNetV1.java
- FaceNetNN4Small2 (face embed)  — zoo/model/FaceNetNN4Small2.java
  (L2-normalized embedding head; trainable with center loss like the
  reference's variant)
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_conf import (
    MergeVertex, ElementWiseVertex, ScaleVertex, L2NormalizeVertex,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer, SubsamplingLayer, BatchNormalization, ActivationLayer,
    GlobalPoolingLayer, OutputLayer, DenseLayer, DropoutLayer,
    LocalResponseNormalization, CenterLossOutputLayer,
)
from deeplearning4j_tpu.nn.updaters import Adam, Nesterovs
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class GoogLeNet(ZooModel):
    name = "googlenet"
    default_input_shape = (224, 224, 3)

    def conf(self):
        h, w, c = self.input_shape
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater(Nesterovs(1e-2, momentum=0.9)))
             .weight_init("relu")
             .activation("relu")
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))

        def inception(name, inp, c1, c3r, c3, c5r, c5, pp):
            g.add_layer(f"{name}_1x1", ConvolutionLayer(n_out=c1, kernel_size=1), inp)
            g.add_layer(f"{name}_3x3r", ConvolutionLayer(n_out=c3r, kernel_size=1), inp)
            g.add_layer(f"{name}_3x3", ConvolutionLayer(n_out=c3, kernel_size=3,
                                                        padding=1), f"{name}_3x3r")
            g.add_layer(f"{name}_5x5r", ConvolutionLayer(n_out=c5r, kernel_size=1), inp)
            g.add_layer(f"{name}_5x5", ConvolutionLayer(n_out=c5, kernel_size=5,
                                                        padding=2), f"{name}_5x5r")
            g.add_layer(f"{name}_pool",
                        SubsamplingLayer(pooling_type="max", kernel_size=3,
                                         stride=1, padding=1), inp)
            g.add_layer(f"{name}_poolproj", ConvolutionLayer(n_out=pp,
                                                             kernel_size=1),
                        f"{name}_pool")
            g.add_vertex(f"{name}", MergeVertex(), f"{name}_1x1", f"{name}_3x3",
                         f"{name}_5x5", f"{name}_poolproj")
            return name

        g.add_layer("stem_conv", ConvolutionLayer(n_out=64, kernel_size=7,
                                                  stride=2, padding=3), "input")
        g.add_layer("stem_pool", SubsamplingLayer(pooling_type="max",
                                                  kernel_size=3, stride=2,
                                                  padding=1), "stem_conv")
        g.add_layer("stem_lrn", LocalResponseNormalization(), "stem_pool")
        g.add_layer("stem_conv2", ConvolutionLayer(n_out=64, kernel_size=1),
                    "stem_lrn")
        g.add_layer("stem_conv3", ConvolutionLayer(n_out=192, kernel_size=3,
                                                   padding=1), "stem_conv2")
        g.add_layer("stem_lrn2", LocalResponseNormalization(), "stem_conv3")
        g.add_layer("stem_pool2", SubsamplingLayer(pooling_type="max",
                                                   kernel_size=3, stride=2,
                                                   padding=1), "stem_lrn2")
        x = inception("3a", "stem_pool2", 64, 96, 128, 16, 32, 32)
        x = inception("3b", x, 128, 128, 192, 32, 96, 64)
        g.add_layer("pool3", SubsamplingLayer(pooling_type="max", kernel_size=3,
                                              stride=2, padding=1), x)
        x = inception("4a", "pool3", 192, 96, 208, 16, 48, 64)
        x = inception("4b", x, 160, 112, 224, 24, 64, 64)
        x = inception("4c", x, 128, 128, 256, 24, 64, 64)
        x = inception("4d", x, 112, 144, 288, 32, 64, 64)
        x = inception("4e", x, 256, 160, 320, 32, 128, 128)
        g.add_layer("pool4", SubsamplingLayer(pooling_type="max", kernel_size=3,
                                              stride=2, padding=1), x)
        x = inception("5a", "pool4", 256, 160, 320, 32, 128, 128)
        x = inception("5b", x, 384, 192, 384, 48, 128, 128)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("dropout", DropoutLayer(dropout=0.4), "avgpool")
        g.add_layer("fc", OutputLayer(n_out=self.num_classes,
                                      activation="softmax", loss="mcxent",
                                      n_in=1024), "dropout")
        g.set_outputs("fc")
        return g.build()


class InceptionResNetV1(ZooModel):
    name = "inception_resnet_v1"
    default_input_shape = (160, 160, 3)

    def conf(self):
        h, w, c = self.input_shape
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater(Adam(1e-3)))
             .weight_init("relu")
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))

        def conv_bn(name, inp, n_out, k, stride=1, pad=0, act="relu"):
            g.add_layer(f"{name}_c", ConvolutionLayer(n_out=n_out, kernel_size=k,
                                                      stride=stride, padding=pad,
                                                      has_bias=False), inp)
            g.add_layer(f"{name}_bn", BatchNormalization(activation=act),
                        f"{name}_c")
            return f"{name}_bn"

        def block35(name, inp, scale=0.17):
            """Inception-ResNet-A (35x35)."""
            b0 = conv_bn(f"{name}_b0", inp, 32, 1)
            b1 = conv_bn(f"{name}_b1a", inp, 32, 1)
            b1 = conv_bn(f"{name}_b1b", b1, 32, 3, pad=1)
            b2 = conv_bn(f"{name}_b2a", inp, 32, 1)
            b2 = conv_bn(f"{name}_b2b", b2, 32, 3, pad=1)
            b2 = conv_bn(f"{name}_b2c", b2, 32, 3, pad=1)
            g.add_vertex(f"{name}_cat", MergeVertex(), b0, b1, b2)
            up = conv_bn(f"{name}_up", f"{name}_cat", 256, 1, act="identity")
            g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), up)
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                         f"{name}_scale")
            g.add_layer(f"{name}", ActivationLayer(activation="relu"),
                        f"{name}_add")
            return name

        def block17(name, inp, scale=0.10):
            """Inception-ResNet-B (17x17)."""
            b0 = conv_bn(f"{name}_b0", inp, 128, 1)
            b1 = conv_bn(f"{name}_b1a", inp, 128, 1)
            b1 = conv_bn(f"{name}_b1b", b1, 128, (1, 7), pad=(0, 3))
            b1 = conv_bn(f"{name}_b1c", b1, 128, (7, 1), pad=(3, 0))
            g.add_vertex(f"{name}_cat", MergeVertex(), b0, b1)
            up = conv_bn(f"{name}_up", f"{name}_cat", 896, 1, act="identity")
            g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), up)
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                         f"{name}_scale")
            g.add_layer(f"{name}", ActivationLayer(activation="relu"),
                        f"{name}_add")
            return name

        def block8(name, inp, scale=0.20, act=True):
            """Inception-ResNet-C (8x8)."""
            b0 = conv_bn(f"{name}_b0", inp, 192, 1)
            b1 = conv_bn(f"{name}_b1a", inp, 192, 1)
            b1 = conv_bn(f"{name}_b1b", b1, 192, (1, 3), pad=(0, 1))
            b1 = conv_bn(f"{name}_b1c", b1, 192, (3, 1), pad=(1, 0))
            g.add_vertex(f"{name}_cat", MergeVertex(), b0, b1)
            up = conv_bn(f"{name}_up", f"{name}_cat", 1792, 1, act="identity")
            g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), up)
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                         f"{name}_scale")
            if act:
                g.add_layer(f"{name}", ActivationLayer(activation="relu"),
                            f"{name}_add")
                return name
            return f"{name}_add"

        # stem
        x = conv_bn("stem1", "input", 32, 3, stride=2)
        x = conv_bn("stem2", x, 32, 3)
        x = conv_bn("stem3", x, 64, 3, pad=1)
        g.add_layer("stem_pool", SubsamplingLayer(pooling_type="max",
                                                  kernel_size=3, stride=2), x)
        x = conv_bn("stem4", "stem_pool", 80, 1)
        x = conv_bn("stem5", x, 192, 3)
        x = conv_bn("stem6", x, 256, 3, stride=2)
        for i in range(5):
            x = block35(f"a{i}", x)
        # reduction A
        ra0 = conv_bn("redA_b0", x, 384, 3, stride=2)
        ra1 = conv_bn("redA_b1a", x, 192, 1)
        ra1 = conv_bn("redA_b1b", ra1, 192, 3, pad=1)
        ra1 = conv_bn("redA_b1c", ra1, 256, 3, stride=2)
        g.add_layer("redA_pool", SubsamplingLayer(pooling_type="max",
                                                  kernel_size=3, stride=2), x)
        g.add_vertex("redA", MergeVertex(), ra0, ra1, "redA_pool")
        x = "redA"
        for i in range(10):
            x = block17(f"b{i}", x)
        # reduction B
        rb0 = conv_bn("redB_b0a", x, 256, 1)
        rb0 = conv_bn("redB_b0b", rb0, 384, 3, stride=2)
        rb1 = conv_bn("redB_b1a", x, 256, 1)
        rb1 = conv_bn("redB_b1b", rb1, 256, 3, stride=2)
        rb2 = conv_bn("redB_b2a", x, 256, 1)
        rb2 = conv_bn("redB_b2b", rb2, 256, 3, pad=1)
        rb2 = conv_bn("redB_b2c", rb2, 256, 3, stride=2)
        g.add_layer("redB_pool", SubsamplingLayer(pooling_type="max",
                                                  kernel_size=3, stride=2), x)
        g.add_vertex("redB", MergeVertex(), rb0, rb1, rb2, "redB_pool")
        x = "redB"
        for i in range(5):
            x = block8(f"c{i}", x)
        x = block8("c5", x, scale=1.0, act=False)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("dropout", DropoutLayer(dropout=0.2), "avgpool")
        g.add_layer("bottleneck", DenseLayer(n_out=128, activation="identity",
                                             n_in=1792), "dropout")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("out", CenterLossOutputLayer(
            n_out=self.num_classes, n_in=128, activation="softmax",
            loss="mcxent"), "embeddings")
        g.set_outputs("out")
        return g.build()


class FaceNetNN4Small2(ZooModel):
    """NN4-small2 face embedding net (parity: zoo/model/FaceNetNN4Small2.java).
    Output: 128-d L2-normalized embedding + center-loss softmax head."""
    name = "facenet_nn4_small2"
    default_input_shape = (96, 96, 3)

    def conf(self):
        h, w, c = self.input_shape
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater(Adam(1e-3)))
             .weight_init("relu")
             .activation("relu")
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))

        def conv_bn(name, inp, n_out, k, stride=1, pad=0):
            g.add_layer(f"{name}_c", ConvolutionLayer(n_out=n_out, kernel_size=k,
                                                      stride=stride, padding=pad,
                                                      has_bias=False), inp)
            g.add_layer(f"{name}_bn", BatchNormalization(activation="relu"),
                        f"{name}_c")
            return f"{name}_bn"

        def inception(name, inp, c1, c3r, c3, c5r, c5, pp, pool_type="max"):
            branches = []
            if c1:
                branches.append(conv_bn(f"{name}_1x1", inp, c1, 1))
            b3 = conv_bn(f"{name}_3x3r", inp, c3r, 1)
            branches.append(conv_bn(f"{name}_3x3", b3, c3, 3, pad=1))
            if c5:
                b5 = conv_bn(f"{name}_5x5r", inp, c5r, 1)
                branches.append(conv_bn(f"{name}_5x5", b5, c5, 5, pad=2))
            g.add_layer(f"{name}_pool",
                        SubsamplingLayer(pooling_type=pool_type, kernel_size=3,
                                         stride=1, padding=1), inp)
            if pp:
                branches.append(conv_bn(f"{name}_pp", f"{name}_pool", pp, 1))
            else:
                branches.append(f"{name}_pool")
            g.add_vertex(name, MergeVertex(), *branches)
            return name

        x = conv_bn("stem1", "input", 64, 7, stride=2, pad=3)
        g.add_layer("pool1", SubsamplingLayer(pooling_type="max", kernel_size=3,
                                              stride=2, padding=1), x)
        x = conv_bn("stem2", "pool1", 64, 1)
        x = conv_bn("stem3", x, 192, 3, pad=1)
        g.add_layer("pool2", SubsamplingLayer(pooling_type="max", kernel_size=3,
                                              stride=2, padding=1), x)
        x = inception("3a", "pool2", 64, 96, 128, 16, 32, 32)
        x = inception("3b", x, 64, 96, 128, 32, 64, 64, pool_type="pnorm")
        x = inception("3c", x, 0, 128, 256, 32, 64, 0)
        g.add_layer("pool3", SubsamplingLayer(pooling_type="max", kernel_size=3,
                                              stride=2, padding=1), x)
        x = inception("4a", "pool3", 256, 96, 192, 32, 64, 128,
                      pool_type="pnorm")
        x = inception("4e", x, 0, 160, 256, 64, 128, 0)
        g.add_layer("pool4", SubsamplingLayer(pooling_type="max", kernel_size=3,
                                              stride=2, padding=1), x)
        x = inception("5a", "pool4", 256, 96, 384, 0, 0, 96, pool_type="pnorm")
        x = inception("5b", x, 256, 96, 384, 0, 0, 96)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("bottleneck", DenseLayer(n_out=128, activation="identity"),
                    "avgpool")
        g.add_vertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.add_layer("lossLayer", CenterLossOutputLayer(
            n_out=self.num_classes, n_in=128, activation="softmax",
            loss="mcxent"), "embeddings")
        g.set_outputs("lossLayer")
        return g.build()
