"""Bundled char-LM corpus access (shared by the textgenlstm pretrained
artifact's trainer, its reproduction test, and anyone wanting a small
self-contained text dataset — parity role: the corpus the reference's
TextGenerationLSTM examples train on)."""

from __future__ import annotations

from pathlib import Path

import numpy as np

CORPUS_PATH = Path(__file__).parent / "pretrained_artifacts" / \
    "corpus_textgen.txt"


def corpus_windows(T: int = 64, stride=None):
    """The bundled corpus as one-hot next-char windows + the vocab string.

    The last 1/8th of the TEXT is the held-out split (no window from it
    overlaps training text); training windows may overlap via ``stride``
    (the classic char-RNN augmentation). Returns
    ``(xtr, ytr), (xte, yte), vocab``."""
    text = CORPUS_PATH.read_text(encoding="utf-8")
    vocab = "".join(sorted(set(text)))
    idx = {c: i for i, c in enumerate(vocab)}
    ids = np.array([idx[c] for c in text], np.int64)
    eye = np.eye(len(vocab), dtype=np.float32)
    cut = (len(ids) * 7 // 8)

    def windows(a, st):
        starts = np.arange(0, len(a) - T - 1, st)
        src = np.stack([a[s:s + T] for s in starts])
        tgt = np.stack([a[s + 1:s + T + 1] for s in starts])
        return eye[src], eye[tgt]

    xtr, ytr = windows(ids[:cut], stride or T)
    xte, yte = windows(ids[cut:], T)
    return (xtr, ytr), (xte, yte), vocab
