"""Sequential zoo models.

Parity surface (architectures match the reference definitions; layout is
NHWC-native):
- LeNet            — zoo/model/LeNet.java:1-127
- SimpleCNN        — zoo/model/SimpleCNN.java
- AlexNet          — zoo/model/AlexNet.java (LRN + 5 conv + 3 dense)
- VGG16 / VGG19    — zoo/model/VGG16.java:1-181, VGG19.java
- Darknet19        — zoo/model/Darknet19.java (conv-BN-leakyrelu stacks)
- TextGenerationLSTM — zoo/model/TextGenerationLSTM.java (char-level 2xLSTM)
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer, SubsamplingLayer, DenseLayer, OutputLayer,
    BatchNormalization, LocalResponseNormalization, DropoutLayer,
    GlobalPoolingLayer, LSTM, RnnOutputLayer, ActivationLayer,
)
from deeplearning4j_tpu.nn.updaters import Adam, Nesterovs, Sgd
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class LeNet(ZooModel):
    name = "lenet"
    default_input_shape = (28, 28, 1)

    def conf(self):
        h, w, c = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater(Adam(1e-3)))
                .weight_init("xavier")
                .list()
                .layer(ConvolutionLayer(n_out=20, kernel_size=5, stride=1,
                                        activation="relu"))
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=2,
                                        stride=2))
                .layer(ConvolutionLayer(n_out=50, kernel_size=5, stride=1,
                                        activation="relu"))
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=2,
                                        stride=2))
                .layer(DenseLayer(n_out=500, activation="relu"))
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())


class SimpleCNN(ZooModel):
    name = "simplecnn"
    default_input_shape = (48, 48, 3)

    def conf(self):
        h, w, c = self.input_shape
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater(Adam(1e-3)))
             .activation("relu")
             .weight_init("relu")
             .list())
        for n_out, pool in [(16, False), (16, True), (32, False), (32, True),
                            (64, False), (64, True)]:
            b.layer(ConvolutionLayer(n_out=n_out, kernel_size=3, padding=1))
            b.layer(BatchNormalization())
            if pool:
                b.layer(SubsamplingLayer(pooling_type="max", kernel_size=2,
                                         stride=2))
        b.layer(DropoutLayer(dropout=0.5))
        b.layer(GlobalPoolingLayer(pooling_type="avg"))
        b.layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                            loss="mcxent"))
        return b.set_input_type(InputType.convolutional(h, w, c)).build()


class AlexNet(ZooModel):
    name = "alexnet"
    default_input_shape = (224, 224, 3)

    def conf(self):
        h, w, c = self.input_shape
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater(Nesterovs(1e-2, momentum=0.9)))
                .weight_init("distribution").dist("normal", 0.0, 0.01)
                .activation("relu")
                .l2(5e-4)
                .list()
                .layer(ConvolutionLayer(n_out=96, kernel_size=11, stride=4,
                                        padding=2))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=3,
                                        stride=2))
                .layer(ConvolutionLayer(n_out=256, kernel_size=5, padding=2,
                                        bias_init=1.0))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=3,
                                        stride=2))
                .layer(ConvolutionLayer(n_out=384, kernel_size=3, padding=1))
                .layer(ConvolutionLayer(n_out=384, kernel_size=3, padding=1,
                                        bias_init=1.0))
                .layer(ConvolutionLayer(n_out=256, kernel_size=3, padding=1,
                                        bias_init=1.0))
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=3,
                                        stride=2))
                .layer(DenseLayer(n_out=4096, bias_init=1.0, dropout=0.5))
                .layer(DenseLayer(n_out=4096, bias_init=1.0, dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.convolutional(h, w, c))
                .build())


def _vgg_blocks(b, cfg):
    for item in cfg:
        if item == "M":
            b.layer(SubsamplingLayer(pooling_type="max", kernel_size=2, stride=2))
        else:
            b.layer(ConvolutionLayer(n_out=item, kernel_size=3, padding=1,
                                     activation="relu"))
    return b


class VGG16(ZooModel):
    name = "vgg16"
    default_input_shape = (224, 224, 3)
    _cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
            512, 512, 512, "M", 512, 512, 512, "M"]

    def conf(self):
        h, w, c = self.input_shape
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater(Nesterovs(1e-2, momentum=0.9)))
             .weight_init("relu")
             .list())
        _vgg_blocks(b, self._cfg)
        b.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
        b.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
        b.layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                            loss="mcxent"))
        return b.set_input_type(InputType.convolutional(h, w, c)).build()


class VGG19(VGG16):
    name = "vgg19"
    _cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
            512, 512, 512, 512, "M", 512, 512, 512, 512, "M"]


class Darknet19(ZooModel):
    name = "darknet19"
    default_input_shape = (224, 224, 3)

    def conf(self):
        h, w, c = self.input_shape
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater(Adam(1e-3)))
             .weight_init("relu")
             .list())

        def conv_bn(n_out, k):
            b.layer(ConvolutionLayer(n_out=n_out, kernel_size=k,
                                     padding=k // 2, has_bias=False))
            b.layer(BatchNormalization(activation="leakyrelu"))

        conv_bn(32, 3)
        b.layer(SubsamplingLayer(pooling_type="max", kernel_size=2, stride=2))
        conv_bn(64, 3)
        b.layer(SubsamplingLayer(pooling_type="max", kernel_size=2, stride=2))
        for ns in [(128, 64, 128), (256, 128, 256)]:
            conv_bn(ns[0], 3)
            conv_bn(ns[1], 1)
            conv_bn(ns[2], 3)
            b.layer(SubsamplingLayer(pooling_type="max", kernel_size=2, stride=2))
        for ns in [(512, 256, 512, 256, 512), (1024, 512, 1024, 512, 1024)]:
            for i, n in enumerate(ns):
                conv_bn(n, 3 if i % 2 == 0 else 1)
            if ns[0] == 512:
                b.layer(SubsamplingLayer(pooling_type="max", kernel_size=2,
                                         stride=2))
        b.layer(ConvolutionLayer(n_out=self.num_classes, kernel_size=1))
        b.layer(GlobalPoolingLayer(pooling_type="avg"))
        b.layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                            loss="mcxent", has_bias=True,
                            n_in=self.num_classes))
        return b.set_input_type(InputType.convolutional(h, w, c)).build()


class TextGenerationLSTM(ZooModel):
    name = "textgenlstm"
    default_input_shape = (77,)  # vocab size

    def __init__(self, total_unique_characters: int = 77, seed: int = 123,
                 **kwargs):
        # num_classes is the vocab for an LM — accept the generic zoo kwarg
        total_unique_characters = kwargs.pop("num_classes",
                                             total_unique_characters)
        kwargs.pop("input_shape", None)
        super().__init__(num_classes=total_unique_characters, seed=seed,
                         input_shape=(total_unique_characters,), **kwargs)

    def conf(self):
        vocab = self.input_shape[0]
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater(Adam(1e-3)))
                .weight_init("xavier")
                .gradient_normalization("ClipElementWiseAbsoluteValue", 10.0)
                .list()
                .layer(LSTM(n_out=256, activation="tanh"))
                .layer(LSTM(n_out=256, activation="tanh"))
                .layer(RnnOutputLayer(n_out=vocab, activation="softmax",
                                      loss="mcxent"))
                .set_input_type(InputType.recurrent(vocab))
                .build())


class TinyTransformer(ZooModel):
    """Decoder-only transformer char/LM model — a TPU-first extension (the
    reference's zoo tops out at recurrent TextGenerationLSTM; attention does
    not exist in it, SURVEY §5). Pre-LN blocks of causal MultiHeadAttention
    (flash-attention Pallas kernel when supported) + GELU FFN, residual adds
    via the same layer stack the rest of the framework uses."""
    name = "tinytransformer"
    default_input_shape = (64,)    # vocab size

    def __init__(self, vocab_size: int = 64, n_layers: int = 2,
                 d_model: int = 128, n_heads: int = 4, max_len: int = 512,
                 seed: int = 123, **kwargs):
        # num_classes is the vocab for an LM — accept the generic zoo kwarg
        vocab_size = kwargs.pop("num_classes", vocab_size)
        kwargs.pop("input_shape", None)
        super().__init__(num_classes=vocab_size, seed=seed,
                         input_shape=(vocab_size,), **kwargs)
        self.n_layers = n_layers
        self.d_model = d_model
        self.n_heads = n_heads
        self.max_len = max_len

    def conf(self):
        from deeplearning4j_tpu.nn.conf.graph_conf import ElementWiseVertex
        from deeplearning4j_tpu.nn.layers.attention import (
            MultiHeadAttention, LayerNormalization, PositionalEmbedding)
        from deeplearning4j_tpu.nn.layers.rnn import RnnOutputLayer
        vocab = self.input_shape[0]
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater(Adam(3e-4)))
             .weight_init("xavier")
             .graph_builder()
             .add_inputs("tokens")
             .set_input_types(InputType.recurrent(vocab)))
        g.add_layer("embed", DenseLayer(n_out=self.d_model,
                                        activation="identity"), "tokens")
        g.add_layer("pos", PositionalEmbedding(max_len=self.max_len), "embed")
        prev = "pos"
        for i in range(self.n_layers):
            g.add_layer(f"b{i}_ln1", LayerNormalization(), prev)
            g.add_layer(f"b{i}_attn",
                        MultiHeadAttention(n_out=self.d_model,
                                           n_heads=self.n_heads, causal=True),
                        f"b{i}_ln1")
            g.add_vertex(f"b{i}_res1", ElementWiseVertex(op="add"),
                         f"b{i}_attn", prev)
            g.add_layer(f"b{i}_ln2", LayerNormalization(), f"b{i}_res1")
            g.add_layer(f"b{i}_ff1", DenseLayer(n_out=4 * self.d_model,
                                                activation="gelu"),
                        f"b{i}_ln2")
            g.add_layer(f"b{i}_ff2", DenseLayer(n_out=self.d_model,
                                                activation="identity"),
                        f"b{i}_ff1")
            g.add_vertex(f"b{i}_res2", ElementWiseVertex(op="add"),
                         f"b{i}_ff2", f"b{i}_res1")
            prev = f"b{i}_res2"
        g.add_layer("ln_f", LayerNormalization(), prev)
        g.add_layer("out", RnnOutputLayer(n_out=vocab, activation="softmax",
                                          loss="mcxent"), "ln_f")
        return g.set_outputs("out").build()
