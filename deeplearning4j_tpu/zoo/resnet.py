"""ResNet50 as a ComputationGraph.

Parity surface: reference zoo/model/ResNet50.java:1-239 (bottleneck residual
blocks with identity/projection shortcuts, ElementWiseVertex add). NHWC
layout; BN after each conv (no bias on convs feeding BN — saves HBM traffic,
XLA fuses BN+relu into the conv epilogue).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_conf import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ConvolutionLayer, SubsamplingLayer, BatchNormalization, ActivationLayer,
    GlobalPoolingLayer, OutputLayer, ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.updaters import Nesterovs
from deeplearning4j_tpu.zoo.zoo_model import ZooModel


class ResNet50(ZooModel):
    name = "resnet50"
    default_input_shape = (224, 224, 3)

    def conf(self):
        h, w, c = self.input_shape
        # width_mult shrinks every filter count (bundled-artifact variants;
        # 1.0 = the reference architecture). Kept MXU-friendly by rounding
        # to multiples of 8.
        wm = float(self.kwargs.get("width_mult", 1.0))

        def _w(f):
            return max(8, int(round(f * wm / 8)) * 8) if wm != 1.0 else f
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater(Nesterovs(1e-1, momentum=0.9)))
             .weight_init("relu")
             .l2(1e-4)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(h, w, c)))

        def conv_bn(name, inp, n_out, k, stride=1, pad=0, act=True):
            g.add_layer(f"{name}_conv",
                        ConvolutionLayer(n_out=n_out, kernel_size=k,
                                         stride=stride, padding=pad,
                                         has_bias=False), inp)
            g.add_layer(f"{name}_bn",
                        BatchNormalization(
                            activation="relu" if act else "identity"),
                        f"{name}_conv")
            return f"{name}_bn"

        def bottleneck(name, inp, filters, stride=1, project=False):
            f1, f2, f3 = filters
            x = conv_bn(f"{name}_a", inp, f1, 1, stride=stride)
            x = conv_bn(f"{name}_b", x, f2, 3, pad=1)
            x = conv_bn(f"{name}_c", x, f3, 1, act=False)
            if project:
                sc = conv_bn(f"{name}_sc", inp, f3, 1, stride=stride, act=False)
            else:
                sc = inp
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, sc)
            g.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                        f"{name}_add")
            return f"{name}_out"

        x = conv_bn("stem", "input", _w(64), 7, stride=2, pad=3)
        g.add_layer("stem_pool",
                    SubsamplingLayer(pooling_type="max", kernel_size=3,
                                     stride=2, padding=1), x)
        x = "stem_pool"
        stages = [
            ("res2", (_w(64), _w(64), _w(256)), 3, 1),
            ("res3", (_w(128), _w(128), _w(512)), 4, 2),
            ("res4", (_w(256), _w(256), _w(1024)), 6, 2),
            ("res5", (_w(512), _w(512), _w(2048)), 3, 2),
        ]
        for sname, filters, blocks, stride in stages:
            x = bottleneck(f"{sname}_0", x, filters, stride=stride, project=True)
            for i in range(1, blocks):
                x = bottleneck(f"{sname}_{i}", x, filters)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("fc", OutputLayer(n_out=self.num_classes,
                                      activation="softmax", loss="mcxent",
                                      n_in=_w(2048)), "avgpool")
        g.set_outputs("fc")
        return g.build()


class ResNet50Cifar(ResNet50):
    """Shrunk (width_mult=0.25) CIFAR-shape ResNet50 with a repo-bundled
    pretrained artifact — the ComputationGraph counterpart of the bundled
    MLN artifacts, proving init_pretrained moves CG weights end-to-end
    (parity role: reference ZooModel.initPretrained:40 serving trained
    ResNet50 weights)."""
    name = "resnet50_cifar10"
    default_input_shape = (32, 32, 3)

    def __init__(self, num_classes: int = 10, seed: int = 123, **kwargs):
        kwargs.setdefault("width_mult", 0.25)
        super().__init__(num_classes=num_classes, seed=seed, **kwargs)
