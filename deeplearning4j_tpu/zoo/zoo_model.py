"""ZooModel base.

Parity surface: reference zoo/ZooModel.java — ``init()`` builds the network,
``initPretrained()`` loads pretrained weights. This environment has zero
network egress, so pretrained weights load from the local cache dir
(``<data_dir>/pretrained/<name>.zip`` — same role as the reference's
~/.deeplearning4j cache + checksum) and raise a clear error when absent.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Tuple


class ZooModel:
    name: str = "zoo_model"
    default_input_shape: Tuple[int, ...] = (224, 224, 3)

    def __init__(self, num_classes: int = 1000, seed: int = 123,
                 input_shape: Tuple[int, ...] = None, **kwargs):
        self.num_classes = num_classes
        self.seed = seed
        self.input_shape = tuple(input_shape or self.default_input_shape)
        self.kwargs = kwargs

    def conf(self):
        """Build the MultiLayerConfiguration / ComputationGraphConfiguration."""
        raise NotImplementedError

    def updater(self, default):
        """The training updater: the ``updater=`` constructor kwarg when
        given, else the model's reference-parity default. (Overriding
        ``conf.global_conf.updater`` after build has no effect — finalize()
        copies updaters onto layers — so the kwarg is the supported way.)"""
        return self.kwargs.get("updater") or default

    def init(self):
        """Build + initialize the network (parity: ZooModel.init).

        ``compute_dtype='bfloat16'`` constructor kwarg enables mixed-precision
        compute on any zoo model: params stay f32, forward/backward cast to
        the compute dtype (MXU-friendly; see util/dtypes.py contract)."""
        conf = self.conf()
        cd = self.kwargs.get("compute_dtype")
        if cd:
            conf.global_conf.compute_dtype = cd
        if self.kwargs.get("remat"):
            from deeplearning4j_tpu.util.remat import check_remat_mode
            conf.global_conf.remat = check_remat_mode(self.kwargs["remat"])
        from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
        from deeplearning4j_tpu.models import MultiLayerNetwork, ComputationGraph
        if isinstance(conf, MultiLayerConfiguration):
            return MultiLayerNetwork(conf).init()
        return ComputationGraph(conf).init()

    #: repo-bundled artifacts (trained in-repo by tools/make_pretrained.py,
    #: committed with their manifest) — the fallback when the user cache has
    #: no entry, playing the role of the reference's hosted weight files
    _BUNDLED_DIR = Path(__file__).parent / "pretrained_artifacts"

    def cache_path(self) -> Path:
        """Where a user-provisioned pretrained zip lives (the WRITE
        target); ``pretrained_path`` resolves reads across cache+bundle."""
        from deeplearning4j_tpu.data.fetchers import data_dir
        return data_dir() / "pretrained" / f"{self.name}.zip"

    def pretrained_path(self) -> Path:
        """Read resolution: the user cache when present, else the
        repo-bundled artifact. Never use as a write target (writing here
        could clobber the committed bundle) — use ``cache_path``."""
        cached = self.cache_path()
        if cached.exists():
            return cached
        bundled = self._BUNDLED_DIR / f"{self.name}.zip"
        return bundled if bundled.exists() else cached

    @staticmethod
    def _manifest_path() -> Path:
        from deeplearning4j_tpu.data.fetchers import data_dir
        return data_dir() / "pretrained" / "manifest.json"

    @classmethod
    def manifest(cls) -> dict:
        """Merged manifest: user-cache entries override the bundled ones.
        Values are either a bare sha256 string (legacy) or a dict with
        ``sha256`` plus recorded eval metadata."""
        merged = {}
        bundled = cls._BUNDLED_DIR / "manifest.json"
        if bundled.exists():
            merged.update(json.loads(bundled.read_text()))
        mp = cls._manifest_path()
        if mp.exists():
            merged.update(json.loads(mp.read_text()))
        return merged

    @staticmethod
    def write_manifest_entry(name: str, path) -> str:
        """Record the SHA-256 of a cached pretrained zip in the manifest
        (the publisher-side half of the integrity check). Returns the hash."""
        digest = hashlib.sha256(Path(path).read_bytes()).hexdigest()
        mp = ZooModel._manifest_path()
        manifest = {}
        if mp.exists():
            manifest = json.loads(mp.read_text())
        manifest[name] = digest
        mp.parent.mkdir(parents=True, exist_ok=True)
        mp.write_text(json.dumps(manifest, indent=2))
        return digest

    def init_pretrained(self):
        """Load pretrained weights from the local cache, verifying the
        file's SHA-256 against ``pretrained/manifest.json`` when an entry
        exists (parity: ZooModel.initPretrained :40 downloads then verifies
        a checksum — the air gap removes the download, not the integrity
        check). A corrupt or tampered cache raises instead of silently
        loading garbage weights."""
        p = self.pretrained_path()
        if not p.exists():
            raise FileNotFoundError(
                f"No pretrained weights for '{self.name}' at {p}. This "
                f"environment has no network egress; place a model zip there "
                f"(util.model_serializer format) to use init_pretrained().")
        # validate against the manifest that SHIPPED WITH this file's
        # source: a user-provisioned cache zip checks the cache manifest
        # (none -> unchecked, as before the bundle existed), a bundled zip
        # checks the committed bundle manifest — so a user's own lenet.zip
        # is never rejected against the bundled artifact's hash
        if p.parent == self._BUNDLED_DIR:
            mf = self._BUNDLED_DIR / "manifest.json"
        else:
            mf = self._manifest_path()
        want = None
        if mf.exists():
            want = json.loads(mf.read_text()).get(self.name)
        if isinstance(want, dict):
            want = want.get("sha256")
        if want is not None:
            got = hashlib.sha256(p.read_bytes()).hexdigest()
            if got != want:
                raise IOError(
                    f"Checksum mismatch for pretrained '{self.name}': "
                    f"manifest says sha256={want} but {p} hashes to "
                    f"{got}. The cached file is corrupt or was "
                    f"replaced — delete it and re-provision.")
        from deeplearning4j_tpu.util.model_serializer import guess_model
        return guess_model(str(p))
