"""Model zoo.

Parity surface: reference deeplearning4j-zoo/ — 11 instantiable
architectures (zoo/model/*.java) + ZooModel.initPretrained weight loading
(ZooModel.java:40).
"""

from deeplearning4j_tpu.zoo.zoo_model import ZooModel
from deeplearning4j_tpu.zoo.simple import (
    LeNet, SimpleCNN, AlexNet, VGG16, VGG19, Darknet19, TextGenerationLSTM,
    TinyTransformer,
)
from deeplearning4j_tpu.zoo.resnet import ResNet50, ResNet50Cifar
from deeplearning4j_tpu.zoo.inception import (
    GoogLeNet, InceptionResNetV1, FaceNetNN4Small2,
)

__all__ = ["ZooModel", "LeNet", "SimpleCNN", "AlexNet", "VGG16", "VGG19",
           "Darknet19", "TextGenerationLSTM", "TinyTransformer", "ResNet50", "ResNet50Cifar", "GoogLeNet",
           "InceptionResNetV1", "FaceNetNN4Small2"]
