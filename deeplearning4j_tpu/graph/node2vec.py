"""node2vec: biased second-order random walks + skip-gram embeddings.

Parity: the reference ships node2vec inside deeplearning4j-nlp
(models/node2vec — SURVEY.md §2 #26 lists it with the embeddings family)
on top of the same SequenceVectors machinery DeepWalk uses. Here it reuses
the DeepWalk trainer (graph/deepwalk.py) with a (p, q)-biased walker
(Grover & Leskovec 2016): return parameter p penalizes revisiting the
previous node, in-out parameter q interpolates BFS (q>1) vs DFS (q<1)."""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.graph.api import Graph
from deeplearning4j_tpu.graph.deepwalk import DeepWalk


class Node2VecWalkIterator:
    """Second-order biased walks. Yields one walk (list of vertex ids) per
    ``__next__``; one pass enumerates every vertex as a start (parity with
    RandomWalkIterator's epoch semantics)."""

    def __init__(self, graph: Graph, walk_length: int, p: float = 1.0,
                 q: float = 1.0, seed: int = 0):
        self.graph = graph
        self.walk_length = walk_length
        self.p = float(p)
        self.q = float(q)
        self.seed = seed
        self._rng = np.random.RandomState(seed)
        self._order = self._rng.permutation(graph.num_vertices())
        self._pos = 0

    def reset(self):
        self._rng = np.random.RandomState(self.seed)
        self._order = self._rng.permutation(self.graph.num_vertices())
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._order)

    def __iter__(self):
        return self

    def __next__(self) -> List[int]:
        if not self.has_next():
            raise StopIteration
        start = int(self._order[self._pos])
        self._pos += 1
        return self._walk(start)

    def _walk(self, start: int) -> List[int]:
        walk = [start]
        prev: Optional[int] = None
        cur = start
        for _ in range(self.walk_length - 1):
            nbrs = self.graph.neighbors(cur)
            if not nbrs:
                break
            if prev is None:
                nxt = nbrs[self._rng.randint(len(nbrs))]
            else:
                prev_nbrs = set(self.graph.neighbors(prev))
                w = np.empty(len(nbrs))
                for i, nb in enumerate(nbrs):
                    if nb == prev:
                        w[i] = 1.0 / self.p          # return
                    elif nb in prev_nbrs:
                        w[i] = 1.0                   # distance 1 from prev
                    else:
                        w[i] = 1.0 / self.q          # explore outward
                w /= w.sum()
                nxt = nbrs[self._rng.choice(len(nbrs), p=w)]
            walk.append(int(nxt))
            prev, cur = cur, int(nxt)
        return walk


class Node2Vec(DeepWalk):
    """DeepWalk trainer fed by (p, q)-biased walks.

        n2v = (Node2Vec.Builder().vector_size(64).window_size(5)
               .p(0.25).q(4.0).build())
        n2v.initialize(graph)
        n2v.fit(graph, walk_length=40)
    """

    def __init__(self, *args, p: float = 1.0, q: float = 1.0, **kwargs):
        super().__init__(*args, **kwargs)
        self.p = p
        self.q = q

    class Builder(DeepWalk.Builder):
        def __init__(self):
            super().__init__()
            self._p = 1.0
            self._q = 1.0

        def p(self, v):
            self._p = v
            return self

        def q(self, v):
            self._q = v
            return self

        def build(self):
            dw = super().build()
            n2v = Node2Vec(vector_size=dw.vector_size,
                           window_size=dw.window_size,
                           learning_rate=dw.learning_rate, seed=dw.seed,
                           p=self._p, q=self._q)
            return n2v

    def fit(self, graph: Graph, walk_length: int = 40, epochs: int = 1):
        for ep in range(epochs):
            it = Node2VecWalkIterator(graph, walk_length, self.p, self.q,
                                      seed=self.seed + ep)
            self.fit_walks(it)
        return self
