"""DeepWalk graph vectorization (Perozzi et al. 2014).

Parity surface: reference graph/models/deepwalk/DeepWalk.java (builder:
vectorSize/windowSize/learningRate/seed; fit over random walks),
deepwalk/GraphHuffman.java (degree-weighted Huffman tree for hierarchical
softmax), models/embeddings/InMemoryGraphLookupTable.java (in/out vector
tables + sigmoid table) and GraphVectorsImpl.java (similarity / nearest).

TPU re-design: the reference trains with per-pair scalar updates across a
thread pool. Here walks are generated vectorized on host
(:func:`walks.generate_walks_batch`), expanded into (center, target) skip-gram
pairs, and each batch is ONE jit'd hierarchical-softmax step on device —
shared with Word2Vec (:func:`nlp.word2vec._sg_hs_step`), gather → sigmoid →
scatter-add over the embedding tables.
"""

from __future__ import annotations

import heapq
import json
import os
from typing import List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.graph.api import Graph, NoEdgeHandling
from deeplearning4j_tpu.graph.walks import generate_walks_batch
from deeplearning4j_tpu.nlp.word2vec import _sg_hs_step


class GraphHuffman:
    """Huffman tree over vertex degrees for hierarchical softmax
    (parity: graph/models/deepwalk/GraphHuffman.java — codes, code lengths
    and inner-node paths per leaf)."""

    def __init__(self, n_vertices: int, max_code_length: int = 64):
        self.n = n_vertices
        self.max_code_length = max_code_length
        self.codes: List[List[int]] = [[] for _ in range(n_vertices)]
        self.points: List[List[int]] = [[] for _ in range(n_vertices)]

    def build_tree(self, vertex_degrees: Sequence[int]) -> "GraphHuffman":
        n = self.n
        assert len(vertex_degrees) == n
        if n == 1:
            self.codes[0], self.points[0] = [0], [0]
            return self
        heap = [(int(d), i, i) for i, d in enumerate(vertex_degrees)]
        heapq.heapify(heap)
        children = {}
        next_id = n
        while len(heap) > 1:
            c1, _, id1 = heapq.heappop(heap)
            c2, _, id2 = heapq.heappop(heap)
            children[next_id] = (id1, id2)
            heapq.heappush(heap, (c1 + c2, next_id, next_id))
            next_id += 1
        root = heap[0][2]
        stack = [(root, [], [])]
        while stack:
            node, code, path = stack.pop()
            if len(code) > self.max_code_length:
                raise RuntimeError(
                    f"code length exceeds {self.max_code_length} bits")
            if node < n:
                self.codes[node] = code
                # inner nodes numbered relative to leaf count, root first
                self.points[node] = [p - n for p in path]
                continue
            left, right = children[node]
            stack.append((left, code + [0], path + [node]))
            stack.append((right, code + [1], path + [node]))
        return self

    def get_code_length(self, v: int) -> int:
        return len(self.codes[v])

    def get_code(self, v: int) -> int:
        """Code as packed int, LSB = first branch (parity: getCode)."""
        out = 0
        for i, b in enumerate(self.codes[v]):
            out |= b << i
        return out

    def get_path_inner_node(self, v: int) -> List[int]:
        return list(self.points[v])

    def padded(self):
        """(points, codes, mask) padded (V, L) arrays for device HS steps."""
        L = max(1, max(len(c) for c in self.codes))
        pts = np.zeros((self.n, L), np.int32)
        cds = np.zeros((self.n, L), np.float32)
        msk = np.zeros((self.n, L), np.float32)
        for v in range(self.n):
            k = len(self.codes[v])
            pts[v, :k] = self.points[v]
            cds[v, :k] = self.codes[v]
            msk[v, :k] = 1.0
        return pts, cds, msk


class DeepWalk:
    """DeepWalk model (parity: graph/models/deepwalk/DeepWalk.java +
    GraphVectorsImpl similarity/nearest API; Builder pattern kept)."""

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 learning_rate: float = 0.025, seed: int = 12345,
                 batch_size: int = 4096, walks_per_vertex: int = 1):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.seed = seed
        self.batch_size = batch_size
        self.walks_per_vertex = walks_per_vertex
        self.syn0 = None     # (V, D) in-vectors (the embeddings)
        self.syn1 = None     # (V-1, D) inner-node vectors
        self._hs = None
        self._init_called = False

    # -- builder parity ----------------------------------------------------
    class Builder:
        def __init__(self):
            self._kw = {}

        def vector_size(self, d):
            self._kw["vector_size"] = d
            return self

        def window_size(self, w):
            self._kw["window_size"] = w
            return self

        def learning_rate(self, lr):
            self._kw["learning_rate"] = lr
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def build(self) -> "DeepWalk":
            return DeepWalk(**self._kw)

    # -- init --------------------------------------------------------------
    def initialize(self, graph_or_degrees) -> "DeepWalk":
        """Build the Huffman tree + lookup tables (parity: initialize)."""
        if isinstance(graph_or_degrees, Graph):
            degrees = graph_or_degrees.degrees()
        else:
            degrees = np.asarray(graph_or_degrees, np.int64)
        V = len(degrees)
        self._hs = GraphHuffman(V).build_tree(degrees)
        rng = np.random.default_rng(self.seed)
        scale = 0.5 / self.vector_size
        self.syn0 = jnp.asarray(
            rng.uniform(-scale, scale, (V, self.vector_size)), jnp.float32)
        self.syn1 = jnp.zeros((max(V - 1, 1), self.vector_size), jnp.float32)
        self._pts, self._cds, self._msk = self._hs.padded()
        self._init_called = True
        return self

    # -- training ----------------------------------------------------------
    def fit(self, graph: Graph, walk_length: int = 40, *,
            epochs: int = 1,
            mode: NoEdgeHandling = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED
            ) -> "DeepWalk":
        """Generate random walks over every vertex and train skip-gram HS
        (parity: DeepWalk.fit(IGraph, walkLength) — the reference fans walks
        across threads; here walk lanes are a vectorized batch and updates
        are one jit'd device step per pair-batch)."""
        if not self._init_called:
            self.initialize(graph)
        rng = np.random.default_rng(self.seed)
        V = graph.num_vertices()
        lr = self.learning_rate
        for _ in range(epochs):
            for _ in range(self.walks_per_vertex):
                starts = rng.permutation(V)
                for ofs in range(0, V, 1024):
                    walks = generate_walks_batch(
                        graph, starts[ofs:ofs + 1024], walk_length, rng,
                        mode=mode)
                    self._train_walks(walks, lr, rng)
        return self

    def fit_walks(self, walks: np.ndarray,
                  lr: Optional[float] = None) -> "DeepWalk":
        """Train directly on pre-generated (B, T) walks (parity:
        fit(GraphWalkIteratorProvider) — bring-your-own walk source)."""
        if not self._init_called:
            raise RuntimeError("DeepWalk not initialized (call initialize)")
        self._train_walks(np.asarray(walks, np.int32),
                          self.learning_rate if lr is None else lr,
                          np.random.default_rng(self.seed))
        return self

    def _train_walks(self, walks: np.ndarray, lr: float,
                     rng: np.random.Generator) -> None:
        B, T = walks.shape
        win = self.window_size
        centers, targets = [], []
        for i in range(T):
            lo, hi = max(0, i - win), min(T, i + win + 1)
            for j in range(lo, hi):
                if j == i:
                    continue
                centers.append(walks[:, i])
                targets.append(walks[:, j])
        centers = np.concatenate(centers)
        targets = np.concatenate(targets)
        order = rng.permutation(len(centers))
        centers, targets = centers[order], targets[order]
        bs = self.batch_size
        for ofs in range(0, len(centers), bs):
            c = jnp.asarray(centers[ofs:ofs + bs])
            t = targets[ofs:ofs + bs]
            self.syn0, self.syn1 = _sg_hs_step(
                self.syn0, self.syn1, c,
                jnp.asarray(self._pts[t]), jnp.asarray(self._cds[t]),
                jnp.asarray(self._msk[t]), jnp.float32(lr), normalize=True)

    # -- GraphVectors API --------------------------------------------------
    def get_vertex_vector(self, v: int) -> np.ndarray:
        return np.asarray(self.syn0[v])

    def num_vertices(self) -> int:
        return int(self.syn0.shape[0])

    def similarity(self, v1: int, v2: int) -> float:
        """Cosine similarity (parity: GraphVectorsImpl.similarity)."""
        a, b = np.asarray(self.syn0[v1]), np.asarray(self.syn0[v2])
        return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))

    def vertices_nearest(self, v: int, top: int = 5) -> List[int]:
        e = np.asarray(self.syn0)
        q = e[v] / (np.linalg.norm(e[v]) + 1e-12)
        sims = (e / (np.linalg.norm(e, axis=1, keepdims=True) + 1e-12)) @ q
        sims[v] = -np.inf
        return list(np.argsort(-sims)[:top])

    # -- persistence (parity: models/loader/GraphVectorSerializer) ---------
    def save(self, path: str) -> None:
        np.savez(path if path.endswith(".npz") else path + ".npz",
                 syn0=np.asarray(self.syn0), syn1=np.asarray(self.syn1),
                 pts=self._pts, cds=self._cds, msk=self._msk,
                 meta=json.dumps({"vector_size": self.vector_size,
                                  "window_size": self.window_size,
                                  "learning_rate": self.learning_rate,
                                  "seed": self.seed,
                                  "batch_size": self.batch_size,
                                  "walks_per_vertex": self.walks_per_vertex}))

    @staticmethod
    def load(path: str) -> "DeepWalk":
        if not os.path.exists(path) and os.path.exists(path + ".npz"):
            path = path + ".npz"
        z = np.load(path, allow_pickle=False)
        meta = json.loads(str(z["meta"]))
        dw = DeepWalk(vector_size=meta["vector_size"],
                      window_size=meta["window_size"],
                      learning_rate=meta["learning_rate"],
                      seed=meta.get("seed", 12345),
                      batch_size=meta.get("batch_size", 4096),
                      walks_per_vertex=meta.get("walks_per_vertex", 1))
        dw.syn0 = jnp.asarray(z["syn0"])
        dw.syn1 = jnp.asarray(z["syn1"])
        dw._pts, dw._cds, dw._msk = z["pts"], z["cds"], z["msk"]
        dw._init_called = True
        return dw
