"""Random-walk iterators over graphs.

Parity surface: reference graph/iterator/RandomWalkIterator.java,
WeightedRandomWalkIterator.java, GraphWalkIterator.java and the parallel
providers (iterator/parallel/RandomWalkGraphIteratorProvider.java).

TPU re-design: instead of the reference's one-vertex-at-a-time walk objects
handed to worker threads, walks are generated **vectorized** — all walks for
a batch of start vertices advance one hop per numpy step using the padded
adjacency matrix — and streamed to the device trainer in batches. The
iterator API below still yields individual walks for parity/tests.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.graph.api import Graph, NoEdgeHandling, NoEdgesException


class RandomWalkIterator:
    """Uniform random walks of fixed length from every vertex in order
    (parity: iterator/RandomWalkIterator.java — walk length semantics:
    ``walk_length`` hops, i.e. walk_length+1 vertices)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 mode: NoEdgeHandling = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
                 first_vertex: int = 0, last_vertex: Optional[int] = None):
        self.graph = graph
        self.walk_length = walk_length
        self.mode = mode
        self.first = first_vertex
        self.last = graph.num_vertices() if last_vertex is None else last_vertex
        self._rng = np.random.default_rng(seed)
        self._pos = self.first

    def __iter__(self) -> "RandomWalkIterator":
        return self

    def reset(self) -> None:
        self._pos = self.first

    def has_next(self) -> bool:
        return self._pos < self.last

    def __next__(self) -> List[int]:
        if self._pos >= self.last:
            raise StopIteration
        walk = [self._pos]
        cur = self._pos
        for _ in range(self.walk_length):
            cur = self.graph.random_neighbor(cur, self._rng, self.mode)
            walk.append(cur)
        self._pos += 1
        return walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional random walks
    (parity: iterator/WeightedRandomWalkIterator.java)."""

    def __next__(self) -> List[int]:
        if self._pos >= self.last:
            raise StopIteration
        walk = [self._pos]
        cur = self._pos
        for _ in range(self.walk_length):
            nbrs = self.graph.neighbors(cur)
            if not nbrs:
                if self.mode is NoEdgeHandling.EXCEPTION_ON_DISCONNECTED:
                    raise NoEdgesException(f"vertex {cur} has no edges")
                walk.append(cur)
                continue
            w = np.asarray(self.graph.neighbor_weights(cur), np.float64)
            cur = nbrs[int(self._rng.choice(len(nbrs), p=w / w.sum()))]
            walk.append(cur)
        self._pos += 1
        return walk


class RandomWalkGraphIteratorProvider:
    """Split the vertex range into N sub-ranges, one iterator each (parity:
    iterator/parallel/RandomWalkGraphIteratorProvider.java). On TPU the
    "threads" are batch lanes, but the provider API is kept for parity."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0,
                 mode: NoEdgeHandling = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED):
        self.graph, self.walk_length, self.seed, self.mode = (
            graph, walk_length, seed, mode)

    def get_graph_walk_iterators(self, n: int) -> List[RandomWalkIterator]:
        V = self.graph.num_vertices()
        n = max(1, min(n, V))
        bounds = np.linspace(0, V, n + 1).astype(int)
        return [RandomWalkIterator(self.graph, self.walk_length,
                                   seed=self.seed + i, mode=self.mode,
                                   first_vertex=int(bounds[i]),
                                   last_vertex=int(bounds[i + 1]))
                for i in range(n)]


def generate_walks_batch(graph: Graph, starts: np.ndarray, walk_length: int,
                         rng: np.random.Generator,
                         weighted: bool = False,
                         mode: NoEdgeHandling =
                         NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED
                         ) -> np.ndarray:
    """Vectorized walk generation: (B,) start vertices → (B, walk_length+1)
    int32 walks, all lanes advancing one hop per step via the padded
    adjacency (degree-0 vertices self-loop, or raise under
    EXCEPTION_ON_DISCONNECTED). This is the hot path DeepWalk.fit uses."""
    adj, w, deg = graph.padded_adjacency()
    B = starts.shape[0]
    out = np.empty((B, walk_length + 1), np.int32)
    out[:, 0] = cur = starts.astype(np.int32)
    max_deg = adj.shape[1]
    for t in range(walk_length):
        if (mode is NoEdgeHandling.EXCEPTION_ON_DISCONNECTED
                and (deg[cur] == 0).any()):
            bad = int(cur[np.argmax(deg[cur] == 0)])
            raise NoEdgesException(f"vertex {bad} has no edges")
        if weighted:
            # per-lane categorical draw over normalized neighbour weights
            u = rng.random((B, 1))
            cdf = np.cumsum(w[cur], axis=1)
            k = (u > cdf).sum(axis=1).clip(max=max_deg - 1)
        else:
            d = np.maximum(deg[cur], 1)
            k = (rng.random(B) * d).astype(np.int64)
        cur = adj[cur, k]
        out[:, t + 1] = cur
    return out
