"""In-memory graph structures.

Parity surface: reference deeplearning4j-graph/.../graph/api/IGraph.java,
graph/graph/Graph.java (adjacency-list graph, directed or undirected),
api/Edge.java, api/Vertex.java, api/NoEdgeHandling.java,
data/GraphLoader.java (delimited edge-list / weighted edge-list loaders).

The TPU re-design keeps the graph itself as host-side numpy adjacency (graphs
here are metadata, not tensors); everything tensor-shaped (walk batches,
embedding tables) lives on device in :mod:`deepwalk`.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Any, Iterable, List, Optional, Sequence

import numpy as np


class NoEdgeHandling(Enum):
    """What a random walk does at a vertex with no outgoing edges
    (parity: api/NoEdgeHandling.java)."""
    SELF_LOOP_ON_DISCONNECTED = "self_loop"
    EXCEPTION_ON_DISCONNECTED = "exception"


class NoEdgesException(RuntimeError):
    """Raised when a walk hits a degree-0 vertex under
    EXCEPTION_ON_DISCONNECTED (parity: exception/NoEdgesException.java)."""


@dataclass
class Vertex:
    """A vertex: integer index + arbitrary value (parity: api/Vertex.java)."""
    index: int
    value: Any = None


@dataclass
class Edge:
    """An edge, directed or not (parity: api/Edge.java)."""
    src: int
    dst: int
    value: Any = None
    directed: bool = False


class Graph:
    """Adjacency-list in-memory graph (parity: graph/graph/Graph.java).

    Supports directed and undirected edges, optional float edge weights
    (used by WeightedRandomWalkIterator), vertex values.
    """

    def __init__(self, n_vertices: int, *, allow_multiple_edges: bool = True,
                 vertices: Optional[Sequence[Any]] = None):
        if n_vertices <= 0:
            raise ValueError("n_vertices must be positive")
        self._n = n_vertices
        self._adj: List[List[int]] = [[] for _ in range(n_vertices)]
        self._weights: List[List[float]] = [[] for _ in range(n_vertices)]
        self._allow_multi = allow_multiple_edges
        self._vertices = [Vertex(i, vertices[i] if vertices else None)
                          for i in range(n_vertices)]
        self._padded_cache = None

    # -- structure ---------------------------------------------------------
    def num_vertices(self) -> int:
        return self._n

    def get_vertex(self, idx: int) -> Vertex:
        return self._vertices[idx]

    def add_edge(self, src: int, dst: int, *, weight: float = 1.0,
                 directed: bool = False, value: Any = None) -> None:
        if not (0 <= src < self._n and 0 <= dst < self._n):
            raise IndexError(f"edge ({src},{dst}) out of range [0,{self._n})")
        if not self._allow_multi and dst in self._adj[src]:
            return
        self._padded_cache = None
        self._adj[src].append(dst)
        self._weights[src].append(float(weight))
        if not directed and src != dst:
            self._adj[dst].append(src)
            self._weights[dst].append(float(weight))

    def add_edges(self, edges: Iterable[Edge]) -> None:
        for e in edges:
            w = e.value if isinstance(e.value, (int, float)) else 1.0
            self.add_edge(e.src, e.dst, weight=w, directed=e.directed,
                          value=e.value)

    def get_vertex_degree(self, idx: int) -> int:
        return len(self._adj[idx])

    def degrees(self) -> np.ndarray:
        return np.array([len(a) for a in self._adj], dtype=np.int64)

    def neighbors(self, idx: int) -> List[int]:
        return list(self._adj[idx])

    def neighbor_weights(self, idx: int) -> List[float]:
        return list(self._weights[idx])

    # -- sampling ----------------------------------------------------------
    def random_neighbor(self, idx: int, rng: np.random.Generator,
                        mode: NoEdgeHandling =
                        NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED) -> int:
        nbrs = self._adj[idx]
        if not nbrs:
            if mode is NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED:
                return idx
            raise NoEdgesException(f"vertex {idx} has no edges")
        return nbrs[int(rng.integers(len(nbrs)))]

    # -- padded device view ------------------------------------------------
    def padded_adjacency(self):
        """(adj, weights, degree) dense padded arrays for vectorized walk
        generation: adj[v, k] = k-th neighbour of v (self-padded), weights
        normalized per row. Shapes (V, max_deg). Cached; invalidated by
        add_edge."""
        if self._padded_cache is not None:
            return self._padded_cache
        deg = self.degrees()
        max_deg = max(int(deg.max()), 1)
        adj = np.tile(np.arange(self._n, dtype=np.int32)[:, None], (1, max_deg))
        w = np.zeros((self._n, max_deg), np.float32)
        for v in range(self._n):
            k = len(self._adj[v])
            if k:
                adj[v, :k] = self._adj[v]
                w[v, :k] = self._weights[v]
                w[v] /= w[v, :k].sum()
            else:
                w[v, 0] = 1.0  # self loop
        self._padded_cache = (adj, w, deg)
        return self._padded_cache


def load_edge_list(path: str, n_vertices: int, *, delimiter: str = ",",
                   directed: bool = False, weighted: bool = False) -> Graph:
    """Build a Graph from a delimited edge-list file — lines of
    ``src,dst[,weight]`` (parity: data/GraphLoader.java
    loadUndirectedGraphEdgeListFile / WeightedEdgeLineProcessor.java).
    Lines starting with '#' or '//' are comments."""
    g = Graph(n_vertices)
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or line.startswith("//"):
                continue
            parts = line.split(delimiter)
            src, dst = int(parts[0]), int(parts[1])
            w = float(parts[2]) if (weighted and len(parts) > 2) else 1.0
            g.add_edge(src, dst, weight=w, directed=directed)
    return g
