"""Graph API + embeddings (parity: reference deeplearning4j-graph/).

In-memory graph structures, random-walk iterators and DeepWalk graph
vectorization (hierarchical softmax over a degree-weighted Huffman tree),
re-designed TPU-first: walks are generated vectorized on host, embedding
updates run as one jit'd batched gather/scatter step on device.
"""

from deeplearning4j_tpu.graph.api import (Vertex, Edge, Graph,
                                          NoEdgeHandling, NoEdgesException)
from deeplearning4j_tpu.graph.walks import (RandomWalkIterator,
                                            WeightedRandomWalkIterator,
                                            RandomWalkGraphIteratorProvider)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk, GraphHuffman

__all__ = [
    "Vertex", "Edge", "Graph", "NoEdgeHandling", "NoEdgesException",
    "RandomWalkIterator", "WeightedRandomWalkIterator",
    "RandomWalkGraphIteratorProvider", "DeepWalk", "GraphHuffman",
]
