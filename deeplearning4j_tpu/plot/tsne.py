"""t-SNE embedding.

Parity surface: reference deeplearning4j-core plot/Tsne.java +
plot/BarnesHutTsne.java (868 LoC, SpTree-based O(N log N) repulsion).

TPU design: the exact O(N²) formulation is a handful of GEMMs/softmax-style
ops that the MXU eats — for the dataset sizes the reference's t-SNE is used
on (embedding viz, ≤50k points) the dense device path beats host-side
Barnes-Hut. ``BarnesHutTsne`` (theta>0) keeps the reference's approximate
algorithm on host via SpTree for API parity and for very large N.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp


def _pairwise_sq_dists(x):
    n2 = (x ** 2).sum(1)
    d = n2[:, None] + n2[None, :] - 2.0 * (x @ x.T)
    return jnp.maximum(d, 0.0)


@jax.jit
def _perplexity_probs(d2, log_perp):
    """Binary-search per-row precision beta so row entropy = log(perplexity).
    Vectorized over rows; 50 bisection steps."""
    n = d2.shape[0]
    inf_diag = jnp.eye(n) * 1e12

    def row_probs(beta):
        p = jnp.exp(-(d2 + inf_diag) * beta[:, None])
        psum = p.sum(1, keepdims=True)
        return p / jnp.maximum(psum, 1e-30)

    def entropy(beta):
        p = row_probs(beta)
        return -(p * jnp.log(jnp.maximum(p, 1e-30))).sum(1)

    def body(_, carry):
        lo, hi, beta = carry
        h = entropy(beta)
        too_high = h > log_perp  # entropy too high → increase beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), beta * 2,
                         jnp.where(jnp.isinf(lo), beta / 2, (lo + hi) / 2))
        return lo, hi, beta

    lo = jnp.full((n,), -jnp.inf)
    hi = jnp.full((n,), jnp.inf)
    beta = jnp.ones((n,))
    _, _, beta = jax.lax.fori_loop(0, 50, body, (lo, hi, beta))
    return row_probs(beta)


@jax.jit
def _tsne_grad(y, P):
    d2 = _pairwise_sq_dists(y)
    n = y.shape[0]
    q_num = 1.0 / (1.0 + d2)
    q_num = q_num * (1.0 - jnp.eye(n))
    Q = q_num / jnp.maximum(q_num.sum(), 1e-30)
    PQ = (P - jnp.maximum(Q, 1e-30)) * q_num
    grad = 4.0 * ((jnp.diag(PQ.sum(1)) - PQ) @ y)
    kl = (P * jnp.log(jnp.maximum(P, 1e-30) / jnp.maximum(Q, 1e-30))).sum()
    return grad, kl


class Tsne:
    """Exact t-SNE on device (parity: plot/Tsne.java API)."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 max_iter: int = 500, learning_rate: float = 200.0,
                 momentum: float = 0.8, early_exaggeration: float = 12.0,
                 exaggeration_iters: int = 100, seed: int = 123):
        self.n_components = n_components
        self.perplexity = perplexity
        self.max_iter = max_iter
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.early_exaggeration = early_exaggeration
        self.exaggeration_iters = exaggeration_iters
        self.seed = seed
        self.embedding: Optional[np.ndarray] = None
        self.kl: float = float("nan")

    def _p_matrix(self, x):
        d2 = _pairwise_sq_dists(jnp.asarray(x, jnp.float32))
        P = _perplexity_probs(d2, jnp.log(self.perplexity))
        P = (P + P.T) / (2.0 * P.shape[0])
        return jnp.maximum(P, 1e-12)

    def fit(self, x):
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        P = self._p_matrix(x)
        rng = np.random.RandomState(self.seed)
        y = jnp.asarray(rng.randn(n, self.n_components).astype(np.float32)
                        * 1e-2)
        vel = jnp.zeros_like(y)
        for it in range(self.max_iter):
            Pc = P * self.early_exaggeration if it < self.exaggeration_iters else P
            grad, kl = _tsne_grad(y, Pc)
            mom = 0.5 if it < 20 else self.momentum
            vel = mom * vel - self.learning_rate * grad
            y = y + vel
            y = y - y.mean(0)
        self.embedding = np.asarray(y)
        self.kl = float(kl)
        return self.embedding

    fit_transform = fit

    def plot(self, x=None):
        if self.embedding is None and x is not None:
            self.fit(x)
        return self.embedding


class BarnesHutTsne(Tsne):
    """Barnes-Hut approximate t-SNE (parity: plot/BarnesHutTsne.java).
    theta=0 falls back to the exact device path."""

    def __init__(self, theta: float = 0.5, **kwargs):
        kwargs.setdefault("max_iter", 300)
        super().__init__(**kwargs)
        self.theta = theta

    def fit(self, x):
        if self.theta <= 0:
            return super().fit(x)
        from deeplearning4j_tpu.clustering.trees import SpTree
        from deeplearning4j_tpu.clustering.knn import NearestNeighbors

        x = np.asarray(x, np.float32)
        n = x.shape[0]
        k = min(n - 1, int(3 * self.perplexity))
        nn = NearestNeighbors(x)
        idx, _ = nn.knn(x, k + 1)
        # sparse P from kNN graph (device perplexity solve on the kNN dists)
        d2_full = np.asarray(_pairwise_sq_dists(jnp.asarray(x)))
        P = np.zeros((n, n), np.float64)
        Pcond = np.asarray(_perplexity_probs(jnp.asarray(d2_full),
                                             jnp.log(self.perplexity)))
        mask = np.zeros((n, n), bool)
        for i in range(n):
            mask[i, idx[i, 1:]] = True
        Pcond = Pcond * mask
        P = (Pcond + Pcond.T)
        P /= max(P.sum(), 1e-12)
        P = np.maximum(P, 1e-12)

        rng = np.random.RandomState(self.seed)
        y = rng.randn(n, self.n_components) * 1e-2
        vel = np.zeros_like(y)
        rows, cols = P.nonzero()
        pvals = P[rows, cols]
        for it in range(self.max_iter):
            ex = self.early_exaggeration if it < self.exaggeration_iters else 1.0
            tree = SpTree(y)
            # attractive forces over sparse edges
            diff = y[rows] - y[cols]
            q = 1.0 / (1.0 + (diff ** 2).sum(1))
            att = np.zeros_like(y)
            w = (ex * pvals * q)[:, None] * diff
            np.add.at(att, rows, w)
            # repulsive via Barnes-Hut
            rep = np.zeros_like(y)
            sum_q = 0.0
            for i in range(n):
                neg, sq = tree.compute_non_edge_forces(y[i], self.theta)
                rep[i] = neg
                sum_q += sq
            grad = 4.0 * (att - rep / max(sum_q, 1e-12))
            mom = 0.5 if it < 20 else self.momentum
            vel = mom * vel - self.learning_rate * grad
            y = y + vel
            y = y - y.mean(0)
        self.embedding = y.astype(np.float32)
        return self.embedding
