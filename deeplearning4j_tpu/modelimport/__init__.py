"""Keras HDF5 model import (parity: reference deeplearning4j-modelimport/).

Imports Keras 1.x / 2.x models saved with ``model.save()`` (config + weights
in one HDF5) or config-JSON + weights-HDF5 pairs, into
:class:`MultiLayerNetwork` (Sequential) or :class:`ComputationGraph`
(functional Model).
"""

from deeplearning4j_tpu.modelimport.keras_import import (
    KerasModelImport,
    import_keras_sequential_model_and_weights,
    import_keras_model_and_weights,
    InvalidKerasConfigurationException,
    UnsupportedKerasConfigurationException,
)

__all__ = [
    "KerasModelImport",
    "import_keras_sequential_model_and_weights",
    "import_keras_model_and_weights",
    "InvalidKerasConfigurationException",
    "UnsupportedKerasConfigurationException",
]
