"""Keras 1.x/2.x HDF5 → deeplearning4j_tpu import.

Parity surface: reference deeplearning4j-modelimport/.../keras/
KerasModelImport.java:41 (importKerasSequentialModelAndWeights /
importKerasModelAndWeights), KerasModel.java + KerasSequentialModel.java
(config parsing, layer graph), layers/** (30+ per-layer translators),
Hdf5Archive.java (here: h5py instead of the JavaCPP HDF5 binding),
preprocessors/TensorFlowCnnToFeedForwardPreProcessor.java (dim-ordering
fixes — here the framework is NHWC-native so TF-format models import with
zero transposition; Theano-format kernels/flatten orderings are permuted).

Weight-layout notes (why this is near-zero-cost on TPU):
- Keras TF-format conv kernels are (kh, kw, in, out) == our HWIO — direct.
- Keras Dense kernels are (in, out) == ours — direct.
- Keras LSTM gate order is [i, f, c, o]; our fused (in, 4H) layout is
  [i, f, o, g] — columns are permuted once at import.
- Theano-format (channels_first) conv kernels (out, in, kh, kw) are
  transposed to HWIO; a Dense directly after Flatten gets its rows permuted
  from (c,h,w) to (h,w,c) flattening order.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, BatchNormalization, Convolution1DLayer, ConvolutionLayer,
    Cropping2D, DenseLayer, DepthwiseConvolution2D, DropoutLayer,
    EmbeddingSequenceLayer, FlattenLayer, GlobalPoolingLayer, LastTimeStep,
    LocalResponseNormalization, LSTM, OutputLayer, ReshapeLayer,
    SeparableConvolution2D, SimpleRnn, Subsampling1DLayer, SubsamplingLayer,
    Upsampling1D, Upsampling2D, ZeroPadding1DLayer, ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.conf.graph_conf import (ElementWiseVertex,
                                                   MergeVertex)
from deeplearning4j_tpu.models.multi_layer_network import MultiLayerNetwork
from deeplearning4j_tpu.models.computation_graph import ComputationGraph


class InvalidKerasConfigurationException(ValueError):
    """Parity: keras/exceptions/InvalidKerasConfigurationException.java."""


class UnsupportedKerasConfigurationException(ValueError):
    """Parity: keras/exceptions/UnsupportedKerasConfigurationException.java."""


# ---------------------------------------------------------------------------
# name maps
# ---------------------------------------------------------------------------

_ACTIVATIONS = {
    "relu": "relu", "softmax": "softmax", "sigmoid": "sigmoid",
    "tanh": "tanh", "linear": "identity", "elu": "elu", "selu": "selu",
    "softplus": "softplus", "softsign": "softsign",
    "hard_sigmoid": "hardsigmoid", "relu6": "relu6", "swish": "swish",
    "gelu": "gelu",
}

_LOSSES = {
    "categorical_crossentropy": "mcxent",
    "sparse_categorical_crossentropy": "mcxent",
    "binary_crossentropy": "xent",
    "mean_squared_error": "mse", "mse": "mse",
    "mean_absolute_error": "mae", "mae": "mae",
    "mean_absolute_percentage_error": "mape",
    "mean_squared_logarithmic_error": "msle",
    "hinge": "hinge", "squared_hinge": "squaredhinge",
    "kullback_leibler_divergence": "kldivergence",
    "poisson": "poisson", "cosine_proximity": "cosineproximity",
}


def _map_activation(name: str) -> str:
    if name not in _ACTIVATIONS:
        raise UnsupportedKerasConfigurationException(
            f"Unsupported Keras activation '{name}'")
    return _ACTIVATIONS[name]


def _map_optimizer(training_cfg: Optional[Dict]):
    """Keras optimizer_config → our Updater (parity: KerasModel training
    config import). Returns None when absent/unknown-safe."""
    from deeplearning4j_tpu.nn import updaters as U
    if not training_cfg:
        return None
    oc = training_cfg.get("optimizer_config")
    if not oc:
        return None
    cls = str(oc.get("class_name", "")).lower()
    cfg = oc.get("config", {})
    lr = float(cfg.get("learning_rate", cfg.get("lr", 0.001)))
    if cls == "adam":
        return U.Adam(lr, beta1=float(cfg.get("beta_1", 0.9)),
                      beta2=float(cfg.get("beta_2", 0.999)))
    if cls == "sgd":
        mom = float(cfg.get("momentum", 0.0))
        return U.Nesterovs(lr, momentum=mom) if mom else U.Sgd(lr)
    if cls == "rmsprop":
        return U.RmsProp(lr, rms_decay=float(cfg.get("rho", 0.9)))
    if cls == "adagrad":
        return U.AdaGrad(lr)
    if cls == "adadelta":
        return U.AdaDelta(rho=float(cfg.get("rho", 0.95)))
    if cls == "adamax":
        return U.AdaMax(lr)
    if cls == "nadam":
        return U.NAdam(lr)
    return None


def _map_loss(loss) -> str:
    """Map a Keras training-config loss — string, list, or {output: loss}
    dict (multi-output compiles) — to our loss name."""
    if isinstance(loss, dict):
        loss = next(iter(loss.values()))
    if isinstance(loss, (list, tuple)):
        loss = loss[0]
    key = str(loss).lower()
    if key not in _LOSSES:
        raise UnsupportedKerasConfigurationException(
            f"Unsupported Keras loss '{loss}'")
    return _LOSSES[key]


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1] if len(v) > 1 else v[0]))
    return (int(v), int(v))


def _single(v) -> int:
    """Scalar-or-singleton-list 1-D hyperparameter (Keras stores Conv1D
    kernel_size as [k])."""
    return int(v[0] if isinstance(v, (list, tuple)) else v)


# ---------------------------------------------------------------------------
# per-layer translators (parity: keras/layers/** KerasDense, KerasConvolution…)
# ---------------------------------------------------------------------------

def _conv_mode(cfg: Dict) -> str:
    border = cfg.get("padding", cfg.get("border_mode", "valid"))
    if border == "same":
        return "same"
    if border == "valid":
        return "truncate"
    raise UnsupportedKerasConfigurationException(
        f"Unsupported Keras padding mode '{border}'")


def _keras1_kernel(cfg: Dict) -> Tuple[int, int]:
    return (int(cfg["nb_row"]), int(cfg["nb_col"]))


def _translate_layer(class_name: str, cfg: Dict, keras_major: int):
    """One Keras layer config → (our Layer | 'flatten' | None-to-skip)."""
    act = cfg.get("activation")
    act = _map_activation(act) if act else None

    if class_name in ("Dense", "TimeDistributedDense"):
        units = int(cfg.get("units", cfg.get("output_dim", 0)))
        return DenseLayer(n_out=units, activation=act or "identity",
                          has_bias=bool(cfg.get("use_bias", cfg.get("bias", True))))
    if class_name == "Activation":
        return ActivationLayer(activation=act or "identity")
    if class_name in ("Dropout", "SpatialDropout2D", "SpatialDropout1D"):
        return DropoutLayer(dropout=float(cfg.get("rate", cfg.get("p", 0.0))))
    if class_name == "Flatten":
        return "flatten"
    if class_name == "Reshape":
        return ReshapeLayer(target_shape=tuple(cfg.get("target_shape", ())))
    if class_name in ("Permute", "RepeatVector", "Masking"):
        raise UnsupportedKerasConfigurationException(
            f"Keras layer '{class_name}' is not yet supported")
    if class_name in ("Conv2D", "Convolution2D", "AtrousConvolution2D"):
        # AtrousConvolution2D (Keras 1) is a dilated conv: atrous_rate maps
        # to dilation (parity: KerasAtrousConvolution2D.java)
        k = (_pair(cfg["kernel_size"]) if "kernel_size" in cfg
             else _keras1_kernel(cfg))
        dil = _pair(cfg.get("dilation_rate", cfg.get("atrous_rate", (1, 1))))
        return ConvolutionLayer(
            n_out=int(cfg.get("filters", cfg.get("nb_filter", 0))),
            kernel_size=k,
            stride=_pair(cfg.get("strides", cfg.get("subsample", (1, 1)))),
            dilation=dil,
            convolution_mode=_conv_mode(cfg),
            activation=act or "identity",
            has_bias=bool(cfg.get("use_bias", cfg.get("bias", True))))
    if class_name in ("Conv1D", "Convolution1D", "AtrousConvolution1D"):
        border = cfg.get("padding", cfg.get("border_mode", "valid"))
        if border == "causal":
            raise UnsupportedKerasConfigurationException(
                "Keras Conv1D causal padding is not supported")
        return Convolution1DLayer(
            n_out=int(cfg.get("filters", cfg.get("nb_filter", 0))),
            kernel_size=_single(cfg.get("kernel_size",
                                        cfg.get("filter_length", 3))),
            stride=_single(cfg.get("strides", cfg.get("subsample_length", 1))),
            dilation=_single(cfg.get("dilation_rate",
                                     cfg.get("atrous_rate", 1))),
            convolution_mode=_conv_mode(cfg),
            activation=act or "identity",
            has_bias=bool(cfg.get("use_bias", cfg.get("bias", True))))
    if class_name in ("MaxPooling1D", "AveragePooling1D"):
        p = _single(cfg.get("pool_size", cfg.get("pool_length", 2)))
        s = _single(cfg.get("strides", cfg.get("stride")) or p)
        return Subsampling1DLayer(
            pooling_type="max" if class_name.startswith("Max") else "avg",
            kernel_size=p, stride=s, convolution_mode=_conv_mode(cfg),
            avg_count_includes_padding=False)   # Keras/TF edge semantics
    if class_name == "UpSampling1D":
        return Upsampling1D(size=_single(cfg.get("size",
                                                 cfg.get("length", 2))))
    if class_name == "ZeroPadding1D":
        p = cfg.get("padding", 1)
        pad = ((int(p[0]), int(p[1])) if isinstance(p, (list, tuple))
               else (int(p), int(p)))
        return ZeroPadding1DLayer(padding=pad)
    if class_name in ("LRN", "LRN2D", "LocalResponseNormalization"):
        # Keras-contrib / Keras 0.x LRN (parity: KerasLRN.java)
        return LocalResponseNormalization(
            k=float(cfg.get("k", 2.0)), alpha=float(cfg.get("alpha", 1e-4)),
            beta=float(cfg.get("beta", 0.75)), n=int(cfg.get("n", 5)))
    if class_name == "SeparableConv2D":
        return SeparableConvolution2D(
            n_out=int(cfg.get("filters", 0)),
            kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", (1, 1))),
            depth_multiplier=int(cfg.get("depth_multiplier", 1)),
            convolution_mode=_conv_mode(cfg),
            activation=act or "identity",
            has_bias=bool(cfg.get("use_bias", True)))
    if class_name == "DepthwiseConv2D":
        return DepthwiseConvolution2D(
            kernel_size=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", (1, 1))),
            depth_multiplier=int(cfg.get("depth_multiplier", 1)),
            convolution_mode=_conv_mode(cfg),
            activation=act or "identity",
            has_bias=bool(cfg.get("use_bias", True)))
    if class_name in ("MaxPooling2D", "AveragePooling2D"):
        return SubsamplingLayer(
            pooling_type="max" if class_name.startswith("Max") else "avg",
            kernel_size=_pair(cfg.get("pool_size", (2, 2))),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", (2, 2))),
            convolution_mode=_conv_mode(cfg),
            avg_count_includes_padding=False)   # Keras/TF edge semantics
    if class_name in ("GlobalMaxPooling2D", "GlobalAveragePooling2D",
                      "GlobalMaxPooling1D", "GlobalAveragePooling1D"):
        return GlobalPoolingLayer(
            pooling_type="max" if "Max" in class_name else "avg")
    if class_name == "BatchNormalization":
        return BatchNormalization(
            activation="identity",
            eps=float(cfg.get("epsilon", 1e-3)),
            decay=float(cfg.get("momentum", 0.99)))
    if class_name == "LSTM":
        units = int(cfg.get("units", cfg.get("output_dim", 0)))
        rnn = LSTM(n_out=units, activation=act or "tanh",
                   gate_activation=_map_activation(
                       cfg.get("recurrent_activation",
                               cfg.get("inner_activation", "sigmoid"))))
        if not cfg.get("return_sequences", False):
            return LastTimeStep(fwd=rnn)
        return rnn
    if class_name == "SimpleRNN":
        units = int(cfg.get("units", cfg.get("output_dim", 0)))
        rnn = SimpleRnn(n_out=units, activation=act or "tanh")
        if not cfg.get("return_sequences", False):
            return LastTimeStep(fwd=rnn)
        return rnn
    if class_name == "Embedding":
        return EmbeddingSequenceLayer(
            activation="identity",
            n_in=int(cfg.get("input_dim", 0)),
            n_out=int(cfg.get("output_dim", 0)))
    if class_name == "ZeroPadding2D":
        p = cfg.get("padding", (1, 1))
        if isinstance(p, (list, tuple)) and p and isinstance(p[0], (list, tuple)):
            pad = (int(p[0][0]), int(p[0][1]), int(p[1][0]), int(p[1][1]))
        else:
            ph, pw = _pair(p)
            pad = (ph, ph, pw, pw)
        return ZeroPaddingLayer(padding=pad)
    if class_name == "UpSampling2D":
        return Upsampling2D(size=_pair(cfg.get("size", (2, 2))))
    if class_name == "Cropping2D":
        c = cfg.get("cropping", (0, 0))
        if isinstance(c, (list, tuple)) and c and isinstance(c[0], (list, tuple)):
            crop = (int(c[0][0]), int(c[0][1]), int(c[1][0]), int(c[1][1]))
        else:
            ch, cw = _pair(c)
            crop = (ch, ch, cw, cw)
        return Cropping2D(cropping=crop)
    if class_name == "LeakyReLU":
        return ActivationLayer(activation="leakyrelu")
    if class_name == "InputLayer":
        return None
    raise UnsupportedKerasConfigurationException(
        f"Unsupported Keras layer type '{class_name}'")


def _check_reshape(t, channels_first: bool):
    """A 3-D Reshape target in a channels_first model means (C, H, W) over
    NCHW data; our NHWC runtime cannot honor it with a plain reshape —
    refuse loudly instead of producing silently scrambled activations."""
    if isinstance(t, ReshapeLayer) and channels_first \
            and len(t.target_shape) == 3:
        raise UnsupportedKerasConfigurationException(
            "Reshape to a 3-D target in a channels_first model is not "
            "supported (NHWC runtime would scramble the layout)")
    return t


def _input_type_from_shape(shape, data_format: str) -> InputType:
    """batch_input_shape (excluding batch dim) → InputType. Rank decides the
    kind; ``None`` dims stay as wildcards (variable timesteps / image size),
    they are NOT dropped — [None, 5] is recurrent(5), not feed_forward(5)."""
    dims = list(shape)
    if len(dims) == 3:
        if data_format == "channels_first":
            c, h, w = dims
        else:
            h, w, c = dims
        if c is None:
            raise UnsupportedKerasConfigurationException(
                f"Convolutional input with unknown channel count: {shape}")
        return InputType.convolutional(int(h) if h else -1,
                                       int(w) if w else -1, int(c))
    if len(dims) == 2:
        if dims[1] is None:
            raise UnsupportedKerasConfigurationException(
                f"Recurrent input with unknown feature size: {shape}")
        return InputType.recurrent(int(dims[1]),
                                   int(dims[0]) if dims[0] else -1)
    if len(dims) == 1:
        if dims[0] is None:
            raise UnsupportedKerasConfigurationException(
                f"Cannot infer input width from {shape}")
        return InputType.feed_forward(int(dims[0]))
    raise UnsupportedKerasConfigurationException(
        f"Cannot infer input type from shape {shape}")


# ---------------------------------------------------------------------------
# weight translation
# ---------------------------------------------------------------------------

def _lstm_reorder(k: np.ndarray, H: int) -> np.ndarray:
    """Keras gate order [i,f,c,o] → our [i,f,o,g] along the last axis."""
    i, f, c, o = (k[..., 0:H], k[..., H:2 * H], k[..., 2 * H:3 * H],
                  k[..., 3 * H:4 * H])
    return np.concatenate([i, f, o, c], axis=-1)


def _theano_conv_kernel(k: np.ndarray) -> np.ndarray:
    """(out, in, kh, kw) → (kh, kw, in, out), with the 180° kernel flip
    Theano's conv (true convolution) implies vs TF's cross-correlation
    (parity: KerasConvolution weight processing)."""
    k = k[:, :, ::-1, ::-1]
    return np.transpose(k, (2, 3, 1, 0))


def _set_layer_weights(layer, params: Dict, weights: List[np.ndarray],
                       theano_kernels: bool,
                       flatten_permute: Optional[Tuple[int, int, int]]):
    """Write Keras weight arrays into our param dict for one layer.
    ``theano_kernels``: conv kernels stored (out, in, kh, kw) with flipped
    taps (Keras 1 on the Theano backend) — decided from the file's backend
    metadata, never from shape heuristics.
    ``flatten_permute`` = (h, w, c) of the conv output feeding a Dense via
    Flatten under channels_first — rows need (c,h,w)→(h,w,c) reordering."""
    if isinstance(layer, LastTimeStep):
        layer = layer.fwd
    dtype = None
    for v in params.values():
        dtype = v.dtype
        break

    def put(key, arr):
        if key not in params:
            raise InvalidKerasConfigurationException(
                f"Layer {layer.__class__.__name__} has no param '{key}'")
        if tuple(params[key].shape) != tuple(arr.shape):
            raise InvalidKerasConfigurationException(
                f"Shape mismatch for {layer.__class__.__name__}.{key}: "
                f"model {tuple(params[key].shape)} vs h5 {tuple(arr.shape)}")
        params[key] = jnp.asarray(arr, dtype)

    name = layer.__class__.__name__
    if isinstance(layer, SeparableConvolution2D):
        put("dW", weights[0])
        put("pW", weights[1])
        if layer.has_bias and len(weights) > 2:
            put("b", weights[2])
    elif isinstance(layer, DepthwiseConvolution2D):
        dk = weights[0]  # keras: (kh, kw, in, mult) — ours: (kh, kw, in, mult)
        put("dW", dk) if "dW" in params else put("W", dk)
        if layer.has_bias and len(weights) > 1:
            put("b", weights[1])
    elif isinstance(layer, Convolution1DLayer):
        k = weights[0]
        if k.ndim == 4:          # keras1 stores (filter_length, 1, in, out)
            k = k[:, 0, :, :]
        put("W", k)
        if layer.has_bias and len(weights) > 1:
            put("b", weights[1])
    elif isinstance(layer, ConvolutionLayer) and not isinstance(
            layer, (SeparableConvolution2D, DepthwiseConvolution2D)):
        k = weights[0]
        if theano_kernels and k.ndim == 4:
            k = _theano_conv_kernel(k)
        put("W", k)
        if layer.has_bias and len(weights) > 1:
            put("b", weights[1])
    elif isinstance(layer, (DenseLayer, OutputLayer)):
        W = weights[0]
        if flatten_permute is not None:
            h, w, c = flatten_permute
            # rows currently ordered (c,h,w); reorder to our (h,w,c)
            W = (W.reshape(c, h, w, -1).transpose(1, 2, 0, 3)
                 .reshape(h * w * c, -1))
        put("W", W)
        if len(weights) > 1:
            put("b", weights[1])
    elif isinstance(layer, LSTM):
        H = layer.n_out
        if len(weights) == 3:        # keras2: kernel, recurrent, bias
            put("W", _lstm_reorder(weights[0], H))
            put("RW", _lstm_reorder(weights[1], H))
            put("b", _lstm_reorder(weights[2].reshape(-1), H))
        elif len(weights) == 12:     # keras1: per-gate W_i,U_i,b_i × [i,c,f,o]
            Wi, Ui, bi, Wc, Uc, bc, Wf, Uf, bf, Wo, Uo, bo = weights
            put("W", np.concatenate([Wi, Wf, Wo, Wc], axis=1))
            put("RW", np.concatenate([Ui, Uf, Uo, Uc], axis=1))
            put("b", np.concatenate([bi, bf, bo, bc]))
        else:
            raise UnsupportedKerasConfigurationException(
                f"Unexpected LSTM weight count {len(weights)}")
    elif isinstance(layer, SimpleRnn):
        put("W", weights[0])
        put("RW", weights[1])
        if len(weights) > 2:
            put("b", weights[2])
    elif isinstance(layer, BatchNormalization):
        # keras order: gamma, beta, moving_mean, moving_variance
        put("gamma", weights[0])
        put("beta", weights[1])
        return {"mean": jnp.asarray(weights[2], dtype),
                "var": jnp.asarray(weights[3], dtype)}
    elif isinstance(layer, EmbeddingSequenceLayer):
        put("W", weights[0])
    elif weights:
        raise UnsupportedKerasConfigurationException(
            f"Don't know how to load weights into {name}")
    return None


# ---------------------------------------------------------------------------
# HDF5 reading (parity: Hdf5Archive.java)
# ---------------------------------------------------------------------------

def _h5_str(v) -> str:
    return v.decode("utf-8") if isinstance(v, bytes) else str(v)


def _read_configs(h5):
    model_config = h5.attrs.get("model_config")
    if model_config is None:
        raise InvalidKerasConfigurationException(
            "HDF5 file has no 'model_config' attribute (weights-only file? "
            "pass the config JSON separately)")
    training_config = h5.attrs.get("training_config")
    return (json.loads(_h5_str(model_config)),
            json.loads(_h5_str(training_config)) if training_config is not None
            else None)


def _weights_group(h5):
    return h5["model_weights"] if "model_weights" in h5 else h5


def _layer_weights(wg, layer_name: str) -> List[np.ndarray]:
    if layer_name not in wg:
        return []
    g = wg[layer_name]
    names = g.attrs.get("weight_names")
    if names is None:
        return []
    out = []
    for n in names:
        n = _h5_str(n)
        node = g[n] if n in g else wg[n]
        out.append(np.asarray(node))
    return out


# ---------------------------------------------------------------------------
# model assembly
# ---------------------------------------------------------------------------

def _iter_seq_layers(model_cfg: Dict):
    """Yield (class_name, config, name) for a Sequential model, Keras 1&2."""
    cfg = model_cfg["config"]
    layer_list = cfg["layers"] if isinstance(cfg, dict) else cfg
    for ld in layer_list:
        lcfg = ld.get("config", {})
        yield ld["class_name"], lcfg, lcfg.get("name", ld.get("name"))


def import_keras_sequential_model_and_weights(
        model_h5_path: str, *, enforce_training_config: bool = False,
        config_json: Optional[str] = None,
        input_type: Optional[InputType] = None) -> MultiLayerNetwork:
    """Keras Sequential → MultiLayerNetwork (parity:
    KerasModelImport.importKerasSequentialModelAndWeights)."""
    import h5py
    with h5py.File(model_h5_path, "r") as h5:
        if config_json is not None:
            model_cfg = json.loads(config_json)
            training_cfg = None
        else:
            model_cfg, training_cfg = _read_configs(h5)
        if model_cfg["class_name"] != "Sequential":
            raise InvalidKerasConfigurationException(
                f"Not a Sequential model: {model_cfg['class_name']}")
        loss_name = None
        if training_cfg and training_cfg.get("loss"):
            loss_name = _map_loss(training_cfg["loss"])
        elif enforce_training_config:
            raise InvalidKerasConfigurationException(
                "enforce_training_config=True but model has no training config")

        entries = list(_iter_seq_layers(model_cfg))
        data_format = "channels_last"
        for _, lcfg, _ in entries:
            if lcfg.get("data_format") or lcfg.get("dim_ordering"):
                df = lcfg.get("data_format") or lcfg.get("dim_ordering")
                data_format = ("channels_first" if df in ("channels_first", "th")
                               else "channels_last")
                break
        channels_first = data_format == "channels_first"
        backend = _h5_str(model_cfg.get("backend", "") or "")
        theano_kernels = channels_first and backend != "tensorflow"

        if input_type is None:
            # Keras 1/2: batch_input_shape on the first real layer;
            # Keras 3 legacy h5: batch_shape on an explicit InputLayer
            shape = None
            for _, lcfg, _ in entries[:2]:
                shape = (lcfg.get("batch_input_shape")
                         or lcfg.get("batch_shape"))
                if shape is not None:
                    break
            if shape is None:
                raise InvalidKerasConfigurationException(
                    "First layer has no batch_input_shape; pass input_type=")
            input_type = _input_type_from_shape(shape[1:], data_format)

        # translate layers
        ours: List[Tuple[Any, str]] = []   # (layer, keras_name)
        flatten_pending = False
        flatten_after: Dict[int, bool] = {}
        for class_name, lcfg, name in entries:
            t = _check_reshape(_translate_layer(class_name, lcfg, 2),
                               channels_first)
            if t == "flatten":
                # a real layer: our Dense is time-distributed over (B, T, C)
                # sequence inputs, so Keras's flatten must actually flatten
                ours.append((FlattenLayer(), name))
                flatten_pending = True
                continue
            if t is None:
                continue
            if flatten_pending:
                flatten_after[len(ours)] = True
                flatten_pending = False
            ours.append((t, name))

        # last layer + loss → OutputLayer (parity: KerasLoss handling)
        if loss_name is not None and isinstance(ours[-1][0], DenseLayer) \
                and not isinstance(ours[-1][0], OutputLayer):
            d = ours[-1][0]
            ours[-1] = (OutputLayer(n_out=d.n_out, activation=d.activation,
                                    loss=loss_name, has_bias=d.has_bias),
                        ours[-1][1])

        bb = NeuralNetConfiguration.builder()
        upd = _map_optimizer(training_cfg)
        if upd is not None:
            bb.updater(upd)
        b = bb.list()
        for l, _ in ours:
            b.layer(l)
        conf = b.set_input_type(input_type).build()
        net = MultiLayerNetwork(conf).init()

        # load weights
        wg = _weights_group(h5)
        out_types = [input_type] + conf.output_types()
        for idx, (l, kname) in enumerate(ours):
            w = _layer_weights(wg, kname)
            if not w:
                continue
            fp = None
            if channels_first and flatten_after.get(idx):
                it = out_types[idx - 1]      # input of the FlattenLayer
                if it.kind == "cnn":
                    fp = (it.height, it.width, it.channels)
            new_state = _set_layer_weights(net.layers[idx], net.params[idx], w,
                                           theano_kernels, fp)
            if new_state:
                net.state[idx].update(new_state)
    return net


def import_keras_model_and_weights(
        model_h5_path: str, *,
        input_type: Optional[InputType] = None) -> ComputationGraph:
    """Keras functional Model → ComputationGraph (parity:
    KerasModelImport.importKerasModelAndWeights). Supports layer nodes plus
    Add/Subtract/Multiply/Average/Maximum/Concatenate merge layers."""
    import h5py
    from deeplearning4j_tpu.nn.conf.graph_conf import GraphBuilder
    from deeplearning4j_tpu.nn.conf.configuration import GlobalConf

    with h5py.File(model_h5_path, "r") as h5:
        model_cfg, training_cfg = _read_configs(h5)
        if model_cfg["class_name"] not in ("Model", "Functional"):
            raise InvalidKerasConfigurationException(
                f"Not a functional model: {model_cfg['class_name']}")
        cfg = model_cfg["config"]
        layers = cfg["layers"]
        loss_name = None
        if training_cfg and training_cfg.get("loss"):
            loss_name = _map_loss(training_cfg["loss"])

        data_format = "channels_last"
        for ld in layers:
            df = ld.get("config", {}).get("data_format")
            if df:
                data_format = df
                break
        channels_first = data_format == "channels_first"
        backend = _h5_str(model_cfg.get("backend", "") or "")
        theano_kernels = channels_first and backend != "tensorflow"

        upd = _map_optimizer(training_cfg)
        gc = GlobalConf(updater=upd) if upd is not None else GlobalConf()
        gb = GraphBuilder(gc)
        input_names = []
        in_types = []
        translated: Dict[str, Any] = {}
        flatten_nodes: set = set()          # names of Flatten pass-throughs
        node_inputs: Dict[str, List[str]] = {}

        def _names(spec) -> List[str]:
            # Keras 2: [["name", 0, 0], ...]; Keras 3 single output:
            # ["name", 0, 0]
            if spec and isinstance(spec[0], str):
                return [spec[0]]
            return [o[0] for o in spec]

        output_names = _names(cfg["output_layers"])

        def inbound(ld) -> List[str]:
            nodes = ld.get("inbound_nodes", [])
            if not nodes:
                return []
            first = nodes[0]
            if isinstance(first, dict):
                # Keras 3: {"args": [KerasTensor | [KerasTensor...]], ...};
                # source layer names live in each tensor's keras_history
                names: List[str] = []

                def walk(o):
                    if isinstance(o, dict):
                        if o.get("class_name") == "__keras_tensor__":
                            names.append(o["config"]["keras_history"][0])
                        else:
                            for v in o.values():
                                walk(v)
                    elif isinstance(o, (list, tuple)):
                        for v in o:
                            walk(v)

                walk(first.get("args", []))
                return names
            return [n[0] for n in first]

        for ld in layers:
            cls, lcfg = ld["class_name"], ld.get("config", {})
            name = lcfg.get("name", ld.get("name"))
            ins = inbound(ld)
            if cls == "InputLayer":
                input_names.append(name)
                shape = (lcfg.get("batch_input_shape")
                         or lcfg.get("batch_shape"))   # keras 3
                if shape is not None:
                    in_types.append(_input_type_from_shape(shape[1:],
                                                           data_format))
                continue
            node_inputs[name] = ins
            if cls in ("Add", "Subtract", "Multiply", "Average", "Maximum"):
                op = {"Add": "add", "Subtract": "subtract",
                      "Multiply": "product", "Average": "average",
                      "Maximum": "max"}[cls]
                gb.add_vertex(name, ElementWiseVertex(op=op), *ins)
                continue
            if cls == "Merge":             # Keras 1 merge with a mode config
                mode = lcfg.get("mode", "concat")
                ew = {"sum": "add", "mul": "product", "ave": "average",
                      "max": "max"}
                if mode in ew:
                    gb.add_vertex(name, ElementWiseVertex(op=ew[mode]), *ins)
                elif mode == "concat":
                    gb.add_vertex(name, MergeVertex(), *ins)
                else:
                    raise UnsupportedKerasConfigurationException(
                        f"Unsupported Keras1 Merge mode '{mode}'")
                continue
            if cls == "Concatenate":
                gb.add_vertex(name, MergeVertex(), *ins)
                continue
            t = _check_reshape(_translate_layer(cls, lcfg, 2), channels_first)
            if t == "flatten":
                flatten_nodes.add(name)
                gb.add_layer(name, FlattenLayer(), *ins)
                continue
            if loss_name is not None and name in output_names \
                    and isinstance(t, DenseLayer) \
                    and not isinstance(t, OutputLayer):
                t = OutputLayer(n_out=t.n_out, activation=t.activation,
                                loss=loss_name, has_bias=t.has_bias)
            gb.add_layer(name, t, *ins)
            translated[name] = t

        gb.add_inputs(*input_names)
        if input_type is not None:
            in_types = [input_type]
        if in_types:
            gb.set_input_types(*in_types)
        gb.set_outputs(*output_names)
        conf = gb.build()
        net = ComputationGraph(conf).init()

        wg = _weights_group(h5)
        node_types = getattr(conf, "node_output_types", {})
        for name, l in translated.items():
            w = _layer_weights(wg, name)
            if not w:
                continue
            fp = None
            ins = node_inputs.get(name, [])
            if channels_first and ins and ins[0] in flatten_nodes:
                # Dense fed by a Flatten of a conv map: permute rows
                # (c,h,w)→(h,w,c) exactly like the sequential path
                src = node_inputs.get(ins[0], [])
                it = node_types.get(src[0]) if src else None
                if it is not None and it.kind == "cnn":
                    fp = (it.height, it.width, it.channels)
            new_state = _set_layer_weights(l, net.params[name], w,
                                           theano_kernels, fp)
            if new_state:
                net.state[name].update(new_state)
    return net


class KerasModelImport:
    """Static facade (parity: KerasModelImport.java:41)."""

    importKerasSequentialModelAndWeights = staticmethod(
        import_keras_sequential_model_and_weights)
    importKerasModelAndWeights = staticmethod(import_keras_model_and_weights)

    @staticmethod
    def import_keras_model(path: str, **kw):
        """Sniff Sequential vs functional and import accordingly
        (parity: util/ModelGuesser-style dispatch)."""
        import h5py
        with h5py.File(path, "r") as h5:
            model_cfg, _ = _read_configs(h5)
        if model_cfg["class_name"] == "Sequential":
            return import_keras_sequential_model_and_weights(path, **kw)
        return import_keras_model_and_weights(path, **kw)
