"""Registry of compiled XLA programs with cost/memory introspection.

Every compile site in the system — the bucketed serving engine, the
continuous-batching decode engine, and both model containers — registers
the program it just traced here, keyed by ``(caller, key)`` (e.g.
``("engine0", "b32")`` or ``("mln0", "fit_scan_k64_b128")``). At
registration the program is re-lowered and AOT-compiled to read XLA's
own ``cost_analysis()`` (flops, bytes accessed) and
``memory_analysis()`` (device footprint); the persistent compile cache
(``util/compile_cache``) makes the second compile of an already-compiled
signature cheap.

What this buys:

- ``dl4jtpu_program_{flops,bytes,memory_bytes,compile_seconds}`` gauges
  labelled ``{caller,key}`` — MFU is now derivable from /metrics alone.
- ``GET /programs`` on the inference server: the live program table.
- ``bench.py`` MFU rows read flops from here instead of re-deriving them
  with a private lowering helper.

Re-lowering re-traces the python callable, which would double-count the
callers' compile accounting (``_note_compile`` / ``_m_compiled.inc()``
run inside traced bodies). Those sites consult :func:`is_registering`
and skip their increment while a registration lowering is in flight.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["ProgramRegistry", "get_programs", "is_registering"]


_REGISTERING = threading.local()


def is_registering() -> bool:
    """True while this thread is re-lowering a program for registration —
    compile-accounting side effects inside traced bodies must no-op."""
    return getattr(_REGISTERING, "on", False)


class _Registering:
    __slots__ = ()

    def __enter__(self):
        _REGISTERING.on = True
        return self

    def __exit__(self, *exc):
        _REGISTERING.on = False
        return False


def _lowerable(fn):
    """The object carrying ``.lower``: a plain ``jax.jit`` result, or one
    of the jitted entries inside a mesh ``Executor.jit`` wrapper."""
    if hasattr(fn, "lower"):
        return fn
    cache = getattr(fn, "_exec_cache", None)
    if cache:
        return next(iter(cache.values()))
    return None


def _analyze(jitted, args):
    """(flops, bytes_accessed, memory_bytes, aot_compile_seconds) via the
    AOT path; any missing analysis comes back None."""
    t0 = time.perf_counter()
    compiled = jitted.lower(*args).compile()
    aot_s = time.perf_counter() - t0
    flops = bytes_accessed = memory_bytes = None
    try:
        an = compiled.cost_analysis()
        if isinstance(an, (list, tuple)):
            an = an[0] if an else {}
        if an:
            f = an.get("flops")
            flops = float(f) if f is not None else None
            b = an.get("bytes accessed")
            bytes_accessed = float(b) if b is not None else None
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        total = 0.0
        found = False
        for attr in ("temp_size_in_bytes", "argument_size_in_bytes",
                     "output_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(mem, attr, None)
            if v is not None:
                total += float(v)
                found = True
        if found:
            memory_bytes = total
    except Exception:
        pass
    return flops, bytes_accessed, memory_bytes, aot_s


class ProgramRegistry:
    """Process-wide table of registered programs (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._programs = {}        # (caller, key) -> record dict
        self._gauges = None

    def _metric(self, record):
        if self._gauges is None:
            from deeplearning4j_tpu.monitor import get_registry
            reg = get_registry()
            self._gauges = {
                "flops": reg.gauge(
                    "dl4jtpu_program_flops",
                    "XLA cost_analysis flops of the registered program",
                    labelnames=("caller", "key")),
                "bytes": reg.gauge(
                    "dl4jtpu_program_bytes",
                    "XLA cost_analysis bytes accessed",
                    labelnames=("caller", "key")),
                "memory_bytes": reg.gauge(
                    "dl4jtpu_program_memory_bytes",
                    "XLA memory_analysis device footprint "
                    "(args + outputs + temps + code)",
                    labelnames=("caller", "key")),
                "compile_seconds": reg.gauge(
                    "dl4jtpu_program_compile_seconds",
                    "wall seconds of the compile-bearing call that "
                    "produced the program (AOT relower time if unmeasured)",
                    labelnames=("caller", "key")),
            }
        lbl = {"caller": record["caller"], "key": record["key"]}
        for field, fam in self._gauges.items():
            v = record.get(field)
            if v is not None:
                fam.labels(**lbl).set(v)

    def record(self, caller: str, key: str, fn, args,
               compile_seconds: Optional[float] = None) -> Optional[dict]:
        """Register program ``(caller, key)``; re-registration of a known
        key is a no-op (returns the existing record). Analysis failures
        degrade to a record with None fields rather than raising into
        the caller's hot path."""
        caller, key = str(caller), str(key)
        with self._lock:
            existing = self._programs.get((caller, key))
        if existing is not None:
            return existing
        jitted = _lowerable(fn)
        if jitted is None:
            return None
        flops = bytes_accessed = memory_bytes = None
        aot_s = None
        try:
            with _Registering():
                flops, bytes_accessed, memory_bytes, aot_s = _analyze(
                    jitted, args)
        except Exception:
            pass
        record = {
            "caller": caller,
            "key": key,
            "flops": flops,
            "bytes": bytes_accessed,
            "memory_bytes": memory_bytes,
            "compile_seconds": (compile_seconds if compile_seconds is not None
                                else aot_s),
        }
        with self._lock:
            # lost a race: keep the first registration
            existing = self._programs.setdefault((caller, key), record)
        if existing is record:
            try:
                self._metric(record)
            except Exception:
                pass
        return existing

    def get(self, caller: str, key: str) -> Optional[dict]:
        with self._lock:
            return self._programs.get((str(caller), str(key)))

    def last(self, caller: str) -> Optional[dict]:
        """Most recently registered program of ``caller``."""
        caller = str(caller)
        with self._lock:
            out = None
            for (c, _), rec in self._programs.items():
                if c == caller:
                    out = rec
            return out

    def entries(self) -> list:
        with self._lock:
            return [dict(rec) for rec in self._programs.values()]

    def clear(self) -> None:
        with self._lock:
            self._programs.clear()


_programs = ProgramRegistry()


def get_programs() -> ProgramRegistry:
    """The process-wide program registry (analog of
    ``monitor.get_registry()``)."""
    return _programs
