"""Elastic data-parallel training worker (one process, one cluster member).

``python -m deeplearning4j_tpu.exec.worker --coordinator URL --worker-id w0
--port-file /run/w0.port`` joins the ElasticCoordinator (exec/elastic.py),
builds the deterministic job model, and trains lockstep data-parallel
steps until ``total_steps``:

- **Deterministic shards.** Every worker materializes the SAME global
  batch from ``(seed, step)`` and takes its committed-rank slice
  (``parallel.distributed.local_batch_slice``) — re-sharding after an
  elastic reform is just a different slice of the same bytes.
- **Reduction.** Grad + loss ravel into one f32 vector, pre-scaled by the
  shard's row count; the coordinator sums contributions in rank order and
  divides by the total rows (``docs/ELASTIC_TRAINING.md``). With
  ``DL4JTPU_CLUSTER_BACKEND=jax`` (and a jaxlib whose backend actually
  ships cross-process collectives) the same vector goes through a real
  ``process_allgather`` and is summed in the same rank order — identical
  math, in-mesh transport. jaxlib CPU wheels ship no such collectives, so
  CI exercises the loopback-TCP path — which is the point: a REAL
  N-process cluster instead of a skip.
- **Elasticity.** A heartbeat thread renews the lease; any fenced RPC or
  rollback directive sends the worker to ``_resync``: restore the anchor
  checkpoint (bitwise, PR 4), ack the proposed generation, resume at the
  anchor step under the committed (rank, world). Replacements walk the
  same path from scratch — join, restore anchor, AOT-restore the train
  programs from the checkpoint's companion bundle, continue — which is
  why a killed-and-replaced run finishes bitwise-equal to an unkilled
  one.
- **Chaos.** ``resilience.faults.WorkerChaos`` (env
  ``DL4JTPU_WORKER_CHAOS``) injects per-step slowdowns and scripted
  self-SIGKILL for the soak tests.

Exit codes: 0 done, 3 evicted (a replacement took the seat), 4 cluster
full, 5 fatal config/setup error.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.exec.elastic import (ClusterFullError, EvictedError,
                                             FencedError)
from deeplearning4j_tpu.resilience.errors import TransientError
from deeplearning4j_tpu.resilience.retry import RetryPolicy, retry_call

__all__ = ["CoordClient", "ElasticWorker", "synth_batch", "params_digest",
           "main"]

# one bundle-validity envelope for the cluster's train programs (grad is
# shape-specialized per shard-row count, update is shape-stable)
_AOT_PRECISION = "cluster-f32"

_RPC_POLICY = RetryPolicy(max_attempts=6, base_delay=0.05, max_delay=1.0)
# the allreduce blocks server-side until the barrier fills; retries are
# idempotent (the coordinator caches reduced steps), so ride out stragglers
# with an overall deadline instead of an attempt cap
_REDUCE_POLICY = RetryPolicy(max_attempts=None, base_delay=0.1,
                             max_delay=1.0, deadline=240.0)


def synth_batch(model: str, seed: int, step: int, n: int):
    """The deterministic GLOBAL batch for ``step`` — a pure function of
    ``(model, seed, step)`` so every member (including a replacement that
    joined five generations later) slices identical bytes."""
    rng = np.random.default_rng([int(seed), int(step), 0xE1A])
    if model == "mlp":
        x = rng.standard_normal((n, 4)).astype(np.float32)
        labels = rng.integers(0, 3, size=n)
        y = np.zeros((n, 3), np.float32)
        y[np.arange(n), labels] = 1.0
        return x, y
    raise ValueError(f"no synthetic batch source for model {model!r} "
                     "(elastic cluster jobs are mlp)")


def params_digest(params) -> str:
    """Order-stable hash of every parameter leaf's bytes — the bitwise
    fit-parity witness the soak compares across killed/unkilled runs."""
    import jax
    h = hashlib.blake2b(digest_size=16)
    for leaf in jax.tree_util.tree_leaves(params):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


# --------------------------------------------------------------------------
# coordinator client
# --------------------------------------------------------------------------

class CoordClient:
    """HTTP adapter to the ElasticCoordinator: every RPC goes through the
    shared retry primitive (``component="cluster"``), and coordinator
    verdicts come back as the elastic exceptions (409 stale_generation →
    FencedError, 410 → EvictedError) so the worker's control flow never
    parses status codes."""

    def __init__(self, base_url: str, worker_id: str, timeout: float = 5.0):
        self.base = base_url.rstrip("/")
        self.worker_id = worker_id
        self.timeout = timeout

    # -- transport ---------------------------------------------------------
    def _raise_mapped(self, e: urllib.error.HTTPError):
        try:
            doc = json.loads(e.read().decode() or "{}")
        except Exception:   # noqa: BLE001 — unparseable body: keep HTTPError
            raise e from None
        kind = doc.get("error")
        if kind == "stale_generation":
            raise FencedError(doc.get("message", "fenced"),
                              proposal=doc.get("proposal"),
                              anchor=doc.get("anchor")) from None
        if kind == "evicted":
            raise EvictedError(doc.get("message", "evicted")) from None
        if kind == "cluster_full":
            raise ClusterFullError(doc.get("message", "full")) from None
        if kind == "barrier_timeout":
            raise TransientError(doc.get("message", "barrier")) from None
        raise e

    def _post_once(self, path: str, body: bytes, headers: Dict[str, str],
                   timeout: float) -> bytes:
        req = urllib.request.Request(self.base + path, data=body,
                                     headers=headers, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            self._raise_mapped(e)
            raise   # pragma: no cover — _raise_mapped always raises

    def _rpc(self, path: str, doc: dict, *, policy=_RPC_POLICY,
             timeout: Optional[float] = None) -> dict:
        body = json.dumps(doc).encode()
        out = retry_call(self._post_once, path, body,
                         {"Content-Type": "application/json"},
                         timeout or self.timeout,
                         policy=policy, component="cluster")
        return json.loads(out or b"{}")

    # -- RPCs --------------------------------------------------------------
    def join(self) -> dict:
        return self._rpc("/join", {"worker_id": self.worker_id})

    def sync(self, generation: int) -> dict:
        return self._rpc("/sync", {"worker_id": self.worker_id,
                                   "generation": int(generation)})

    def heartbeat(self, generation: int, step: int) -> dict:
        return self._rpc("/heartbeat", {"worker_id": self.worker_id,
                                        "generation": int(generation),
                                        "step": int(step)})

    def anchor(self, generation: int, step: int,
               path: Optional[str]) -> dict:
        return self._rpc("/anchor", {"worker_id": self.worker_id,
                                     "generation": int(generation),
                                     "step": int(step), "path": path})

    def result(self, payload: dict) -> None:
        self._rpc("/result", {"worker_id": self.worker_id,
                              "result": payload})

    def leave(self) -> None:
        self._rpc("/leave", {"worker_id": self.worker_id})

    def state(self) -> dict:
        with urllib.request.urlopen(self.base + "/state",
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def allreduce(self, generation: int, step: int, rows: int,
                  vec: np.ndarray) -> np.ndarray:
        """Post this member's pre-scaled vector; block until the reduced
        one comes back. Socket timeout > the coordinator's barrier wait so
        the server, not the client, decides a barrier is stuck."""
        headers = {"Content-Type": "application/octet-stream",
                   "X-Worker": self.worker_id,
                   "X-Gen": str(int(generation)),
                   "X-Step": str(int(step)), "X-Rows": str(int(rows))}
        body = np.ascontiguousarray(vec, dtype=np.float32).tobytes()
        out = retry_call(self._post_once, "/allreduce", body, headers, 75.0,
                         policy=_REDUCE_POLICY, component="cluster")
        return np.frombuffer(out, dtype=np.float32)


# --------------------------------------------------------------------------
# worker
# --------------------------------------------------------------------------

class _LeaseBox:
    """What the heartbeat thread learned last, for the train loop to poll
    between steps (lock-guarded; the two threads share nothing else)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.generation = 0
        self.step = 0
        self.directive = "none"
        self.proposal: Optional[int] = None
        self.evicted = False

    def snapshot(self):
        with self._lock:
            return (self.directive, self.proposal, self.evicted)

    def set_progress(self, generation: int, step: int):
        with self._lock:
            self.generation, self.step = generation, step

    def absorb(self, resp: dict):
        with self._lock:
            self.directive = resp.get("directive", "none")
            self.proposal = resp.get("proposal")

    def mark_evicted(self):
        with self._lock:
            self.evicted = True


class ElasticWorker:
    """One cluster member's whole lifecycle: join → sync → train → result.

    ``clock``/network injection happens in the coordinator; the worker is
    deliberately plain — everything interesting about elasticity lives in
    how it reacts to FencedError (resync at the anchor) and EvictedError
    (exit; the seat belongs to a replacement now).
    """

    def __init__(self, coordinator: str, worker_id: str,
                 port_file: Optional[str] = None):
        self.client = CoordClient(coordinator, worker_id)
        self.worker_id = worker_id
        self.port_file = port_file
        self.box = _LeaseBox()
        self.cfg: dict = {}
        self.net = None
        self.generation = 0
        self.rank: Optional[int] = None
        self.world = 0
        self.anchor: dict = {"step": 0, "path": None}
        self.step = 0
        self.last_loss: Optional[float] = None
        self.aot_restored = 0
        self.rejoined = False
        self._grad_jit = None
        self._upd_jit = None
        self._grad_exec: Dict[int, object] = {}     # rows → AOT program
        self._upd_exec = None
        self._unravel = None
        self._cm = None
        self._stop_hb = threading.Event()
        self._use_jax_collectives = False

    # -- logging -----------------------------------------------------------
    def _log(self, msg: str):
        print(f"CLUSTER[{self.worker_id}] {msg}", flush=True)

    # -- heartbeat thread --------------------------------------------------
    def _hb_loop(self):
        interval = float(self.cfg.get("hb_interval", 0.25))
        while not self._stop_hb.wait(interval):
            try:
                resp = self.client.heartbeat(self.generation, self.step)
                self.box.absorb(resp)
            except EvictedError:
                self.box.mark_evicted()
                return
            except Exception:   # noqa: BLE001 — next beat retries
                pass

    # -- membership --------------------------------------------------------
    def _resync(self, proposal: Optional[int]) -> None:
        """Ack ``proposal`` (or whatever supersedes it) until a generation
        commits, then roll back to its anchor and adopt its (rank, world).
        This is THE recovery path: initial formation, post-eviction reform,
        degraded commit and replacement onboarding all land here."""
        target = proposal or self.generation or 1
        interval = float(self.cfg.get("hb_interval", 0.25))
        while True:
            if self.box.snapshot()[2]:
                raise EvictedError(f"{self.worker_id} evicted during sync")
            resp = self.client.sync(target)
            if resp.get("status") == "go":
                break
            target = resp.get("proposal") or target
            time.sleep(interval / 2)
        self.generation = int(resp["generation"])
        self.rank = int(resp["rank"])
        self.world = int(resp["world"])
        self.anchor = dict(resp.get("anchor") or
                           {"step": 0, "path": None})
        # rank-tag this process for flight-recorder spills and re-stamp the
        # elastic topology + generation fence (parallel/distributed.py)
        os.environ["DL4JTPU_RANK"] = str(self.rank)
        os.environ["DL4JTPU_WORLD"] = str(self.world)
        from deeplearning4j_tpu.parallel import distributed as dist
        dist.initialize(process_id=self.rank, num_processes=self.world,
                        generation=self.generation)
        self._restore_anchor()
        self.step = int(self.anchor.get("step") or 0)
        self.box.set_progress(self.generation, self.step)
        # clear any directive a pre-commit heartbeat left behind; a stale
        # one only costs a harmless replay from the anchor (reduced steps
        # are cached, so replayed contributions read the same vectors)
        self.box.absorb({"directive": "none", "proposal": None})
        self._log(f"generation={self.generation} rank={self.rank} "
                  f"world={self.world} anchor_step={self.step}")

    def _restore_anchor(self) -> None:
        path = self.anchor.get("path")
        if path and os.path.exists(path):
            from deeplearning4j_tpu.util.model_serializer import restore_into
            restore_into(self.net, path)
            self._maybe_restore_aot(path)
            return
        # no anchor yet: restart step 0 on the deterministic seed-built
        # model. A survivor rolling back here (eviction before the first
        # checkpoint) has already applied updates, so resetting the step
        # counter alone would replay steps 0..k onto advanced params while
        # a replacement starts from the fresh seed build — rebuild from
        # seed so every member re-enters step 0 bitwise identical.
        if self.net is not None and self.net.iteration != 0:
            from deeplearning4j_tpu.serving.replica import build_model
            self.net = build_model(self.cfg["model"])
            self._grad_exec.clear()
            self._upd_exec = None
            self._unravel = None
            self._build_programs()
        self.net.iteration = 0

    # -- programs ----------------------------------------------------------
    def _build_programs(self) -> None:
        import jax
        net = self.net

        def grad_step(params, state, x, y, rng):
            (loss, new_state), grads = jax.value_and_grad(
                net._dp_loss, has_aux=True)(params, state, x, y, rng)
            return loss, new_state, grads

        def upd(params, opt_state, grads):
            return net._dp_apply_updates(params, opt_state, grads)

        self._grad_jit = jax.jit(grad_step)
        # NO donate_argnums on the update: after a rollback the params /
        # opt_state leaves are numpy arrays zero-copy-aliased by
        # restore_into, and donating buffers that host memory still aliases
        # lets XLA recycle them under live arrays — the bytes of
        # self.net.params then mutate between steps, breaking bitwise
        # recovery parity (race-dependent; surfaced only under the
        # cluster's barrier delays + heartbeat thread).
        self._upd_jit = jax.jit(upd)

    def _model_sig(self) -> str:
        from deeplearning4j_tpu.exec.aot import model_signature
        return model_signature(self.net.params, self.net.opt_state)

    def _maybe_restore_aot(self, ckpt_path: str) -> None:
        """A replacement restores the anchored checkpoint's companion AOT
        bundle so it re-enters the step loop with ZERO compiles."""
        if not self.cfg.get("aot", True):
            return
        from deeplearning4j_tpu.exec.aot import companion_path, open_bundle
        bundle, reason = open_bundle(companion_path(ckpt_path),
                                     self._model_sig(), _AOT_PRECISION)
        if bundle is None:
            self._log(f"CLUSTER_AOT miss reason={reason}")
            return
        restored = 0
        for key in sorted(bundle.keys()):
            prog = bundle.restore(key, engine="cluster")
            if prog is None:
                continue
            if key == "cluster:update":
                self._upd_exec = prog
                restored += 1
            elif key.startswith("cluster:grad:b"):
                self._grad_exec[int(key.rsplit("b", 1)[1])] = prog
                restored += 1
        self.aot_restored += restored
        self._log(f"CLUSTER_AOT restored={restored}")

    def _export_aot(self, ckpt_path: str, example) -> None:
        """Rank 0 rides an AOT bundle alongside every anchor checkpoint:
        grad program at the current shard width + the update program."""
        from deeplearning4j_tpu.exec.aot import (AotBundle, companion_path,
                                                 export_compiled)
        params, state, x, y, rng, grads = example
        try:
            bundle = AotBundle(self._model_sig(), _AOT_PRECISION)
            bundle.add_compiled(f"cluster:grad:b{x.shape[0]}",
                                export_compiled(self._grad_jit,
                                                (params, state, x, y, rng)))
            bundle.add_compiled("cluster:update",
                                export_compiled(self._upd_jit,
                                                (params,
                                                 self.net.opt_state, grads)))
            bundle.save(companion_path(ckpt_path))
        except Exception as e:    # noqa: BLE001 — AOT is an accelerant,
            self._log(f"CLUSTER_AOT export failed: {e}")  # never a blocker

    # -- collectives -------------------------------------------------------
    def _probe_jax_collectives(self) -> bool:
        """``DL4JTPU_CLUSTER_BACKEND=jax``: form a real ``jax.distributed``
        client (address in DL4JTPU_JAX_COORD) and verify a cross-process
        allgather actually works. jaxlib CPU wheels ship no such
        collectives, so on CI this probe fails and the loopback-TCP path
        carries the traffic; on a jaxlib with gloo/real backends the SAME
        rank-ordered sum runs in-mesh. jax.distributed cannot re-form
        after a membership change, so any reform drops back to TCP."""
        if os.environ.get("DL4JTPU_CLUSTER_BACKEND") != "jax":
            return False
        addr = os.environ.get("DL4JTPU_JAX_COORD")
        if not addr:
            return False
        try:
            from deeplearning4j_tpu.parallel import distributed as dist
            dist.initialize(coordinator_address=addr,
                            num_processes=self.world,
                            process_id=self.rank)
            import jax
            from jax.experimental import multihost_utils
            if jax.process_count() != self.world:
                return False
            probe = multihost_utils.process_allgather(
                np.float32(self.rank))
            return probe.shape[0] == self.world
        except Exception as e:    # noqa: BLE001 — documented fallback
            self._log(f"jax collectives unavailable ({e!r}); "
                      "using loopback-TCP allreduce")
            return False

    def _reduce(self, rows: int, vec: np.ndarray) -> np.ndarray:
        if self._use_jax_collectives:
            from jax.experimental import multihost_utils
            gathered = multihost_utils.process_allgather(vec)
            rows_all = multihost_utils.process_allgather(
                np.float32(rows))
            total = gathered[0].copy()
            for r in range(1, gathered.shape[0]):   # rank order: bitwise
                total = total + gathered[r]
            return np.asarray(total / np.float32(rows_all.sum()))
        return self.client.allreduce(self.generation, self.step, rows, vec)

    # -- training ----------------------------------------------------------
    def _train_step(self, chaos) -> None:
        import jax
        from jax.flatten_util import ravel_pytree

        from deeplearning4j_tpu.parallel.distributed import local_batch_slice
        net, cfg, step = self.net, self.cfg, self.step
        chaos.on_step(step)
        gb = int(cfg["global_batch"])
        x, y = synth_batch(cfg["model"], cfg["seed"], step, gb)
        sl = local_batch_slice(gb, rank=self.rank, world=self.world)
        rows = sl.stop - sl.start
        rng = jax.random.fold_in(jax.random.PRNGKey(int(cfg["seed"])), step)
        fn = self._grad_exec.get(rows, self._grad_jit)
        loss, new_state, grads = fn(net.params, net.state, x[sl], y[sl], rng)
        flat, unravel = ravel_pytree(grads)
        if self._unravel is None:
            self._unravel = unravel
        vec = np.concatenate(
            [np.float32([loss]), np.asarray(flat, np.float32)])
        reduced = self._reduce(rows, vec * np.float32(rows))
        self.last_loss = float(reduced[0])
        mean_grads = self._unravel(np.asarray(reduced[1:], np.float32))
        upd = self._upd_exec or self._upd_jit
        if os.environ.get("DL4JTPU_CLUSTER_TRACE"):
            self._log(f"TRACE-IN step={step} "
                      f"p={params_digest(net.params)[:8]} "
                      f"o={params_digest(net.opt_state)[:8]} "
                      f"g={params_digest(mean_grads)[:8]}")
        net.params, net.opt_state = upd(net.params, net.opt_state,
                                        mean_grads)
        net.state = new_state
        net.iteration = step + 1
        self.step = step + 1
        self.box.set_progress(self.generation, self.step)
        if os.environ.get("DL4JTPU_CLUSTER_TRACE"):
            rd = hashlib.blake2b(
                np.ascontiguousarray(reduced).tobytes(),
                digest_size=8).hexdigest()
            self._log(f"TRACE step={step} gen={self.generation} "
                      f"rows={rows} loss={self.last_loss!r} "
                      f"reduced={rd} opt={params_digest(net.opt_state)} "
                      f"digest={params_digest(net.params)}")
        self._maybe_checkpoint((net.params, net.state, x[sl], y[sl], rng),
                               mean_grads)

    def _maybe_checkpoint(self, grad_example, grads) -> None:
        cfg, step = self.cfg, self.step
        every = int(cfg.get("ckpt_every") or 0)
        final = step >= int(cfg["total_steps"])
        if self.rank != 0 or not cfg.get("ckpt_dir"):
            return
        if not final and (not every or step % every != 0):
            return
        if self._cm is None:
            from deeplearning4j_tpu.resilience.checkpoint import \
                CheckpointManager
            self._cm = CheckpointManager(cfg["ckpt_dir"], keep_last=3)
        path = self._cm.save(self.net)
        if cfg.get("aot", True):
            params, state, x, y, rng = grad_example
            self._export_aot(path, (params, state, x, y, rng, grads))
        self._cm.set_anchor(self.net.iteration)
        self.client.anchor(self.generation, step, path)
        self.anchor = {"step": step, "path": path}
        self._log(f"anchor step={step} path={os.path.basename(path)}")

    # -- lifecycle ---------------------------------------------------------
    def run(self) -> int:
        from deeplearning4j_tpu.resilience.faults import WorkerChaos
        from deeplearning4j_tpu.util.compile_cache import setup_compile_cache
        setup_compile_cache()
        try:
            joined = self.client.join()
        except ClusterFullError as e:
            self._log(f"join rejected: {e}")
            return 4
        self.cfg = joined["config"]
        self.rejoined = bool(joined.get("proposal", 1) > 1)
        if self.port_file:
            tmp = self.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{os.getpid()}\n")
            os.replace(tmp, self.port_file)

        from deeplearning4j_tpu.serving.replica import build_model
        self.net = build_model(self.cfg["model"])
        self._build_programs()
        chaos = WorkerChaos.from_env()

        hb = threading.Thread(target=self._hb_loop, name="cluster-hb",
                              daemon=True)
        hb.start()
        try:
            self._resync(joined.get("proposal"))
            self._use_jax_collectives = self._probe_jax_collectives()
            total = int(self.cfg["total_steps"])
            while self.step < total:
                directive, proposal, evicted = self.box.snapshot()
                if evicted:
                    raise EvictedError(f"{self.worker_id} lease lost")
                if directive == "rollback":
                    self._use_jax_collectives = False
                    self._resync(proposal)
                    continue
                try:
                    self._train_step(chaos)
                except FencedError as e:
                    self._log(f"fenced at step {self.step}: {e}")
                    self._use_jax_collectives = False
                    self._resync(e.proposal)
            self._finish()
            return 0
        except EvictedError as e:
            self._log(f"evicted: {e}")
            return 3
        finally:
            self._stop_hb.set()

    def _finish(self) -> None:
        payload = {"worker_id": self.worker_id, "rank": self.rank,
                   "world": self.world, "generation": self.generation,
                   "steps": self.step, "iteration": self.net.iteration,
                   "final_loss": self.last_loss,
                   "params_digest": params_digest(self.net.params),
                   "aot_restored": self.aot_restored,
                   "rejoined": self.rejoined}
        self.client.result(payload)
        self._log(f"done digest={payload['params_digest']} "
                  f"loss={self.last_loss}")
        # hold the lease until every live member reported, so a slightly
        # slower peer is not evicted into a pointless terminal reform
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                if self.client.state().get("phase") == "done":
                    return
            except Exception:   # noqa: BLE001 — coordinator going away is fine
                return
            time.sleep(0.1)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="elastic DP training worker")
    p.add_argument("--coordinator", required=True,
                   help="ElasticCoordinator base URL")
    p.add_argument("--worker-id", required=True)
    p.add_argument("--rank", type=int, default=None,
                   help="informational spawn rank (committed rank is "
                        "assigned by the coordinator at each generation)")
    p.add_argument("--port-file", default=None,
                   help="written with this worker's pid after a "
                        "successful join (the spawn handshake)")
    args = p.parse_args(argv)
    try:
        return ElasticWorker(args.coordinator, args.worker_id,
                             port_file=args.port_file).run()
    except (ClusterFullError,) as e:
        print(f"CLUSTER[{args.worker_id}] fatal: {e}", flush=True)
        return 4
    except Exception as e:      # noqa: BLE001 — setup/config failures
        import traceback
        traceback.print_exc()
        print(f"CLUSTER[{args.worker_id}] fatal: {e}", flush=True)
        return 5


if __name__ == "__main__":
    sys.exit(main())
