"""Elastic data-parallel training worker (one process, one cluster member).

``python -m deeplearning4j_tpu.exec.worker --coordinator URL --worker-id w0
--port-file /run/w0.port`` joins the ElasticCoordinator (exec/elastic.py),
builds the deterministic job model, and trains lockstep data-parallel
steps until ``total_steps``:

- **Deterministic shards.** Every worker materializes the SAME global
  batch from ``(seed, step)`` and takes its committed-rank slice
  (``parallel.distributed.local_batch_slice``) — re-sharding after an
  elastic reform is just a different slice of the same bytes.
- **Reduction.** Grad + loss ravel into one f32 vector, pre-scaled by the
  shard's row count, and mean-reduced over the pluggable data plane
  (``docs/ELASTIC_TRAINING.md`` "Data plane"). The default is the
  chunk-pipelined peer-to-peer chain (``exec/comms.py``): gradient bytes
  flow worker-to-worker over persistent loopback TCP, the coordinator
  stays control-plane-only, and the rank-ordered accumulation keeps the
  dense path bitwise-equal to the ``data_plane="star"`` fallback (PR 19's
  coordinator-reduced HTTP path, kept as the parity oracle) and to
  ``single_process_reference``. With ``DL4JTPU_CLUSTER_BACKEND=jax`` (and
  a jaxlib whose backend actually ships cross-process collectives) the
  same vector goes through a real ``process_allgather`` summed in the
  same rank order — identical math, in-mesh transport.
- **Elasticity.** A heartbeat thread renews the lease; any fenced RPC or
  rollback directive sends the worker to ``_resync``: restore the anchor
  checkpoint (bitwise, PR 4), ack the proposed generation, resume at the
  anchor step under the committed (rank, world). Replacements walk the
  same path from scratch — join, restore anchor, AOT-restore the train
  programs from the checkpoint's companion bundle, continue — which is
  why a killed-and-replaced run finishes bitwise-equal to an unkilled
  one.
- **Chaos.** ``resilience.faults.WorkerChaos`` (env
  ``DL4JTPU_WORKER_CHAOS``) injects per-step slowdowns and scripted
  self-SIGKILL for the soak tests.

Exit codes: 0 done, 3 evicted (a replacement took the seat), 4 cluster
full, 5 fatal config/setup error.
"""

from __future__ import annotations

import argparse
import hashlib
import http.client
import json
import os
import sys
import threading
import time
from typing import Dict, Optional
from urllib.parse import urlparse

import numpy as np

from deeplearning4j_tpu.exec.comms import (ChainComms, CommsAbortedError,
                                           CommsError, record_star_bytes)
from deeplearning4j_tpu.exec.elastic import (ClusterFullError, EvictedError,
                                             FencedError)
from deeplearning4j_tpu.resilience.errors import TransientError
from deeplearning4j_tpu.resilience.retry import RetryPolicy, retry_call

__all__ = ["CoordClient", "ElasticWorker", "synth_batch", "params_digest",
           "single_process_reference", "main"]

# one bundle-validity envelope for the cluster's train programs (grad is
# shape-specialized per shard-row count, update is shape-stable)
_AOT_PRECISION = "cluster-f32"

_RPC_POLICY = RetryPolicy(max_attempts=6, base_delay=0.05, max_delay=1.0)
# the allreduce blocks server-side until the barrier fills; retries are
# idempotent (the coordinator caches reduced steps), so ride out stragglers
# with an overall deadline instead of an attempt cap
_REDUCE_POLICY = RetryPolicy(max_attempts=None, base_delay=0.1,
                             max_delay=1.0, deadline=240.0)


def synth_batch(model: str, seed: int, step: int, n: int):
    """The deterministic GLOBAL batch for ``step`` — a pure function of
    ``(model, seed, step)`` so every member (including a replacement that
    joined five generations later) slices identical bytes."""
    rng = np.random.default_rng([int(seed), int(step), 0xE1A])
    if model in ("mlp", "widemlp"):
        x = rng.standard_normal((n, 4)).astype(np.float32)
        labels = rng.integers(0, 3, size=n)
        y = np.zeros((n, 3), np.float32)
        y[np.arange(n), labels] = 1.0
        return x, y
    if model == "charlstm":
        from deeplearning4j_tpu.serving.replica import CHAR_VOCAB
        T = 16
        toks = rng.integers(0, CHAR_VOCAB, (n, T + 1))
        x = np.zeros((n, T, CHAR_VOCAB), np.float32)
        y = np.zeros((n, T, CHAR_VOCAB), np.float32)
        ar = np.arange(T)
        for i in range(n):   # next-token prediction on synthetic streams
            x[i, ar, toks[i, :-1]] = 1.0
            y[i, ar, toks[i, 1:]] = 1.0
        return x, y
    raise ValueError(f"no synthetic batch source for model {model!r} "
                     "(elastic cluster jobs: mlp | widemlp | charlstm)")


def params_digest(params) -> str:
    """Order-stable hash of every parameter leaf's bytes — the bitwise
    fit-parity witness the soak compares across killed/unkilled runs."""
    import jax
    h = hashlib.blake2b(digest_size=16)
    for leaf in jax.tree_util.tree_leaves(params):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def dp_programs(net):
    """The two jitted programs every data plane shares: a grad step that
    returns ``(vec, new_state)`` with ``vec = [loss, flat-grads]`` already
    flattened IN-GRAPH, and an update that takes the flat mean-grad vector
    back and unravels it in-graph. Flatten/unflatten living inside XLA
    instead of eager numpy is worth ~0.15 s/step on a ~13 MB-of-grads
    model (ravel_pytree dispatches one eager op per leaf), and the wire
    wants the flat vector anyway. Concatenate/reshape are pure layout, so
    the arithmetic — and the bitwise parity contract between chain, star
    and the single-process oracle — is unchanged."""
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    # grads mirror the param tree, so params donate the unravel closure
    _, unravel = ravel_pytree(net.params)

    def grad_step(params, state, x, y, rng):
        (loss, new_state), grads = jax.value_and_grad(
            net._dp_loss, has_aux=True)(params, state, x, y, rng)
        flat, _ = ravel_pytree(grads)
        vec = jnp.concatenate(
            [jnp.reshape(loss, (1,)).astype(jnp.float32),
             flat.astype(jnp.float32)])
        return vec, new_state

    def upd(params, opt_state, flat_grads):
        return net._dp_apply_updates(params, opt_state,
                                     unravel(flat_grads))

    return jax.jit(grad_step), jax.jit(upd)


def single_process_reference(model: str = "mlp", seed: int = 42,
                             total_steps: int = 8, global_batch: int = 32,
                             world: int = 2) -> dict:
    """The cluster's exact arithmetic replayed in ONE process: per-rank
    shard gradients from the same jitted program at the same shard
    shapes, summed in rank order, divided by ``float32(total rows)``, one
    shared update. This is the single-process oracle the dense data
    planes (chain AND star) must match BITWISE — a literal big-batch fit
    is only tolerance-close, because XLA's batch reduction associates
    floats differently than the shard-wise rank-ordered sum."""
    import jax

    from deeplearning4j_tpu.parallel.distributed import local_batch_slice
    from deeplearning4j_tpu.serving.replica import build_model
    net = build_model(model)
    gj, uj = dp_programs(net)
    reduced = None
    for step in range(int(total_steps)):
        x, y = synth_batch(model, seed, step, int(global_batch))
        rng = jax.random.fold_in(jax.random.PRNGKey(int(seed)), step)
        total, rows_sum, new_state = None, 0, net.state
        for r in range(int(world)):
            sl = local_batch_slice(int(global_batch), rank=r, world=world)
            rows = sl.stop - sl.start
            out, new_state = gj(net.params, net.state, x[sl], y[sl], rng)
            vec = np.asarray(out, np.float32) * np.float32(rows)
            total = vec.copy() if total is None else total + vec
            rows_sum += rows
        reduced = total / np.float32(rows_sum)
        net.params, net.opt_state = uj(net.params, net.opt_state,
                                       np.asarray(reduced[1:], np.float32))
        net.state = new_state
        net.iteration = step + 1
    return {"params_digest": params_digest(net.params),
            "final_loss": float(reduced[0]) if reduced is not None else None,
            "steps": int(total_steps)}


# --------------------------------------------------------------------------
# coordinator client
# --------------------------------------------------------------------------

# socket-level failures meaning "the keep-alive connection died", not "the
# coordinator answered an error" — eligible for the in-call reconnect (the
# serving/client.py idiom; IncompleteRead covers a drop mid-response)
_CONN_ERRORS = (http.client.RemoteDisconnected,
                http.client.CannotSendRequest,
                http.client.BadStatusLine,
                http.client.IncompleteRead,
                ConnectionError, BrokenPipeError, OSError)


class CoordClient:
    """HTTP adapter to the ElasticCoordinator: every RPC goes through the
    shared retry primitive (``component="cluster"``), and coordinator
    verdicts come back as the elastic exceptions (409 stale_generation →
    FencedError, 410 → EvictedError) so the worker's control flow never
    parses status codes.

    Transport is one persistent keep-alive ``http.client.HTTPConnection``
    per thread (the train loop and the heartbeat thread each own one —
    connections are not thread-safe), the serving/client.py idiom: a
    dropped socket reconnects ONCE within the call before the retry
    policy sees an error. The control plane runs dozens of RPCs per
    second per worker; re-dialing each one was measurable coordinator
    load at N=4."""

    def __init__(self, base_url: str, worker_id: str, timeout: float = 5.0):
        self.base = base_url.rstrip("/")
        parsed = urlparse(self.base)
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or 80
        self.worker_id = worker_id
        self.timeout = timeout
        self._local = threading.local()

    # -- transport ---------------------------------------------------------
    def _conn(self, timeout: float) -> http.client.HTTPConnection:
        c = getattr(self._local, "conn", None)
        if c is None:
            c = http.client.HTTPConnection(self.host, self.port,
                                           timeout=timeout)
            self._local.conn = c
        else:
            c.timeout = timeout
            if c.sock is not None:
                c.sock.settimeout(timeout)
        return c

    def close(self) -> None:
        """Drop this thread's persistent connection; the next RPC redials."""
        c = getattr(self._local, "conn", None)
        if c is not None:
            try:
                c.close()
            except Exception:   # noqa: BLE001 — already-dead socket
                pass
            self._local.conn = None

    def _roundtrip(self, method: str, path: str, body: Optional[bytes],
                   headers: Dict[str, str], timeout: float):
        # attempt 0 may find a keep-alive socket the coordinator already
        # reaped; reconnect once within the call — a second failure is a
        # real connection problem for the retry policy
        for attempt in (0, 1):
            conn = self._conn(timeout)
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                return resp.status, resp.read()
            except TimeoutError:
                self.close()
                raise
            except _CONN_ERRORS as e:
                self.close()
                if attempt:
                    # surface as retryable: the classifier treats a bare
                    # OSError as fatal, but a dead coordinator socket is
                    # exactly what the retry budget exists for
                    raise TransientError(
                        f"coordinator connection failed: {e!r}") from e

    def _raise_mapped(self, status: int, data: bytes):
        try:
            doc = json.loads(data.decode() or "{}")
        except Exception:   # noqa: BLE001 — unparseable body
            doc = {}
        kind = doc.get("error")
        msg = doc.get("message", f"HTTP {status}")
        if kind == "stale_generation":
            raise FencedError(msg, proposal=doc.get("proposal"),
                              anchor=doc.get("anchor"))
        if kind == "evicted":
            raise EvictedError(msg)
        if kind == "cluster_full":
            raise ClusterFullError(msg)
        if kind == "barrier_timeout":
            raise TransientError(msg)
        if status in (429, 502, 503, 504):
            raise TransientError(msg)
        raise RuntimeError(f"coordinator HTTP {status}: {msg}")

    def _post_once(self, path: str, body: bytes, headers: Dict[str, str],
                   timeout: float) -> bytes:
        status, data = self._roundtrip("POST", path, body, headers, timeout)
        if status >= 400:
            self._raise_mapped(status, data)
        return data

    def _rpc(self, path: str, doc: dict, *, policy=_RPC_POLICY,
             timeout: Optional[float] = None) -> dict:
        body = json.dumps(doc).encode()
        out = retry_call(self._post_once, path, body,
                         {"Content-Type": "application/json"},
                         timeout or self.timeout,
                         policy=policy, component="cluster")
        return json.loads(out or b"{}")

    # -- RPCs --------------------------------------------------------------
    def join(self, data_port: int = 0) -> dict:
        return self._rpc("/join", {"worker_id": self.worker_id,
                                   "data_port": int(data_port)})

    def sync(self, generation: int) -> dict:
        return self._rpc("/sync", {"worker_id": self.worker_id,
                                   "generation": int(generation)})

    def heartbeat(self, generation: int, step: int) -> dict:
        return self._rpc("/heartbeat", {"worker_id": self.worker_id,
                                        "generation": int(generation),
                                        "step": int(step)})

    def anchor(self, generation: int, step: int,
               path: Optional[str]) -> dict:
        return self._rpc("/anchor", {"worker_id": self.worker_id,
                                     "generation": int(generation),
                                     "step": int(step), "path": path})

    def result(self, payload: dict) -> None:
        self._rpc("/result", {"worker_id": self.worker_id,
                              "result": payload})

    def leave(self) -> None:
        self._rpc("/leave", {"worker_id": self.worker_id})

    def state(self) -> dict:
        status, data = self._roundtrip("GET", "/state", None, {},
                                       self.timeout)
        if status >= 400:
            self._raise_mapped(status, data)
        return json.loads(data)

    def allreduce(self, generation: int, step: int, rows: int,
                  vec: np.ndarray) -> np.ndarray:
        """Post this member's pre-scaled vector; block until the reduced
        one comes back. Socket timeout > the coordinator's barrier wait so
        the server, not the client, decides a barrier is stuck."""
        headers = {"Content-Type": "application/octet-stream",
                   "X-Worker": self.worker_id,
                   "X-Gen": str(int(generation)),
                   "X-Step": str(int(step)), "X-Rows": str(int(rows))}
        body = np.ascontiguousarray(vec, dtype=np.float32).tobytes()
        out = retry_call(self._post_once, "/allreduce", body, headers, 75.0,
                         policy=_REDUCE_POLICY, component="cluster")
        return np.frombuffer(out, dtype=np.float32)


# --------------------------------------------------------------------------
# worker
# --------------------------------------------------------------------------

class _LeaseBox:
    """What the heartbeat thread learned last, for the train loop to poll
    between steps (lock-guarded; the two threads share nothing else)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.generation = 0
        self.step = 0
        self.directive = "none"
        self.proposal: Optional[int] = None
        self.coord_gen = 0          # coordinator's committed generation
        self.evicted = False        # as stamped on the last heartbeat

    def snapshot(self):
        with self._lock:
            return (self.directive, self.proposal, self.evicted)

    def snapshot_full(self):
        with self._lock:
            return (self.directive, self.proposal, self.coord_gen,
                    self.evicted)

    def set_progress(self, generation: int, step: int):
        with self._lock:
            self.generation, self.step = generation, step

    def absorb(self, resp: dict):
        with self._lock:
            self.directive = resp.get("directive", "none")
            self.proposal = resp.get("proposal")
            self.coord_gen = int(resp.get("generation") or 0)

    def mark_evicted(self):
        with self._lock:
            self.evicted = True


class ElasticWorker:
    """One cluster member's whole lifecycle: join → sync → train → result.

    ``clock``/network injection happens in the coordinator; the worker is
    deliberately plain — everything interesting about elasticity lives in
    how it reacts to FencedError (resync at the anchor) and EvictedError
    (exit; the seat belongs to a replacement now).
    """

    def __init__(self, coordinator: str, worker_id: str,
                 port_file: Optional[str] = None):
        self.client = CoordClient(coordinator, worker_id)
        self.worker_id = worker_id
        self.port_file = port_file
        self.box = _LeaseBox()
        self.cfg: dict = {}
        self.net = None
        self.generation = 0
        self.rank: Optional[int] = None
        self.world = 0
        self.anchor: dict = {"step": 0, "path": None}
        self.step = 0
        self.last_loss: Optional[float] = None
        self.aot_restored = 0
        self.rejoined = False
        self._grad_jit = None
        self._upd_jit = None
        self._grad_exec: Dict[int, object] = {}     # rows → AOT program
        self._upd_exec = None
        self._cm = None
        self._stop_hb = threading.Event()
        self._use_jax_collectives = False
        # data plane (exec/comms.py): the listener must exist before join
        # so its port can ride the join RPC; the codec/bucket policy is
        # adopted from the coordinator's config after join
        self.comms: Optional[ChainComms] = ChainComms()
        self._plane = "chain"
        self._comm_seconds = 0.0
        self._step_seconds = 0.0
        self._star_sent = 0
        self._star_recv = 0

    # -- logging -----------------------------------------------------------
    def _log(self, msg: str):
        print(f"CLUSTER[{self.worker_id}] {msg}", flush=True)

    # -- heartbeat thread --------------------------------------------------
    def _hb_loop(self):
        interval = float(self.cfg.get("hb_interval", 0.25))
        while not self._stop_hb.wait(interval):
            try:
                resp = self.client.heartbeat(self.generation, self.step)
                self.box.absorb(resp)
            except EvictedError:
                self.box.mark_evicted()
                return
            except Exception:   # noqa: BLE001 — next beat retries
                pass

    # -- membership --------------------------------------------------------
    def _abort_check(self) -> bool:
        """Should a blocked data-plane wait give up? Yes once the lease
        layer has seen a rollback directive or our own eviction — the
        membership changed, the current exchange can never complete."""
        directive, proposal, evicted = self.box.snapshot()
        if evicted:
            return True
        return (directive == "rollback"
                and not self._stale_rollback(proposal))

    def _stale_rollback(self, proposal: Optional[int]) -> bool:
        """A heartbeat response computed DURING a reform can land after
        that reform committed and we already resynced — its rollback
        directive targets a generation we are already in. Acting on it
        would tear down a healthy chain (peers mid-step would see EOF), so
        directives that do not point PAST our committed generation are
        ignored; the next heartbeat clears them."""
        _, _, coord_gen, _ = self.box.snapshot_full()
        return max(proposal or 0, coord_gen) <= self.generation

    def _await_reform(self, why: str) -> Optional[int]:
        """The data plane failed (peer died / chain torn): the coordinator
        is the membership arbiter, so park until the lease detector turns
        the failure into a reform proposal — or into our own eviction."""
        cfg = self.cfg
        deadline = time.monotonic() + (float(cfg.get("evict_after", 4.0))
                                       + float(cfg.get("replacement_grace",
                                                       8.0)) + 60.0)
        interval = float(cfg.get("hb_interval", 0.25))
        self._log(f"data plane failed ({why}); awaiting reform")
        while time.monotonic() < deadline:
            directive, proposal, coord_gen, evicted = \
                self.box.snapshot_full()
            if evicted:
                raise EvictedError(f"{self.worker_id} evicted while "
                                   "awaiting reform")
            if (directive == "rollback"
                    and not self._stale_rollback(proposal)):
                return proposal
            time.sleep(interval / 2)
        raise CommsError(f"data plane failed ({why}) and no reform "
                         "proposal arrived")

    def _resync(self, proposal: Optional[int]) -> None:
        """Ack ``proposal`` (or whatever supersedes it) until a generation
        commits, then roll back to its anchor, adopt its (rank, world) and
        rebuild the data plane. This is THE recovery path: initial
        formation, post-eviction reform, degraded commit and replacement
        onboarding all land here."""
        target = proposal or self.generation or 1
        interval = float(self.cfg.get("hb_interval", 0.25))
        while True:
            if self.box.snapshot()[2]:
                raise EvictedError(f"{self.worker_id} evicted during sync")
            resp = self.client.sync(target)
            if resp.get("status") != "go":
                target = resp.get("proposal") or target
                time.sleep(interval / 2)
                continue
            reconfigure = (int(resp["generation"]) != self.generation
                           or (self.comms is not None
                               and self.comms.generation
                               != int(resp["generation"])))
            self.generation = int(resp["generation"])
            self.rank = int(resp["rank"])
            self.world = int(resp["world"])
            self.anchor = dict(resp.get("anchor") or
                               {"step": 0, "path": None})
            # rank-tag this process for flight-recorder spills and re-stamp
            # the elastic topology + generation fence
            # (parallel/distributed.py)
            os.environ["DL4JTPU_RANK"] = str(self.rank)
            os.environ["DL4JTPU_WORLD"] = str(self.world)
            from deeplearning4j_tpu.parallel import distributed as dist
            dist.initialize(process_id=self.rank, num_processes=self.world,
                            generation=self.generation)
            self._restore_anchor()
            self.step = int(self.anchor.get("step") or 0)
            self.box.set_progress(self.generation, self.step)
            # clear any directive a pre-commit heartbeat left behind; a
            # stale one only costs a harmless replay from the anchor
            # (reduced steps are cached, so replayed contributions read the
            # same vectors)
            self.box.absorb({"directive": "none", "proposal": None,
                             "generation": self.generation})
            self._log(f"generation={self.generation} rank={self.rank} "
                      f"world={self.world} anchor_step={self.step}")
            if self._plane != "chain" or self.comms is None or not reconfigure:
                return
            # rebuild the peer chain from the committed view's endpoints;
            # configure() also resets the threshold codec on a generation
            # change — a stale pre-reform residual must never survive into
            # the new membership
            eps = {int(r): (hp[0], int(hp[1]))
                   for r, hp in (resp.get("endpoints") or {}).items()}
            try:
                self.comms.configure(self.generation, self.rank, self.world,
                                     eps, should_abort=self._abort_check)
                return
            except CommsAbortedError:
                # another reform started while we formed — resync to it
                target = self.box.snapshot()[1] or target
                continue
            except CommsError as e:
                # a peer died between commit and chain formation: the lease
                # detector will turn that into the next proposal
                target = self._await_reform(f"chain formation: {e}") or target
                continue

    def _restore_anchor(self) -> None:
        path = self.anchor.get("path")
        if path and os.path.exists(path):
            from deeplearning4j_tpu.util.model_serializer import restore_into
            restore_into(self.net, path)
            self._maybe_restore_aot(path)
            return
        # no anchor yet: restart step 0 on the deterministic seed-built
        # model. A survivor rolling back here (eviction before the first
        # checkpoint) has already applied updates, so resetting the step
        # counter alone would replay steps 0..k onto advanced params while
        # a replacement starts from the fresh seed build — rebuild from
        # seed so every member re-enters step 0 bitwise identical.
        if self.net is not None and self.net.iteration != 0:
            from deeplearning4j_tpu.serving.replica import build_model
            self.net = build_model(self.cfg["model"])
            self._grad_exec.clear()
            self._upd_exec = None
            self._build_programs()
        self.net.iteration = 0

    # -- programs ----------------------------------------------------------
    def _build_programs(self) -> None:
        # NO donate_argnums on the update: after a rollback the params /
        # opt_state leaves are numpy arrays zero-copy-aliased by
        # restore_into, and donating buffers that host memory still aliases
        # lets XLA recycle them under live arrays — the bytes of
        # self.net.params then mutate between steps, breaking bitwise
        # recovery parity (race-dependent; surfaced only under the
        # cluster's barrier delays + heartbeat thread).
        self._grad_jit, self._upd_jit = dp_programs(self.net)

    def _model_sig(self) -> str:
        from deeplearning4j_tpu.exec.aot import model_signature
        return model_signature(self.net.params, self.net.opt_state)

    def _maybe_restore_aot(self, ckpt_path: str) -> None:
        """A replacement restores the anchored checkpoint's companion AOT
        bundle so it re-enters the step loop with ZERO compiles."""
        if not self.cfg.get("aot", True):
            return
        from deeplearning4j_tpu.exec.aot import companion_path, open_bundle
        bundle, reason = open_bundle(companion_path(ckpt_path),
                                     self._model_sig(), _AOT_PRECISION)
        if bundle is None:
            self._log(f"CLUSTER_AOT miss reason={reason}")
            return
        restored = 0
        for key in sorted(bundle.keys()):
            prog = bundle.restore(key, engine="cluster")
            if prog is None:
                continue
            if key == "cluster:update":
                self._upd_exec = prog
                restored += 1
            elif key.startswith("cluster:grad:b"):
                self._grad_exec[int(key.rsplit("b", 1)[1])] = prog
                restored += 1
        self.aot_restored += restored
        self._log(f"CLUSTER_AOT restored={restored}")

    def _export_aot(self, ckpt_path: str, example) -> None:
        """Rank 0 rides an AOT bundle alongside every anchor checkpoint:
        grad program at the current shard width + the update program."""
        from deeplearning4j_tpu.exec.aot import (AotBundle, companion_path,
                                                 export_compiled)
        params, state, x, y, rng, flat_grads = example
        try:
            bundle = AotBundle(self._model_sig(), _AOT_PRECISION)
            bundle.add_compiled(f"cluster:grad:b{x.shape[0]}",
                                export_compiled(self._grad_jit,
                                                (params, state, x, y, rng)))
            bundle.add_compiled("cluster:update",
                                export_compiled(self._upd_jit,
                                                (params, self.net.opt_state,
                                                 flat_grads)))
            bundle.save(companion_path(ckpt_path))
        except Exception as e:    # noqa: BLE001 — AOT is an accelerant,
            self._log(f"CLUSTER_AOT export failed: {e}")  # never a blocker

    # -- collectives -------------------------------------------------------
    def _probe_jax_collectives(self) -> bool:
        """``DL4JTPU_CLUSTER_BACKEND=jax``: form a real ``jax.distributed``
        client (address in DL4JTPU_JAX_COORD) and verify a cross-process
        allgather actually works. jaxlib CPU wheels ship no such
        collectives, so on CI this probe fails and the loopback-TCP path
        carries the traffic; on a jaxlib with gloo/real backends the SAME
        rank-ordered sum runs in-mesh. jax.distributed cannot re-form
        after a membership change, so any reform drops back to TCP."""
        if os.environ.get("DL4JTPU_CLUSTER_BACKEND") != "jax":
            return False
        addr = os.environ.get("DL4JTPU_JAX_COORD")
        if not addr:
            return False
        try:
            from deeplearning4j_tpu.parallel import distributed as dist
            dist.initialize(coordinator_address=addr,
                            num_processes=self.world,
                            process_id=self.rank)
            import jax
            from jax.experimental import multihost_utils
            if jax.process_count() != self.world:
                return False
            probe = multihost_utils.process_allgather(
                np.float32(self.rank))
            return probe.shape[0] == self.world
        except Exception as e:    # noqa: BLE001 — documented fallback
            self._log(f"jax collectives unavailable ({e!r}); "
                      "using loopback-TCP allreduce")
            return False

    def _reduce(self, rows: int, vec: np.ndarray) -> np.ndarray:
        t0 = time.perf_counter()
        try:
            if self._use_jax_collectives:
                from jax.experimental import multihost_utils
                gathered = multihost_utils.process_allgather(vec)
                rows_all = multihost_utils.process_allgather(
                    np.float32(rows))
                total = gathered[0].copy()
                for r in range(1, gathered.shape[0]):  # rank order: bitwise
                    total = total + gathered[r]
                return np.asarray(total / np.float32(rows_all.sum()))
            if self._plane == "chain" and self.comms is not None:
                return self.comms.allreduce(self.step, vec, rows,
                                            should_abort=self._abort_check)
            out = self.client.allreduce(self.generation, self.step, rows,
                                        vec)
            self._star_sent += vec.nbytes
            self._star_recv += out.nbytes
            record_star_bytes(vec.nbytes, out.nbytes)
            return out
        finally:
            self._comm_seconds += time.perf_counter() - t0

    # -- training ----------------------------------------------------------
    def _train_step(self, chaos) -> None:
        import jax

        from deeplearning4j_tpu.parallel.distributed import local_batch_slice
        t_step = time.perf_counter()
        net, cfg, step = self.net, self.cfg, self.step
        chaos.on_step(step)
        gb = int(cfg["global_batch"])
        x, y = synth_batch(cfg["model"], cfg["seed"], step, gb)
        sl = local_batch_slice(gb, rank=self.rank, world=self.world)
        rows = sl.stop - sl.start
        rng = jax.random.fold_in(jax.random.PRNGKey(int(cfg["seed"])), step)
        fn = self._grad_exec.get(rows, self._grad_jit)
        out, new_state = fn(net.params, net.state, x[sl], y[sl], rng)
        vec = np.asarray(out, np.float32)
        reduced = self._reduce(rows, vec * np.float32(rows))
        self.last_loss = float(reduced[0])
        flat_mean = np.asarray(reduced[1:], np.float32)
        upd = self._upd_exec or self._upd_jit
        if os.environ.get("DL4JTPU_CLUSTER_TRACE"):
            self._log(f"TRACE-IN step={step} "
                      f"p={params_digest(net.params)[:8]} "
                      f"o={params_digest(net.opt_state)[:8]} "
                      f"g={params_digest(flat_mean)[:8]}")
        net.params, net.opt_state = upd(net.params, net.opt_state,
                                        flat_mean)
        net.state = new_state
        net.iteration = step + 1
        self.step = step + 1
        self.box.set_progress(self.generation, self.step)
        self._step_seconds += time.perf_counter() - t_step
        if os.environ.get("DL4JTPU_CLUSTER_TRACE"):
            rd = hashlib.blake2b(
                np.ascontiguousarray(reduced).tobytes(),
                digest_size=8).hexdigest()
            self._log(f"TRACE step={step} gen={self.generation} "
                      f"rows={rows} loss={self.last_loss!r} "
                      f"reduced={rd} opt={params_digest(net.opt_state)} "
                      f"digest={params_digest(net.params)}")
        self._maybe_checkpoint((net.params, net.state, x[sl], y[sl], rng),
                               flat_mean)

    def _maybe_checkpoint(self, grad_example, flat_grads) -> None:
        cfg, step = self.cfg, self.step
        every = int(cfg.get("ckpt_every") or 0)
        final = step >= int(cfg["total_steps"])
        if self.rank != 0 or not cfg.get("ckpt_dir"):
            return
        if not final and (not every or step % every != 0):
            return
        if self._cm is None:
            from deeplearning4j_tpu.resilience.checkpoint import \
                CheckpointManager
            self._cm = CheckpointManager(cfg["ckpt_dir"], keep_last=3)
        path = self._cm.save(self.net)
        if cfg.get("aot", True):
            params, state, x, y, rng = grad_example
            self._export_aot(path, (params, state, x, y, rng, flat_grads))
        self._cm.set_anchor(self.net.iteration)
        self.client.anchor(self.generation, step, path)
        self.anchor = {"step": step, "path": path}
        self._log(f"anchor step={step} path={os.path.basename(path)}")

    # -- lifecycle ---------------------------------------------------------
    def run(self) -> int:
        from deeplearning4j_tpu.resilience.faults import WorkerChaos
        from deeplearning4j_tpu.util.compile_cache import setup_compile_cache
        setup_compile_cache()
        try:
            joined = self.client.join(data_port=self.comms.data_port)
        except ClusterFullError as e:
            self._log(f"join rejected: {e}")
            return 4
        self.cfg = joined["config"]
        self.rejoined = bool(joined.get("proposal", 1) > 1)
        self._plane = str(self.cfg.get("data_plane", "chain"))
        if self._plane == "chain":
            self.comms.set_policy(
                str(self.cfg.get("codec", "dense")),
                float(self.cfg.get("bucket_mb", 4.0)),
                {k: float(self.cfg[k]) for k in
                 ("threshold", "min_threshold", "threshold_step",
                  "capacity_fraction") if k in self.cfg})
        else:
            # star: gradient bytes go through the coordinator; no peer
            # listener needed
            self.comms.close()
            self.comms = None
        if self.port_file:
            tmp = self.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{os.getpid()}\n")
            os.replace(tmp, self.port_file)

        # lease alive BEFORE the expensive part: building + jitting the
        # model can outlast evict_after on a contended host (N workers
        # compiling concurrently), and a worker evicted mid-compile never
        # even reaches its first step
        hb = threading.Thread(target=self._hb_loop, name="cluster-hb",
                              daemon=True)
        hb.start()

        from deeplearning4j_tpu.serving.replica import build_model
        self.net = build_model(self.cfg["model"])
        self._build_programs()
        chaos = WorkerChaos.from_env()
        try:
            self._resync(joined.get("proposal"))
            self._use_jax_collectives = self._probe_jax_collectives()
            total = int(self.cfg["total_steps"])
            while self.step < total:
                directive, proposal, evicted = self.box.snapshot()
                if evicted:
                    raise EvictedError(f"{self.worker_id} lease lost")
                if directive == "rollback":
                    if self._stale_rollback(proposal):
                        # late echo of a reform we already synced past —
                        # acting on it would tear down a healthy chain
                        self.box.absorb({"directive": "none",
                                         "proposal": None,
                                         "generation": self.generation})
                        continue
                    self._use_jax_collectives = False
                    self._resync(proposal)
                    continue
                try:
                    self._train_step(chaos)
                except FencedError as e:
                    self._log(f"fenced at step {self.step}: {e}")
                    self._use_jax_collectives = False
                    self._resync(e.proposal)
                except CommsError as e:
                    # the peer chain tore mid-step (a SIGKILLed neighbor,
                    # or our abort on a rollback directive): wait for the
                    # coordinator's verdict, then walk the normal resync
                    proposal = self._await_reform(f"step {self.step}: {e}")
                    self._use_jax_collectives = False
                    self._resync(proposal)
            self._finish()
            return 0
        except EvictedError as e:
            self._log(f"evicted: {e}")
            return 3
        finally:
            self._stop_hb.set()
            if self.comms is not None:
                self.comms.close()

    def _finish(self) -> None:
        comms = {"data_plane": self._plane,
                 "codec": (self.comms.codec if self.comms is not None
                           else "dense"),
                 "comm_seconds": round(self._comm_seconds, 4),
                 "step_seconds": round(self._step_seconds, 4)}
        if self.comms is not None:
            comms["bytes_sent"] = self.comms.bytes_sent
            comms["bytes_recv"] = self.comms.bytes_recv
            comms["compression_ratio"] = self.comms.last.get(
                "compression_ratio", 1.0)
            comms["residual_resets"] = (
                self.comms.codec_state.resets
                if self.comms.codec_state is not None else 0)
        else:
            comms["bytes_sent"] = self._star_sent
            comms["bytes_recv"] = self._star_recv
            comms["compression_ratio"] = 1.0
        payload = {"worker_id": self.worker_id, "rank": self.rank,
                   "world": self.world, "generation": self.generation,
                   "steps": self.step, "iteration": self.net.iteration,
                   "final_loss": self.last_loss,
                   "params_digest": params_digest(self.net.params),
                   "aot_restored": self.aot_restored,
                   "rejoined": self.rejoined,
                   "comms": comms}
        self.client.result(payload)
        self._log(f"done digest={payload['params_digest']} "
                  f"loss={self.last_loss}")
        # hold the lease until every live member reported, so a slightly
        # slower peer is not evicted into a pointless terminal reform
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            try:
                if self.client.state().get("phase") == "done":
                    return
            except Exception:   # noqa: BLE001 — coordinator going away is fine
                return
            time.sleep(0.1)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="elastic DP training worker")
    p.add_argument("--coordinator", required=True,
                   help="ElasticCoordinator base URL")
    p.add_argument("--worker-id", required=True)
    p.add_argument("--rank", type=int, default=None,
                   help="informational spawn rank (committed rank is "
                        "assigned by the coordinator at each generation)")
    p.add_argument("--port-file", default=None,
                   help="written with this worker's pid after a "
                        "successful join (the spawn handshake)")
    args = p.parse_args(argv)
    try:
        return ElasticWorker(args.coordinator, args.worker_id,
                             port_file=args.port_file).run()
    except (ClusterFullError,) as e:
        print(f"CLUSTER[{args.worker_id}] fatal: {e}", flush=True)
        return 4
    except Exception as e:      # noqa: BLE001 — setup/config failures
        import traceback
        traceback.print_exc()
        print(f"CLUSTER[{args.worker_id}] fatal: {e}", flush=True)
        return 5


if __name__ == "__main__":
    sys.exit(main())
