"""Per-backend kernel autotune harness.

KERNELS_TPU.json ships v5e numbers; any other backend (a different TPU
generation, CPU interpret runs) inherits routing decisions measured on
hardware it is not running on. This module closes that gap: on first
use per (kernel, shape, dtype) — gated behind ``DL4JTPU_AUTOTUNE=1`` so
CPU test runs never benchmark — it measures kernel-vs-reference for
BOTH phases on the actual backend, persists the rows next to the
persistent compile cache (``<cache_dir>/autotune_<backend>.json``, same
resolution as util/compile_cache.py), and merges them into the
exec/routing.py measured tables, where they override the shipped file.

The measurement contract matches bench_kernels exactly — rows use the
KERNELS_TPU.json ``results`` schema, so ``routing.load_measurements``
absorbs a persisted autotune table and the shipped file identically,
and ``tools/autotune.py`` can sweep shapes offline and pre-warm the
table for a fleet.

Timing: jitted closures per side, one warmup dispatch, then
min-over-iters of ``block_until_ready`` wall time (min is robust to
co-tenant noise; the same discipline bench.py uses).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Optional

_attempted = set()        # (kernel, shape_key) measurement already tried
_in_progress = False      # re-entrance guard: measuring calls the kernels,
                          # which ask routing, which must not re-enter here


def _metrics():
    from deeplearning4j_tpu.monitor.metrics import get_registry
    reg = get_registry()
    return (reg.counter("dl4jtpu_autotune_measurements_total",
                        "Kernel-vs-reference autotune measurements run "
                        "(first use per kernel/shape/dtype/backend).",
                        ("kernel",)),
            reg.gauge("dl4jtpu_autotune_table_rows",
                      "Rows in the persisted per-backend autotune table."))


def backend_name() -> str:
    import jax
    return jax.default_backend()


def table_path(backend: Optional[str] = None) -> str:
    """The persisted table for ``backend``, next to the persistent
    compile cache (same resolution: ``DL4JTPU_JAX_CACHE`` env else
    ``.jax_cache`` at the repo root)."""
    from pathlib import Path
    d = (os.environ.get("DL4JTPU_JAX_CACHE")
         or str(Path(__file__).resolve().parents[2] / ".jax_cache"))
    return os.path.join(d, f"autotune_{backend or backend_name()}.json")


def load_table(path: Optional[str] = None) -> list:
    path = path or table_path()
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return json.load(f).get("results", [])


def _row_key(row) -> tuple:
    if row.get("kernel") == "flash_attention":
        return ("flash_attention", row.get("BH"), row.get("T"),
                row.get("Dh"), bool(row.get("causal")))
    return (row.get("kernel"), row.get("B"), row.get("T"), row.get("H"),
            row.get("dtype"))


def save_rows(rows, path: Optional[str] = None) -> str:
    """Merge ``rows`` into the persisted table (by shape identity, new
    rows win) with an atomic replace — concurrent processes lose an
    update at worst, never corrupt the file."""
    path = path or table_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    merged = {_row_key(r): r for r in load_table(path)}
    for r in rows:
        merged[_row_key(r)] = r
    out = sorted(merged.values(), key=lambda r: json.dumps(r, sort_keys=True))
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump({"backend": os.path.basename(path)
                       .removeprefix("autotune_").removesuffix(".json"),
                       "results": out}, f, indent=1)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        _, rows_gauge = _metrics()
        rows_gauge.set(len(out))
    except Exception:
        pass
    return path


def load_persisted_into_routing(path: Optional[str] = None) -> int:
    """Feed the persisted table into exec/routing.py's measured tables.
    Called lazily by routing's first lookup; returns rows absorbed."""
    from deeplearning4j_tpu.exec import routing
    rows = load_table(path)
    kernels = {r.get("kernel") for r in rows} - {None}
    return sum(routing.load_measurements(rows, kernel=k)
               for k in sorted(kernels))


# ------------------------------------------------------------- measurement

def _time_us(fn, args, iters: int) -> float:
    import jax
    out = fn(*args)                      # warmup: compile + first dispatch
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e6


def _speed(ref_us: float, ker_us: float) -> Optional[float]:
    if not ker_us:
        return None
    return round(ref_us / ker_us, 2)


def measure_fused_lstm(b: int, t: int, h: int, dtype: str = "float32",
                       iters: int = 3,
                       interpret: Optional[bool] = None) -> Optional[dict]:
    """Measure the fused-LSTM Pallas kernel against its lax.scan
    reference, forward AND backward, at one shape. Returns a
    KERNELS_TPU.json-schema row, or None when the compiled kernel does
    not support the shape (nothing to route)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops import lstm_pallas as lp

    if interpret is None:
        interpret = backend_name() != "tpu"
    dt = jnp.dtype(dtype)
    if not lp.supported(b, t, h, dt.itemsize, interpret=interpret):
        return None
    ks = jax.random.split(jax.random.PRNGKey(0), 6)
    gate_in = jax.random.normal(ks[0], (t, b, 4 * h), dt)
    rw = jax.random.normal(ks[1], (h, 4 * h), dt) * 0.1
    h0 = jax.random.normal(ks[2], (b, h), dt)
    c0 = jax.random.normal(ks[3], (b, h), dt)

    fwd_p = jax.jit(lambda gi, rw, h0, c0: lp._fwd_call(
        gi, rw, h0, c0, interpret=interpret, save_reserve=True)[0])
    fwd_s = jax.jit(lambda gi, rw, h0, c0: lp._scan_fwd(
        gi, rw, h0, c0, save_reserve=True)[0])
    fwd_us = _time_us(fwd_p, (gate_in, rw, h0, c0), iters)
    fwd_scan_us = _time_us(fwd_s, (gate_in, rw, h0, c0), iters)

    # backward: same residuals both sides (the scan fwd emits the exact
    # reserve-space contract the kernels share)
    hs, tc, cprev, gates, _ = lp._scan_fwd(gate_in, rw, h0, c0,
                                           save_reserve=True)
    dhs = jax.random.normal(ks[4], (t, b, h), dt)
    dcT = jax.random.normal(ks[5], (b, h), dt)
    bwd_p = jax.jit(lambda g, tc, cp, rw, dhs, dcT: lp._bwd_call(
        g, tc, cp, rw, dhs, dcT, interpret=interpret)[0])
    bwd_s = jax.jit(lambda g, tc, cp, rw, dhs, dcT: lp._scan_bwd(
        g, tc, cp, rw, dhs, dcT)[0])
    grad_us = _time_us(bwd_p, (gates, tc, cprev, rw, dhs, dcT), iters)
    grad_scan_us = _time_us(bwd_s, (gates, tc, cprev, rw, dhs, dcT), iters)

    return {"kernel": "fused_lstm", "B": b, "T": t, "H": h,
            "dtype": str(dt),
            "fwd_us": round(fwd_us, 1), "fwd_scan_us": round(fwd_scan_us, 1),
            "fwd_speedup": _speed(fwd_scan_us, fwd_us),
            "grad_us": round(grad_us, 1),
            "grad_scan_us": round(grad_scan_us, 1),
            "grad_speedup": _speed(grad_scan_us, grad_us),
            "backend": backend_name(), "autotuned": True}


def measure_flash_attention(bh: int, t: int, dh: int, causal: bool = False,
                            iters: int = 3,
                            interpret: Optional[bool] = None) \
        -> Optional[dict]:
    """Measure the flash-attention kernel against the dense XLA
    softmax-attention reference, forward and grad."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops import flash_attention as fa

    if interpret is None:
        interpret = backend_name() != "tpu"
    if not fa.supported(t, dh):
        return None
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (bh, t, dh), jnp.float32)
    k = jax.random.normal(ks[1], (bh, t, dh), jnp.float32)
    v = jax.random.normal(ks[2], (bh, t, dh), jnp.float32)

    def dense(q, k, v):
        s = jnp.einsum("btd,bsd->bts", q, k) / (dh ** 0.5)
        if causal:
            tt = jnp.arange(t)
            s = jnp.where(tt[:, None] >= tt[None, :], s, -jnp.inf)
        return jnp.einsum("bts,bsd->btd", jax.nn.softmax(s, axis=-1), v)

    flash = lambda q, k, v: fa.flash_attention(q, k, v, causal, interpret)
    fwd_us = _time_us(jax.jit(flash), (q, k, v), iters)
    fwd_ref_us = _time_us(jax.jit(dense), (q, k, v), iters)
    g_fl = jax.jit(jax.grad(lambda q, k, v: flash(q, k, v).sum(),
                            argnums=(0, 1, 2)))
    g_de = jax.jit(jax.grad(lambda q, k, v: dense(q, k, v).sum(),
                            argnums=(0, 1, 2)))
    grad_us = _time_us(g_fl, (q, k, v), iters)
    grad_ref_us = _time_us(g_de, (q, k, v), iters)

    return {"kernel": "flash_attention", "BH": bh, "T": t, "Dh": dh,
            "causal": bool(causal),
            "fwd_us": round(fwd_us, 1), "fwd_ref_us": round(fwd_ref_us, 1),
            "fwd_speedup": _speed(fwd_ref_us, fwd_us),
            "grad_us": round(grad_us, 1),
            "grad_ref_us": round(grad_ref_us, 1),
            "grad_speedup": _speed(grad_ref_us, grad_us),
            "backend": backend_name(), "autotuned": True}


# --------------------------------------------------------- first-use hook

def ensure_measured(kernel: str, shape_key: tuple) -> Optional[str]:
    """Routing's first-use hook (DL4JTPU_AUTOTUNE=1): measure this shape
    on the actual backend, persist + merge the row, and return the
    fresh route for the asked phase — or None when the shape was
    already attempted, is unsupported, or a measurement is running
    (re-entrance: the measurement itself calls the kernels)."""
    global _in_progress
    if _in_progress or (kernel, shape_key) in _attempted:
        return None
    _attempted.add((kernel, shape_key))
    from deeplearning4j_tpu.exec import routing
    _in_progress = True
    try:
        if kernel in ("fused_lstm_fwd", "fused_lstm_grad"):
            b, t, h, dtype = shape_key
            row = measure_fused_lstm(b, t, h, dtype)
            if row is None:
                return None
            save_rows([row])
            routing.load_measurements([row], kernel="fused_lstm")
            table = (routing._MEASURED if kernel == "fused_lstm_fwd"
                     else routing._MEASURED_GRAD)
            route = table.get(("fused_lstm", b, t, h, str(dtype)))
        elif kernel == "flash_attention":
            bh, t, dh, causal, train = shape_key
            row = measure_flash_attention(bh, t, dh, causal)
            if row is None:
                return None
            save_rows([row])
            routing.load_measurements([row], kernel="flash_attention")
            phases = ("fwd", "grad") if train else ("fwd",)
            hits = [routing._FLASH_MEASURED.get((ph, bh, t, dh,
                                                 bool(causal)))
                    for ph in phases]
            route = ("scan" if any(h == "scan" for h in hits)
                     else "pallas" if all(h == "pallas" for h in hits)
                     else None)
        else:
            return None
        try:
            meas, _ = _metrics()
            meas.labels(kernel=kernel).inc()
        except Exception:
            pass
        return route
    finally:
        _in_progress = False


def sweep(lstm_shapes=(), flash_shapes=(), iters: int = 3,
          interpret: Optional[bool] = None,
          path: Optional[str] = None) -> list:
    """Measure a batch of shapes and persist them in one table write
    (the tools/autotune.py CLI entry point). ``lstm_shapes``: iterable
    of (B, T, H, dtype); ``flash_shapes``: (BH, T, Dh, causal)."""
    rows = []
    for b, t, h, dtype in lstm_shapes:
        row = measure_fused_lstm(b, t, h, dtype, iters=iters,
                                 interpret=interpret)
        if row is not None:
            rows.append(row)
    for bh, t, dh, causal in flash_shapes:
        row = measure_flash_attention(bh, t, dh, causal, iters=iters,
                                      interpret=interpret)
        if row is not None:
            rows.append(row)
    if rows:
        save_rows(rows, path=path)
    return rows
