"""Mesh construction for the execution core (docs/SHARDING.md).

One place decides what the device mesh looks like; every compile site
(train step, ``fit_scan``, bucketed serving, incremental decode) builds
its ``NamedSharding`` specs against the SAME two named axes:

- ``data``  — batch / slot dimension shards here (pure DP by default);
- ``model`` — Megatron-style tensor parallelism (weight output/input
  dims); size 1 unless explicitly requested, so the default mesh is
  pure data-parallel over ``jax.devices()``.

Single-device processes get a 1x1 mesh and the executor collapses to a
plain ``jax.jit`` (the mesh=1 special case — zero new XLA programs, the
trace-count tests pin this).

The mesh can be shaped without code changes via ``DL4JTPU_MESH``:

    DL4JTPU_MESH=off            # force single-device execution
    DL4JTPU_MESH=data=4,model=2 # explicit axis sizes (product must
                                # divide the visible device count)
    DL4JTPU_MESH=model=2        # data axis absorbs the rest

CPU CI gets multiple devices by setting
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` BEFORE jax
initializes; ``host_device_env`` composes that flag into a subprocess
environment without perturbing the current process (tests/conftest.py
``mesh8`` and the bench sharded rows use it).
"""

import os
from typing import Optional

import numpy as np
import jax
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"

_HOST_COUNT_FLAG = "--xla_force_host_platform_device_count"

_default_mesh: Optional[Mesh] = None


def build_mesh(devices=None, model_parallel: int = 1) -> Mesh:
    """A 2-D ``(data, model)`` mesh over ``devices`` (default: all).

    ``model_parallel`` must divide the device count; the data axis
    absorbs the rest.
    """
    devs = list(jax.devices()) if devices is None else list(devices)
    m = max(1, int(model_parallel))
    if len(devs) % m:
        raise ValueError(
            f"model_parallel={m} does not divide {len(devs)} devices")
    return Mesh(np.array(devs).reshape(len(devs) // m, m),
                (DATA_AXIS, MODEL_AXIS))


def _publish_gauges(mesh: Mesh) -> None:
    from deeplearning4j_tpu.monitor.metrics import get_registry
    reg = get_registry()
    reg.gauge(
        "dl4jtpu_mesh_devices",
        "Devices in the execution mesh (1 = single-device special case)."
    ).set(mesh.size)
    ax = reg.gauge(
        "dl4jtpu_mesh_axis_size",
        "Size of each named mesh axis (batch shards over 'data', "
        "Megatron TP over 'model').", ("axis",))
    for name in mesh.axis_names:
        ax.labels(axis=name).set(mesh.shape[name])


def _mesh_from_env(spec: str) -> Mesh:
    spec = spec.strip().lower()
    if spec in ("off", "1", "single", "none"):
        return build_mesh(jax.devices()[:1])
    sizes = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        k, _, v = part.partition("=")
        sizes[k.strip()] = int(v)
    n = len(jax.devices())
    model = sizes.get(MODEL_AXIS, 1)
    data = sizes.get(DATA_AXIS, max(1, n // max(1, model)))
    want = data * model
    if want > n or n % want:
        raise ValueError(
            f"DL4JTPU_MESH={spec!r} needs {want} devices, have {n}")
    return build_mesh(jax.devices()[:want], model_parallel=model)


def default_mesh() -> Mesh:
    """The process-wide mesh: all visible devices, pure DP, unless
    ``DL4JTPU_MESH`` or ``set_default_mesh`` says otherwise."""
    global _default_mesh
    if _default_mesh is None:
        env = os.environ.get("DL4JTPU_MESH", "").strip()
        _default_mesh = _mesh_from_env(env) if env else build_mesh()
        _publish_gauges(_default_mesh)
    return _default_mesh


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    """Override (or with None, reset) the process default mesh. Drops
    the cached default executor so the next compile sees the new mesh;
    programs already compiled keep their old placement."""
    global _default_mesh
    _default_mesh = mesh
    if mesh is not None:
        _publish_gauges(mesh)
    from deeplearning4j_tpu.exec import executor as _ex
    _ex._invalidate_default()


def host_device_env(n: int = 8, base=None) -> dict:
    """Environment for a SUBPROCESS that should see ``n`` virtual CPU
    devices. The host-device-count flag only takes effect before jax
    initializes, so it cannot be flipped in-process — composing it into
    a child environment is the subprocess-safe way (the parent's device
    state is untouched)."""
    env = dict(os.environ if base is None else base)
    flags = [t for t in env.get("XLA_FLAGS", "").split()
             if not t.startswith(_HOST_COUNT_FLAG)]
    flags.append(f"{_HOST_COUNT_FLAG}={int(n)}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    return env
