"""Shape-keyed kernel-vs-reference routing (data-driven, overridable).

A hand-written kernel does not win everywhere: KERNELS_TPU.json
(bench_kernels, v5e) shows the fused-LSTM forward LOSING to XLA's scan
codegen at small ``B*H`` for BOTH dtypes (bf16 (4,16,8) runs at 0.1x,
(1,4,8) at 0.03x) and on two f32 shapes the old ``B*H >= 2048``
heuristic routed to Pallas anyway:

    (16, 64, 128, float32)  fwd 0.96x   — crossover shape, scan wins
    (32, 128, 256, float32) fwd 0.72x   — long-T f32: double-width
                                          streams, scan pipelines better

This module owns the routing decision per (backend, kernel, phase,
shape). The shipped measurement file (KERNELS_TPU.json at the repo
root) is absorbed wholesale at first use — every row with a measured
``fwd_speedup`` routes the forward to pallas iff it beat XLA, and every
row with a measured ``grad_route``/``grad_speedup`` routes the BACKWARD
the same way (the backward kernel wins at most validated shapes, but
two measured bf16 rows lose — (4,16,8) 0.24x, (8,32,120) 0.4x — so the
backward is measurement-routed exactly like the forward, with pallas as
the no-data default). Tables produced by the per-backend autotune
harness (exec/autotune.py — persisted next to the compile cache) merge
on top of the shipped file, so first-use measurements on the actual
backend override v5e numbers.

Overrides, strongest first:

1. ``set_route(kernel, "pallas"|"scan"|None)`` — programmatic pin
   (per kernel: "fused_lstm", "fused_lstm_grad", "decode_attn",
   "flash_attn")
2. ``DL4JTPU_LSTM_FWD_ROUTE`` / ``DL4JTPU_LSTM_GRAD_ROUTE`` /
   ``DL4JTPU_DECODE_ATTN_ROUTE`` / ``DL4JTPU_FLASH_ATTN_ROUTE`` —
   environment pins
3. measured per-shape table (exact (B, T, H, dtype) match, seeded from
   the shipped KERNELS_TPU.json via ``load_measurements`` plus any
   persisted autotune table)
4. heuristic: scan when ``B*H < 2048``; f32 additionally needs
   ``B*H > 2048`` and ``T < 128`` (both measured f32 losses above sit
   on those boundaries); otherwise pallas.  The backward defaults to
   pallas (it wins at every validated shape the heuristic covers).

The flash decode-step kernel (ops/flash_decode.py) routes through the
same table: ``decode_attn_route`` defaults to pallas wherever the
kernel supports the shape (the decode step is bandwidth-bound on the
KV cache at every capacity, and the kernel reads only ``pos+1`` of the
``C`` cached rows), with the same pin/env overrides for tests and
rollbacks.

The flash-attention training/inference forward (ops/flash_attention.py)
routes via ``flash_attn_route``: 'pallas' means the flash kernel,
'scan' means the dense XLA softmax-attention path (same vocabulary as
``decode_attn_route``). Training asks for BOTH phases — the custom-vjp
kernel commits forward and backward together, so a shape where the
measured backward loses stays dense even if the forward wins.
"""

import json
import os
from typing import Dict, Optional

# exact measured rows where the decision differs per shape. Seeded from
# the shipped KERNELS_TPU.json on first lookup (``load_measurements``
# absorbs every measured row — bf16 exactly like f32); the literal
# entries below keep the module meaningful without the file and remain
# human-auditable.
_MEASURED = {
    # (kernel, B, T, H, dtype) -> route        measured fwd speedup
    ("fused_lstm", 16, 64, 128, "float32"): "scan",     # 0.96x
    ("fused_lstm", 16, 64, 128, "bfloat16"): "pallas",  # 1.23x
    ("fused_lstm", 32, 128, 256, "float32"): "scan",    # 0.72x
    ("fused_lstm", 32, 128, 256, "bfloat16"): "pallas",  # 1.23x
    ("fused_lstm", 32, 64, 256, "float32"): "pallas",   # 1.19x
    ("fused_lstm", 64, 32, 512, "float32"): "pallas",   # 1.07x
}

# backward-phase table, same key schema. The two literal rows are the
# measured v5e LOSSES (every other validated shape wins — see the
# grad_speedup column of KERNELS_TPU.json); the default is pallas.
_MEASURED_GRAD = {
    ("fused_lstm", 4, 16, 8, "bfloat16"): "scan",     # 0.24x
    ("fused_lstm", 8, 32, 120, "bfloat16"): "scan",   # 0.40x
}

# flash-attention table: (phase, BH, T, Dh, causal) -> route. Seeded
# from the shipped file's flash_attention rows at first lookup.
_FLASH_MEASURED: Dict[tuple, str] = {}

# measured latency/bandwidth crossover (see ops/lstm_pallas.py docstring)
_MIN_BH = 2048

_forced: Dict[str, str] = {}      # kernel -> pinned route
_file_loaded = False


def set_route(kernel: str, route: Optional[str]) -> None:
    """Pin every ``kernel`` decision to ``route`` ('pallas'/'scan' — for
    ``decode_attn``/``flash_attn``, 'scan' means the dense reference
    path), or None to restore data-driven routing. Kernels:
    "fused_lstm" (forward), "fused_lstm_grad" (backward),
    "decode_attn", "flash_attn". Test/debug hook."""
    if route not in (None, "pallas", "scan"):
        raise ValueError(f"route must be pallas/scan/None, got {route!r}")
    if route is None:
        _forced.pop(kernel, None)
    else:
        _forced[kernel] = route


def _grad_decision(row) -> Optional[str]:
    """A row's backward route: explicit ``grad_route`` wins, else the
    measured ``grad_speedup`` decides (pallas iff it beat the scan)."""
    gr = row.get("grad_route")
    if gr in ("pallas", "scan"):
        return gr
    gs = row.get("grad_speedup")
    if gs is None:
        return None
    return "pallas" if gs > 1 else "scan"


def load_measurements(results, kernel: str = "fused_lstm") -> int:
    """Merge bench rows (KERNELS_TPU.json ``results`` schema) into the
    tables: a row routes its forward to pallas iff its measured
    ``fwd_speedup`` > 1, and its backward by ``grad_route`` /
    ``grad_speedup`` the same way. Returns the number of rows absorbed
    (a row counts once even when it feeds both phases)."""
    n = 0
    for row in results:
        if row.get("kernel") != kernel:
            continue
        if kernel == "flash_attention":
            key = (row.get("BH"), row.get("T"), row.get("Dh"),
                   bool(row.get("causal")))
            hit = False
            if row.get("fwd_speedup") is not None:
                _FLASH_MEASURED[("fwd",) + key] = \
                    "pallas" if row["fwd_speedup"] > 1 else "scan"
                hit = True
            grad = _grad_decision(row)
            if grad is not None:
                _FLASH_MEASURED[("grad",) + key] = grad
                hit = True
            n += 1 if hit else 0
            continue
        key = (kernel, row.get("B"), row.get("T"), row.get("H"),
               row.get("dtype"))
        hit = False
        if row.get("fwd_speedup") is not None:
            _MEASURED[key] = "pallas" if row["fwd_speedup"] > 1 else "scan"
            hit = True
        grad = _grad_decision(row)
        if grad is not None:
            _MEASURED_GRAD[key] = grad
            hit = True
        n += 1 if hit else 0
    return n


def load_measurements_file(path: Optional[str] = None) -> int:
    """Absorb a KERNELS_TPU.json bench file (default: the one shipped at
    the repo root) for every kernel it measures. Idempotent; rows merge
    into the same table ``load_measurements`` feeds."""
    if path is None:
        here = os.path.dirname(os.path.abspath(__file__))
        path = os.path.join(os.path.dirname(os.path.dirname(here)),
                            "KERNELS_TPU.json")
    if not os.path.exists(path):
        return 0
    with open(path) as f:
        results = json.load(f).get("results", [])
    kernels = {r.get("kernel") for r in results} - {None}
    return sum(load_measurements(results, kernel=k) for k in sorted(kernels))


def _ensure_file_measurements() -> None:
    """Lazy one-shot load of the shipped measurement file PLUS any
    persisted autotune table for the current backend (the autotune rows
    merge last, so first-use measurements on the actual hardware
    override the shipped v5e numbers)."""
    global _file_loaded
    if not _file_loaded:
        _file_loaded = True
        load_measurements_file()
        try:
            from deeplearning4j_tpu.exec import autotune
            autotune.load_persisted_into_routing()
        except Exception:
            pass        # a corrupt table must never take down routing


def _reset_measurement_cache() -> None:
    """Forget the lazy-load latch (tests re-point the autotune table)."""
    global _file_loaded
    _file_loaded = False


def _maybe_autotune(kernel: str, shape_key: tuple) -> Optional[str]:
    """First-use measurement hook: when DL4JTPU_AUTOTUNE is on and the
    tables have no row for this shape, measure kernel-vs-reference on
    the actual backend, persist, and return the fresh route (None when
    autotuning is off or the measurement could not run)."""
    if os.environ.get("DL4JTPU_AUTOTUNE", "").strip().lower() \
            not in ("1", "true", "on", "yes"):
        return None
    try:
        from deeplearning4j_tpu.exec import autotune
        return autotune.ensure_measured(kernel, shape_key)
    except Exception:
        return None


def lstm_fwd_route(b: int, h: int, t: Optional[int] = None,
                   dtype: Optional[str] = None,
                   backend: Optional[str] = None) -> str:
    """Route the fused-LSTM forward for one shape: 'pallas' or 'scan'.

    ``backend`` other than TPU always scans (the kernel only compiles
    for Mosaic; CPU/interpret callers gate on that before asking)."""
    forced = _forced.get("fused_lstm")
    if forced is not None:
        return forced
    env = os.environ.get("DL4JTPU_LSTM_FWD_ROUTE", "").strip().lower()
    if env in ("pallas", "scan"):
        return env
    if backend is not None and backend != "tpu":
        return "scan"
    if t is not None and dtype is not None:
        _ensure_file_measurements()
        hit = _MEASURED.get(("fused_lstm", b, t, h, str(dtype)))
        if hit is not None:
            return hit
        hit = _maybe_autotune("fused_lstm_fwd", (b, t, h, str(dtype)))
        if hit is not None:
            return hit
    if b * h < _MIN_BH:
        return "scan"
    if str(dtype) == "float32" and (b * h <= _MIN_BH
                                    or (t is not None and t >= 128)):
        return "scan"
    return "pallas"


def lstm_grad_route(b: int, h: int, t: Optional[int] = None,
                    dtype: Optional[str] = None,
                    backend: Optional[str] = None) -> str:
    """Route the fused-LSTM backward for one shape: 'pallas' (the
    reverse-grid kernel) or 'scan' (the equivalent reverse lax.scan,
    ops/lstm_pallas.py ``_scan_bwd``). Default is pallas — the backward
    kernel wins at every validated shape except the measured bf16
    losses in the table — with the same pin/env/measured precedence as
    the forward."""
    forced = _forced.get("fused_lstm_grad")
    if forced is not None:
        return forced
    env = os.environ.get("DL4JTPU_LSTM_GRAD_ROUTE", "").strip().lower()
    if env in ("pallas", "scan"):
        return env
    if backend is not None and backend != "tpu":
        return "scan"
    if t is not None and dtype is not None:
        _ensure_file_measurements()
        hit = _MEASURED_GRAD.get(("fused_lstm", b, t, h, str(dtype)))
        if hit is not None:
            return hit
        hit = _maybe_autotune("fused_lstm_grad", (b, t, h, str(dtype)))
        if hit is not None:
            return hit
    return "pallas"


def flash_attn_route(bh: int, t: int, dh: int, causal: bool,
                     train: bool = False,
                     backend: Optional[str] = None,
                     min_t: int = 4096) -> str:
    """Route the flash-attention forward at the layer seam: 'pallas'
    (ops/flash_attention.py) or 'scan' (the dense XLA path).

    ``train=True`` commits the custom-vjp pair, so the decision needs
    BOTH phases to win: a measured 'scan' on either the fwd or grad row
    keeps the shape dense. Without measurements the seam falls back to
    the ``t >= min_t`` crossover (MIN_SEQ_FOR_AUTO_ROUTE, measured on
    v5e — the caller passes 0 in interpret mode so CPU tests exercise
    the kernel at any length)."""
    forced = _forced.get("flash_attn")
    if forced is not None:
        return forced
    env = os.environ.get("DL4JTPU_FLASH_ATTN_ROUTE", "").strip().lower()
    if env in ("pallas", "scan"):
        return env
    if backend is not None and backend != "tpu":
        return "scan"
    if backend == "tpu":
        # measured rows only steer REAL compiled routing; interpret-mode
        # callers (backend=None) keep the deterministic min_t gate so the
        # CPU parity tests always exercise the kernel
        _ensure_file_measurements()
        key = (bh, t, dh, bool(causal))
        phases = ("fwd", "grad") if train else ("fwd",)
        hits = [_FLASH_MEASURED.get((ph,) + key) for ph in phases]
        if any(h == "scan" for h in hits):
            return "scan"
        if all(h == "pallas" for h in hits):
            return "pallas"
        hit = _maybe_autotune("flash_attention",
                              (bh, t, dh, bool(causal), bool(train)))
        if hit is not None:
            return hit
    return "pallas" if t >= min_t else "scan"


def decode_attn_route(c: Optional[int] = None, dh: Optional[int] = None,
                      backend: Optional[str] = None,
                      paged: bool = False) -> str:
    """Route the attention decode step: 'pallas' (flash decode-step
    kernel, ops/flash_decode.py) or 'scan' (the dense reference step —
    the path the bitwise-parity decode tests pin on CPU).

    ``paged=True`` asks for the block-table-gather variant
    (``flash_decode_step_paged``): same decision surface — the one
    ``decode_attn`` pin and ``DL4JTPU_DECODE_ATTN_ROUTE`` env apply to
    both, so a rollback or test pin flips the dense and paged engines
    together ('scan' means gather-then-dense-math there, the parity
    oracle).

    Default is pallas wherever the kernel supports the shape: the step
    is HBM-bound on the KV cache and the kernel stops reading at the
    cache position, so it wins by construction once the cache is larger
    than one block (the caller screens ``supported(c, dh)`` /
    ``supported_paged(block_size, dh)`` first)."""
    forced = _forced.get("decode_attn")
    if forced is not None:
        return forced
    env = os.environ.get("DL4JTPU_DECODE_ATTN_ROUTE", "").strip().lower()
    if env in ("pallas", "scan"):
        return env
    if backend is not None and backend != "tpu":
        return "scan"
    return "pallas"
