"""Shape-keyed kernel-vs-scan routing (data-driven, overridable).

The fused-LSTM Pallas kernel does not win everywhere: KERNELS_TPU.json
(bench_kernels, v5e) shows the forward LOSING to XLA's scan codegen at
small ``B*H`` (latency-bound — (4,16,8) runs at 0.1x) and on two shapes
the old ``B*H >= 2048`` heuristic routed to Pallas anyway:

    (16, 64, 128, float32)  fwd 0.96x   — crossover shape, scan wins
    (32, 128, 256, float32) fwd 0.72x   — long-T f32: double-width
                                          streams, scan pipelines better

This module owns the routing decision per (backend, kernel, phase,
shape): exact measured shapes first (the table below is distilled from
KERNELS_TPU.json and can be re-derived with ``load_measurements``),
then the measured heuristic for everything in between. The backward
kernel wins at every validated shape, so only the forward routes.

Overrides, strongest first:

1. ``set_route("fused_lstm", "pallas"|"scan"|None)`` — programmatic pin
2. ``DL4JTPU_LSTM_FWD_ROUTE=pallas|scan`` — environment pin
3. measured per-shape table (exact (B, T, H, dtype) match)
4. heuristic: scan when ``B*H < 2048``; f32 additionally needs
   ``B*H > 2048`` and ``T < 128`` (both measured losses above sit on
   those boundaries); otherwise pallas
"""

import os
from typing import Optional

# exact measured rows where the decision differs per shape — distilled
# from KERNELS_TPU.json (only rows the heuristic alone would misroute
# need listing; kept small and human-auditable on purpose)
_MEASURED = {
    # (kernel, B, T, H, dtype) -> route        measured fwd speedup
    ("fused_lstm", 16, 64, 128, "float32"): "scan",     # 0.96x
    ("fused_lstm", 16, 64, 128, "bfloat16"): "pallas",  # 1.23x
    ("fused_lstm", 32, 128, 256, "float32"): "scan",    # 0.72x
    ("fused_lstm", 32, 128, 256, "bfloat16"): "pallas",  # 1.23x
    ("fused_lstm", 32, 64, 256, "float32"): "pallas",   # 1.19x
    ("fused_lstm", 64, 32, 512, "float32"): "pallas",   # 1.07x
}

# measured latency/bandwidth crossover (see ops/lstm_pallas.py docstring)
_MIN_BH = 2048

_forced: Optional[str] = None


def set_route(kernel: str, route: Optional[str]) -> None:
    """Pin every ``kernel`` forward to ``route`` ('pallas'/'scan'), or
    None to restore data-driven routing. Test/debug hook."""
    global _forced
    if route not in (None, "pallas", "scan"):
        raise ValueError(f"route must be pallas/scan/None, got {route!r}")
    _forced = route


def load_measurements(results, kernel: str = "fused_lstm") -> int:
    """Merge bench rows (KERNELS_TPU.json ``results`` schema) into the
    table: a row routes to pallas iff its measured ``fwd_speedup`` > 1.
    Returns the number of rows absorbed."""
    n = 0
    for row in results:
        if row.get("kernel") != kernel or row.get("fwd_speedup") is None:
            continue
        key = (kernel, row["B"], row["T"], row["H"], row["dtype"])
        _MEASURED[key] = "pallas" if row["fwd_speedup"] > 1 else "scan"
        n += 1
    return n


def lstm_fwd_route(b: int, h: int, t: Optional[int] = None,
                   dtype: Optional[str] = None,
                   backend: Optional[str] = None) -> str:
    """Route the fused-LSTM forward for one shape: 'pallas' or 'scan'.

    ``backend`` other than TPU always scans (the kernel only compiles
    for Mosaic; CPU/interpret callers gate on that before asking)."""
    if _forced is not None:
        return _forced
    env = os.environ.get("DL4JTPU_LSTM_FWD_ROUTE", "").strip().lower()
    if env in ("pallas", "scan"):
        return env
    if backend is not None and backend != "tpu":
        return "scan"
    if t is not None and dtype is not None:
        hit = _MEASURED.get(("fused_lstm", b, t, h, str(dtype)))
        if hit is not None:
            return hit
    if b * h < _MIN_BH:
        return "scan"
    if str(dtype) == "float32" and (b * h <= _MIN_BH
                                    or (t is not None and t >= 128)):
        return "scan"
    return "pallas"
