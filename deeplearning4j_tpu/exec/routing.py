"""Shape-keyed kernel-vs-reference routing (data-driven, overridable).

A hand-written kernel does not win everywhere: KERNELS_TPU.json
(bench_kernels, v5e) shows the fused-LSTM forward LOSING to XLA's scan
codegen at small ``B*H`` for BOTH dtypes (bf16 (4,16,8) runs at 0.1x,
(1,4,8) at 0.03x) and on two f32 shapes the old ``B*H >= 2048``
heuristic routed to Pallas anyway:

    (16, 64, 128, float32)  fwd 0.96x   — crossover shape, scan wins
    (32, 128, 256, float32) fwd 0.72x   — long-T f32: double-width
                                          streams, scan pipelines better

This module owns the routing decision per (backend, kernel, phase,
shape). The shipped measurement file (KERNELS_TPU.json at the repo
root) is absorbed wholesale at first use — every row with a measured
``fwd_speedup`` routes to pallas iff it beat XLA, for f32 and bf16
alike — and the measured heuristic covers everything in between. The
backward kernel wins at every validated shape, so only the forward
routes.

Overrides, strongest first:

1. ``set_route(kernel, "pallas"|"scan"|None)`` — programmatic pin
   (per kernel: "fused_lstm", "decode_attn")
2. ``DL4JTPU_LSTM_FWD_ROUTE`` / ``DL4JTPU_DECODE_ATTN_ROUTE`` —
   environment pins
3. measured per-shape table (exact (B, T, H, dtype) match, seeded from
   the shipped KERNELS_TPU.json via ``load_measurements``)
4. heuristic: scan when ``B*H < 2048``; f32 additionally needs
   ``B*H > 2048`` and ``T < 128`` (both measured f32 losses above sit
   on those boundaries); otherwise pallas

The flash decode-step kernel (ops/flash_decode.py) routes through the
same table: ``decode_attn_route`` defaults to pallas wherever the
kernel supports the shape (the decode step is bandwidth-bound on the
KV cache at every capacity, and the kernel reads only ``pos+1`` of the
``C`` cached rows), with the same pin/env overrides for tests and
rollbacks.
"""

import json
import os
from typing import Dict, Optional

# exact measured rows where the decision differs per shape. Seeded from
# the shipped KERNELS_TPU.json on first lookup (``load_measurements``
# absorbs every measured row — bf16 exactly like f32); the literal
# entries below keep the module meaningful without the file and remain
# human-auditable.
_MEASURED = {
    # (kernel, B, T, H, dtype) -> route        measured fwd speedup
    ("fused_lstm", 16, 64, 128, "float32"): "scan",     # 0.96x
    ("fused_lstm", 16, 64, 128, "bfloat16"): "pallas",  # 1.23x
    ("fused_lstm", 32, 128, 256, "float32"): "scan",    # 0.72x
    ("fused_lstm", 32, 128, 256, "bfloat16"): "pallas",  # 1.23x
    ("fused_lstm", 32, 64, 256, "float32"): "pallas",   # 1.19x
    ("fused_lstm", 64, 32, 512, "float32"): "pallas",   # 1.07x
}

# measured latency/bandwidth crossover (see ops/lstm_pallas.py docstring)
_MIN_BH = 2048

_forced: Dict[str, str] = {}      # kernel -> pinned route
_file_loaded = False


def set_route(kernel: str, route: Optional[str]) -> None:
    """Pin every ``kernel`` forward to ``route`` ('pallas'/'scan' — for
    ``decode_attn``, 'scan' means the dense reference step), or None to
    restore data-driven routing. Test/debug hook."""
    if route not in (None, "pallas", "scan"):
        raise ValueError(f"route must be pallas/scan/None, got {route!r}")
    if route is None:
        _forced.pop(kernel, None)
    else:
        _forced[kernel] = route


def load_measurements(results, kernel: str = "fused_lstm") -> int:
    """Merge bench rows (KERNELS_TPU.json ``results`` schema) into the
    table: a row routes to pallas iff its measured ``fwd_speedup`` > 1.
    Returns the number of rows absorbed."""
    n = 0
    for row in results:
        if row.get("kernel") != kernel or row.get("fwd_speedup") is None:
            continue
        key = (kernel, row.get("B"), row.get("T"), row.get("H"),
               row.get("dtype"))
        _MEASURED[key] = "pallas" if row["fwd_speedup"] > 1 else "scan"
        n += 1
    return n


def load_measurements_file(path: Optional[str] = None) -> int:
    """Absorb a KERNELS_TPU.json bench file (default: the one shipped at
    the repo root) for every kernel it measures. Idempotent; rows merge
    into the same table ``load_measurements`` feeds."""
    if path is None:
        here = os.path.dirname(os.path.abspath(__file__))
        path = os.path.join(os.path.dirname(os.path.dirname(here)),
                            "KERNELS_TPU.json")
    if not os.path.exists(path):
        return 0
    with open(path) as f:
        results = json.load(f).get("results", [])
    kernels = {r.get("kernel") for r in results} - {None}
    return sum(load_measurements(results, kernel=k) for k in sorted(kernels))


def _ensure_file_measurements() -> None:
    """Lazy one-shot load of the shipped measurement file, so the per-shape
    choice is measurement-driven for every dtype it covers (the bf16
    small-shape losses included) without any caller wiring."""
    global _file_loaded
    if not _file_loaded:
        _file_loaded = True
        load_measurements_file()


def lstm_fwd_route(b: int, h: int, t: Optional[int] = None,
                   dtype: Optional[str] = None,
                   backend: Optional[str] = None) -> str:
    """Route the fused-LSTM forward for one shape: 'pallas' or 'scan'.

    ``backend`` other than TPU always scans (the kernel only compiles
    for Mosaic; CPU/interpret callers gate on that before asking)."""
    forced = _forced.get("fused_lstm")
    if forced is not None:
        return forced
    env = os.environ.get("DL4JTPU_LSTM_FWD_ROUTE", "").strip().lower()
    if env in ("pallas", "scan"):
        return env
    if backend is not None and backend != "tpu":
        return "scan"
    if t is not None and dtype is not None:
        _ensure_file_measurements()
        hit = _MEASURED.get(("fused_lstm", b, t, h, str(dtype)))
        if hit is not None:
            return hit
    if b * h < _MIN_BH:
        return "scan"
    if str(dtype) == "float32" and (b * h <= _MIN_BH
                                    or (t is not None and t >= 128)):
        return "scan"
    return "pallas"


def decode_attn_route(c: Optional[int] = None, dh: Optional[int] = None,
                      backend: Optional[str] = None) -> str:
    """Route the attention decode step: 'pallas' (flash decode-step
    kernel, ops/flash_decode.py) or 'scan' (the dense reference step —
    the path the bitwise-parity decode tests pin on CPU).

    Default is pallas wherever the kernel supports the shape: the step
    is HBM-bound on the KV cache and the kernel stops reading at the
    cache position, so it wins by construction once the cache is larger
    than one block (the caller screens ``supported(c, dh)`` first)."""
    forced = _forced.get("decode_attn")
    if forced is not None:
        return forced
    env = os.environ.get("DL4JTPU_DECODE_ATTN_ROUTE", "").strip().lower()
    if env in ("pallas", "scan"):
        return env
    if backend is not None and backend != "tpu":
        return "scan"
    return "pallas"
