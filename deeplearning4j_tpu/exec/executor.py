"""The execution core: every XLA program in the repo compiles here.

``Executor.jit`` is the one wrapper the four compile sites use — the
train-step / ``fit_scan`` programs in both model containers, the
bucketed serving forward, and the continuous-batching decode step. A
compile site declares WHAT each argument is (``"params"``, ``"repl"``,
``"batch"``, ``"step_batch"``, ``"slots"``) and the executor owns HOW
that maps onto the mesh:

- params / updater state / model state: replicated on a pure-DP mesh,
  Megatron TP placement (``param_spec``) when the ``model`` axis > 1 —
  updater-state leaves co-shard with the param whose shape they mirror;
- batch-like args: sharded over ``data`` when the leading rows divide
  the axis AND each shard keeps at least ``min_rows_per_shard`` rows
  (sharding 4-row batches buys nothing and costs collectives — the
  threshold is the measured crossover knob, see docs/SHARDING.md);
  otherwise the call runs the exact single-device program it runs
  today. The decision is a pure function of the argument shapes, so a
  given shape always maps to the same compiled program and the
  trace-count accounting the tests pin (`_note_compile`/`_note_trace`)
  is unchanged;
- ``slots`` args (decode state trees): per-sequence rows — useful to
  shard at 1 row/shard, so they get their own threshold, and KV-cache
  leaves additionally TP-shard their feature dim when ``model`` > 1.

On a 1-device mesh ``Executor.jit`` RETURNS ``jax.jit(fn, ...)``
itself — not a wrapper — so the single-device path is byte-identical
to the pre-executor code and compiles zero new programs.
"""

import os
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.exec.mesh import (DATA_AXIS, MODEL_AXIS,
                                          default_mesh)

# argument/output spec vocabulary
PARAMS = "params"          # weight tree: replicated or Megatron TP
STATE = "state"            # model state (BN stats): replicated
OPT = "opt"                # updater state: co-sharded with params
REPL = "repl"              # replicate (scalars, loss)
BATCH = "batch"            # shard dim 0 over 'data' (x, y, masks)
STEP_BATCH = "step_batch"  # shard dim 1 over 'data' ((steps, batch, ...))
SLOTS = "slots"            # decode state: dim 0 = slot rows, KV dims TP
AUX = "aux"                # small replicated side-outputs (telemetry):
                           # never donated, never sharded — a fused
                           # (L, C) stats array rides the step program
                           # without perturbing its main-output layout

_ROW_TOKENS = ("Wo", "ff2", "down")
_COL_TOKENS = ("Wq", "Wk", "Wv", "ff1", "up")


def param_spec(path: str, leaf, model_size: int,
               axis: str = MODEL_AXIS) -> P:
    """Megatron TP placement for one weight leaf (the GSPMD annotation;
    XLA inserts the collectives, correctness never depends on it):
    column-parallel (shard the output/last dim) for Q/K/V, FFN
    up-projections and generic kernels; row-parallel (shard the
    input/first dim) for the pair's second half — ``Wo``/``ff2``/
    ``down`` by name or a wide->narrow shape; 1-D vectors replicate."""
    nd = getattr(leaf, "ndim", 0)
    if model_size <= 1 or nd < 2:
        return P()
    row_name = any(t in path for t in _ROW_TOKENS)
    row_shape = leaf.shape[0] > leaf.shape[-1]
    if (row_name or (row_shape
                     and not any(t in path for t in _COL_TOKENS))) \
            and leaf.shape[0] % model_size == 0 \
            and leaf.shape[0] >= model_size:
        return P(*([axis] + [None] * (nd - 1)))
    if leaf.shape[-1] % model_size == 0 and leaf.shape[-1] >= model_size:
        return P(*([None] * (nd - 1) + [axis]))
    return P()


def _slot_spec(leaf, data_ok: bool, model_size: int) -> P:
    """Decode-state leaf: slot rows over 'data', and (KV caches — any
    leaf with a wide trailing feature dim) the last dim over 'model'."""
    nd = getattr(leaf, "ndim", 0)
    lead = DATA_AXIS if (data_ok and nd >= 1) else None
    if (model_size > 1 and nd >= 2
            and leaf.shape[-1] % model_size == 0
            and leaf.shape[-1] >= model_size):
        return P(*([lead] + [None] * (nd - 2) + [MODEL_AXIS]))
    if nd == 0:
        return P()
    return P(*([lead] + [None] * (nd - 1)))


class Executor:
    """One mesh + one policy for turning step functions into programs."""

    def __init__(self, mesh: Optional[Mesh] = None, *,
                 min_rows_per_shard: Optional[int] = None,
                 min_slots_per_shard: Optional[int] = None,
                 precision: Optional[str] = None,
                 train_precision: Optional[str] = None):
        self.mesh = default_mesh() if mesh is None else mesh
        self.data_size = (self.mesh.shape[DATA_AXIS]
                          if DATA_AXIS in self.mesh.axis_names else 1)
        self.model_size = (self.mesh.shape[MODEL_AXIS]
                           if MODEL_AXIS in self.mesh.axis_names else 1)
        env = os.environ.get("DL4JTPU_MIN_ROWS_PER_SHARD")
        self.min_rows = int(env) if min_rows_per_shard is None and env \
            else (16 if min_rows_per_shard is None
                  else int(min_rows_per_shard))
        self.min_slots = 2 if min_slots_per_shard is None \
            else int(min_slots_per_shard)
        # declarative serving precision: every engine built against this
        # executor (bucketed forward, decode step, replica --checkpoint
        # loads) inherits it without per-caller code (docs/QUANTIZATION.md)
        from deeplearning4j_tpu.quant import resolve_precision
        self.precision = resolve_precision(
            precision if precision is not None
            else os.environ.get("DL4JTPU_PRECISION"))
        # declarative TRAINING precision: 'bf16' casts activations+params
        # to bfloat16 in the fit-path forward of every f32 model built
        # against this executor (loss and updater math stay f32 — the MXU
        # accumulates bf16 matmuls in f32, docs/TRAINING_PERF.md). Read at
        # trace time: containers rebuilt against a new executor pick it up.
        tp = (train_precision if train_precision is not None
              else os.environ.get("DL4JTPU_TRAIN_PRECISION")) or "f32"
        tp = tp.strip().lower()
        if tp not in ("f32", "float32", "bf16", "bfloat16"):
            raise ValueError(
                f"train_precision must be 'f32' or 'bf16', got {tp!r}")
        self.train_precision = "bf16" if tp in ("bf16", "bfloat16") else "f32"
        try:
            from deeplearning4j_tpu.monitor.metrics import get_registry
            get_registry().gauge(
                "dl4jtpu_train_precision_bf16",
                "1 when the executor's training-precision policy is bf16"
            ).set(1.0 if self.train_precision == "bf16" else 0.0)
        except Exception:
            pass

    @property
    def train_dtype(self):
        """The compute dtype the train-precision policy imposes on the fit
        path (None = storage dtype, i.e. no cast)."""
        import jax.numpy as jnp
        return jnp.bfloat16 if self.train_precision == "bf16" else None

    def prepare_params(self, tree, precision: Optional[str] = None):
        """Apply the serving-precision policy to a weight tree: per-channel
        weight-only quantization for 'int8'/'fp8', the identity (same
        objects, bitwise f32 path) for 'f32'. Engines call this once at
        load/swap time — never per request."""
        from deeplearning4j_tpu.quant import quantize_tree
        p = precision if precision is not None else self.precision
        return quantize_tree(tree, p)

    # ------------------------------------------------------------- shardings
    @property
    def is_single(self) -> bool:
        return self.mesh.size == 1

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def _named(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def param_shardings(self, tree):
        """Per-leaf NamedSharding tree for a weight pytree (replicated
        unless the mesh has a model axis)."""
        if self.model_size <= 1:
            return self.replicated()

        def place(path, leaf):
            return self._named(param_spec(jax.tree_util.keystr(path), leaf,
                                          self.model_size))
        return jax.tree_util.tree_map_with_path(place, tree)

    def put_params(self, tree):
        """Commit a weight tree to its mesh placement (used by
        ParallelWrapper and TP setups before the first step)."""
        if self.model_size <= 1:
            return jax.device_put(tree, self.replicated())
        return jax.tree_util.tree_map_with_path(
            lambda p, a: jax.device_put(
                a, self._named(param_spec(jax.tree_util.keystr(p), a,
                                          self.model_size))), tree)

    def _state_shardings(self, tree, params):
        """Updater/model state co-sharded with params: a leaf whose shape
        matches a TP-sharded weight (momentum/velocity mirror their
        param) takes that weight's spec; everything else replicates."""
        if self.model_size <= 1:
            return self.replicated()
        by_shape = {}
        def note(path, leaf):
            sp = param_spec(jax.tree_util.keystr(path), leaf,
                            self.model_size)
            by_shape.setdefault(getattr(leaf, "shape", None), sp)
        jax.tree_util.tree_map_with_path(note, params)
        return jax.tree_util.tree_map(
            lambda leaf: self._named(
                by_shape.get(getattr(leaf, "shape", None), P())), tree)

    def shardable_rows(self, n: int, *, min_rows: Optional[int] = None) \
            -> bool:
        mr = self.min_rows if min_rows is None else min_rows
        return (self.data_size > 1 and n % self.data_size == 0
                and n // self.data_size >= mr)

    # ------------------------------------------------------------------ jit
    def jit(self, fn, *, in_specs: Optional[Sequence] = None,
            out_specs: Optional[Sequence] = None, donate_argnums=(),
            static_argnums=()):
        """Compile ``fn`` against the mesh. ``in_specs``/``out_specs``
        name one spec per positional argument / output (see module
        docstring); each spec is applied as a pytree prefix, so an
        argument may be any tree (a list of graph inputs, an optional
        mask, a decode-state tree, None).

        mesh.size == 1 → returns ``jax.jit`` directly (the special case
        the trace-count tests pin: zero wrapper, zero new programs).
        """
        if self.is_single or in_specs is None:
            return jax.jit(fn, donate_argnums=donate_argnums,
                           static_argnums=static_argnums)
        if static_argnums:
            raise ValueError("static_argnums is only supported on the "
                             "single-device path")
        in_specs = tuple(in_specs)
        cache = {}

        def _rows(args):
            """Leading batch rows seen by the data-sharded args; None when
            absent or inconsistent (→ replicate)."""
            dims = set()
            for spec, a in zip(in_specs, args):
                if spec not in (BATCH, STEP_BATCH, SLOTS):
                    continue
                axis = 1 if spec == STEP_BATCH else 0
                for leaf in jax.tree_util.tree_leaves(a):
                    if getattr(leaf, "ndim", 0) > axis:
                        dims.add(leaf.shape[axis])
            if len(dims) != 1:
                return None
            return next(iter(dims))

        def _build(shard_data, args):
            if not shard_data and self.model_size <= 1:
                # exact single-device program (today's path, on the
                # default device); GSPMD never sees it
                return jax.jit(fn, donate_argnums=donate_argnums)
            params_args = [a for s, a in zip(in_specs, args)
                           if s == PARAMS]
            params_tree = params_args[0] if params_args else None

            def resolve(spec, arg):
                if spec == PARAMS:
                    return self.param_shardings(arg)
                if spec == OPT:
                    return self._state_shardings(arg, params_tree)
                if spec == BATCH:
                    return self._named(P(DATA_AXIS)) if shard_data \
                        else self.replicated()
                if spec == STEP_BATCH:
                    return self._named(P(None, DATA_AXIS)) if shard_data \
                        else self.replicated()
                if spec == SLOTS:
                    return jax.tree_util.tree_map(
                        lambda leaf: self._named(_slot_spec(
                            leaf, shard_data, self.model_size)), arg)
                return self.replicated()

            in_sh = tuple(resolve(s, a) for s, a in zip(in_specs, args))
            out_sh = None
            if out_specs is not None:
                # outputs resolve against the input trees they mirror
                # (a step's new params/state/opt/dstate have the same
                # structure as the input they update)
                by_spec = {}
                for s, a in zip(in_specs, args):
                    by_spec.setdefault(s, a)
                resolved = [resolve(s, by_spec.get(s)) for s in out_specs]
                # single-output functions take the sharding directly
                # (a 1-tuple would claim a tuple-shaped output pytree)
                out_sh = resolved[0] if len(resolved) == 1 \
                    else tuple(resolved)
            return jax.jit(fn, in_shardings=in_sh, out_shardings=out_sh,
                           donate_argnums=donate_argnums)

        slot_specs = any(s == SLOTS for s in in_specs)
        min_rows = self.min_slots if slot_specs else None

        def wrapped(*args):
            rows = _rows(args)
            shard = rows is not None and self.shardable_rows(
                rows, min_rows=min_rows)
            jf = cache.get(shard)
            if jf is None:
                jf = cache[shard] = _build(shard, args)
            return jf(*args)

        wrapped._dl4jtpu_exec_wrapper = True   # introspection for tests
        wrapped._exec_cache = cache
        return wrapped

    # ------------------------------------------------------------- programs
    @property
    def programs(self):
        """The process-wide compiled-program registry (``exec.programs``):
        every compile site records cost/memory analysis of the programs
        it built through this executor — ``GET /programs`` and the
        ``dl4jtpu_program_*`` gauges read from here."""
        from deeplearning4j_tpu.exec.programs import get_programs
        return get_programs()

    def register_program(self, caller, key, fn, args, compile_seconds=None):
        """Record a program built by :meth:`jit` (single-device ``jax.jit``
        results and mesh wrappers both work); see
        ``programs.ProgramRegistry.record``."""
        return self.programs.record(caller, key, fn, args,
                                    compile_seconds=compile_seconds)


# ------------------------------------------------------- process default
_default_executor: Optional[Executor] = None


def get_executor() -> Executor:
    global _default_executor
    if _default_executor is None:
        _default_executor = Executor()
    return _default_executor


def set_executor(ex: Optional[Executor]) -> None:
    global _default_executor
    _default_executor = ex


def _invalidate_default() -> None:
    global _default_executor
    _default_executor = None
