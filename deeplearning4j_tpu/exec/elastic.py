"""Elastic training coordination: leases, generations, fenced recovery.

The coordination plane that makes the N-process cluster (exec/cluster.py)
survive worker death (docs/ELASTIC_TRAINING.md). One ``ElasticCoordinator``
owns the membership truth:

- **Heartbeat leases.** Every worker renews a lease; a missed lease walks
  the router health-state-machine idiom: ``live → suspect →`` evicted.
  All timing flows through an injectable clock, so tests drive the whole
  matrix with a fake clock and zero sleeps.
- **Generation-numbered membership.** Every committed membership is a
  *generation*. Any change — eviction, a replacement joining, a rejoin
  after a healed partition — proposes generation ``g+1``; members must
  roll back to the checkpoint anchor and ``sync`` to the proposal before
  it commits. Contributions stamped with a dead generation are fenced
  (rejected + counted), so a partitioned straggler can never corrupt a
  step it no longer participates in.
- **Checkpoint-anchored recovery.** Rank 0 reports every atomic
  checkpoint save as the *anchor*; recovery means everyone restores the
  anchor and resumes from its step. Because the checkpoint is bitwise
  (PR 4) and batches/reduction order are deterministic, a killed-and-
  recovered run re-trains into the exact trajectory of an unkilled one.
- **Graceful degradation.** After an eviction the coordinator waits
  ``replacement_grace`` seconds for a replacement; if none joins, it
  commits the new generation at N-1 (ranks compacted, batch re-sharded)
  — throughput drops, correctness doesn't. A later join re-forms at N.
- **Control plane only (by default).** Gradient bytes travel the
  peer-to-peer chunk-pipelined chain (``exec/comms.py``,
  ``data_plane="chain"``): the coordinator hands each committed
  generation's rank → (host, port) endpoint map to the members and never
  sees a gradient. The PR 19 star reducer is kept behind
  ``data_plane="star"`` as the parity oracle and bench baseline: each
  member posts ``loss‖grads`` pre-scaled by its shard rows; the
  coordinator sums in rank order (fixed float association → bitwise
  reproducible) and divides by the total rows — the exact arithmetic the
  chain reproduces hop by hop. On jaxlibs with real collectives the
  worker's ``DL4JTPU_CLUSTER_BACKEND=jax`` probe switches to an in-mesh
  psum instead.

``CoordinatorServer`` wraps the state machine in the same stdlib
ThreadingHTTPServer transport the serving tier uses; workers talk to it
through the shared retry primitive (``component="cluster"``).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ElasticCoordinator", "CoordinatorServer", "Member",
           "FencedError", "EvictedError", "ClusterFullError",
           "LIVE", "SUSPECT"]

LIVE = "live"
SUSPECT = "suspect"

# how many reduced steps stay cached for idempotent re-reads after a
# worker's HTTP timeout made it re-POST an already-reduced contribution
_REDUCED_KEEP = 8


class FencedError(Exception):
    """Contribution stamped with a dead generation (or posted while a
    reform is in flight): rejected, counted, the worker must roll back to
    the anchor and sync to the proposed generation."""

    def __init__(self, msg: str, proposal: Optional[int] = None,
                 anchor: Optional[dict] = None):
        super().__init__(msg)
        self.proposal = proposal
        self.anchor = anchor or {}


class EvictedError(Exception):
    """The worker is no longer a member (lease expired, or it left): its
    process should exit; a *replacement* joins in its place."""


class ClusterFullError(Exception):
    """A join beyond ``world_size`` — the supervisor overspawned."""


@dataclass
class Member:
    worker_id: str
    joined_at: float
    last_hb: float
    state: str = LIVE
    rank: Optional[int] = None          # assigned at generation commit
    synced_gen: int = 0                 # highest proposal this member ack'd
    steps_done: int = 0
    data_port: int = 0                  # peer data-plane listener (comms.py)


@dataclass
class _Barrier:
    """One allreduce step's contributions (keyed by (generation, step))."""

    contrib: Dict[int, tuple] = field(default_factory=dict)  # rank → (rows, vec)
    fenced: bool = False


class ElasticCoordinator:
    """Membership + lease + generation + reduction state machine.

    Pure logic: no sockets, no threads of its own, every timestamp from
    the injected ``clock`` — tests/test_elastic.py drives the whole
    suspect/evict/rejoin/degrade matrix with a fake clock. The HTTP plane
    (``CoordinatorServer``) and the in-process adapter call the same
    methods.
    """

    def __init__(self, world_size: int, *, total_steps: int = 8,
                 global_batch: int = 32, model: str = "mlp", seed: int = 42,
                 ckpt_dir: Optional[str] = None, ckpt_every: int = 4,
                 aot: bool = True,
                 hb_interval: float = 0.25, suspect_after: float = 1.5,
                 evict_after: float = 4.0, replacement_grace: float = 8.0,
                 data_plane: str = "chain", codec: str = "dense",
                 bucket_mb: float = 4.0, threshold: float = 1e-3,
                 min_threshold: float = 1e-5, threshold_step: float = 1e-5,
                 capacity_fraction: float = 0.1,
                 clock=time.monotonic):
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        if data_plane not in ("chain", "star"):
            raise ValueError(f"data_plane must be chain|star, "
                             f"got {data_plane!r}")
        if codec not in ("dense", "threshold"):
            raise ValueError(f"codec must be dense|threshold, got {codec!r}")
        self.target_world = int(world_size)
        self.total_steps = int(total_steps)
        self.global_batch = int(global_batch)
        self.model = model
        self.seed = int(seed)
        self.ckpt_dir = ckpt_dir
        self.ckpt_every = int(ckpt_every)
        self.aot = bool(aot)
        self.hb_interval = float(hb_interval)
        self.suspect_after = float(suspect_after)
        self.evict_after = float(evict_after)
        self.replacement_grace = float(replacement_grace)
        self.data_plane = data_plane
        self.codec = codec
        self.bucket_mb = float(bucket_mb)
        self.threshold = float(threshold)
        self.min_threshold = float(min_threshold)
        self.threshold_step = float(threshold_step)
        self.capacity_fraction = float(capacity_fraction)
        self._clock = clock

        self.generation = 0                 # last COMMITTED generation
        self.world = 0                      # committed member count
        self.proposal: Optional[int] = 1    # pending generation (1 = forming)
        self._grace_deadline: Optional[float] = None
        self._evict_t: Optional[float] = None   # start of current recovery
        self.last_recovery_wall: Optional[float] = None
        self.phase = "forming"              # forming | running | done
        self.anchor: dict = {"step": 0, "path": None}

        self._members: Dict[str, Member] = {}
        self._barriers: Dict[tuple, _Barrier] = {}
        self._reduced: Dict[tuple, np.ndarray] = {}
        self._results: Dict[str, dict] = {}
        self.events: List[dict] = []        # supervisor-facing journal
        self._joins = 0
        self.reduced_steps = 0

        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._metrics_init()

    # ------------------------------------------------------------- metrics
    def _metrics_init(self):
        from deeplearning4j_tpu.monitor import get_registry
        reg = get_registry()
        self._g_workers = reg.gauge(
            "dl4jtpu_cluster_workers",
            "Cluster members by lease state (evicted members leave the "
            "table, so live+suspect is current membership).", ("state",))
        self._g_generation = reg.gauge(
            "dl4jtpu_cluster_generation",
            "Committed membership generation; stale-generation "
            "contributions are fenced.")
        self._g_world = reg.gauge(
            "dl4jtpu_cluster_world_size",
            "Members in the committed generation (target-N, or N-1 while "
            "degraded after an unreplaced eviction).")
        self._c_hb = reg.counter(
            "dl4jtpu_cluster_heartbeats_total",
            "Heartbeat lease renewals accepted by the coordinator.")
        self._c_evict = reg.counter(
            "dl4jtpu_cluster_evictions_total",
            "Members evicted from the cluster, by reason.", ("reason",))
        self._c_rejoin = reg.counter(
            "dl4jtpu_cluster_rejoins_total",
            "Joins after initial formation: replacements for evicted "
            "workers and healed partitions coming back.")
        self._c_fenced = reg.counter(
            "dl4jtpu_cluster_fenced_contributions_total",
            "RPCs rejected for carrying a dead generation (or landing "
            "mid-reform), by rpc kind.", ("rpc",))
        self._c_recover = reg.counter(
            "dl4jtpu_cluster_recoveries_total",
            "Eviction-triggered reforms committed: 'replaced' back at "
            "target N, 'degraded' at N-1 after the grace window.",
            ("outcome",))
        self._c_steps = reg.counter(
            "dl4jtpu_cluster_steps_total",
            "Gradient allreduce steps reduced across the cluster.")
        self._h_reduce = reg.histogram(
            "dl4jtpu_cluster_allreduce_seconds",
            "Wall seconds a contribution waited at the allreduce barrier "
            "(first contribution in to reduction out).",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0))

    def _publish_gauges(self):
        live = sum(1 for m in self._members.values() if m.state == LIVE)
        sus = sum(1 for m in self._members.values() if m.state == SUSPECT)
        self._g_workers.labels(state=LIVE).set(live)
        self._g_workers.labels(state=SUSPECT).set(sus)
        self._g_generation.set(self.generation)
        self._g_world.set(self.world)

    # ----------------------------------------------------------- membership
    def config(self) -> dict:
        """Static job config a joining worker needs before first sync."""
        return {"model": self.model, "seed": self.seed,
                "total_steps": self.total_steps,
                "global_batch": self.global_batch,
                "ckpt_dir": self.ckpt_dir, "ckpt_every": self.ckpt_every,
                "aot": self.aot,
                "hb_interval": self.hb_interval,
                "suspect_after": self.suspect_after,
                "evict_after": self.evict_after,
                "replacement_grace": self.replacement_grace,
                "data_plane": self.data_plane, "codec": self.codec,
                "bucket_mb": self.bucket_mb,
                "threshold": self.threshold,
                "min_threshold": self.min_threshold,
                "threshold_step": self.threshold_step,
                "capacity_fraction": self.capacity_fraction}

    def join(self, worker_id: str, data_port: int = 0) -> dict:
        """Register a worker. Initial joins assemble generation 1; any
        join after that (replacement / healed partition) counts as a
        rejoin and proposes a new generation everyone must sync to.
        ``data_port`` is the worker's peer data-plane listener — published
        to every member in the committed membership view so the chain can
        dial rank-adjacent neighbors directly."""
        with self._lock:
            now = self._clock()
            if (worker_id not in self._members
                    and len(self._members) >= self.target_world):
                raise ClusterFullError(
                    f"cluster already has {len(self._members)} members "
                    f"(target {self.target_world})")
            rejoin = self.generation > 0
            self._members[worker_id] = Member(worker_id=worker_id,
                                              joined_at=now, last_hb=now,
                                              data_port=int(data_port))
            self._joins += 1
            if rejoin:
                self._c_rejoin.inc()
                self._propose(now, reason=f"join:{worker_id}")
            self.events.append({"type": "join", "worker_id": worker_id,
                                "rejoin": rejoin, "t": now})
            self._publish_gauges()
            return {"ok": True, "proposal": self.proposal,
                    "config": self.config()}

    def leave(self, worker_id: str) -> None:
        """Graceful departure (drain): evict without a lease expiry."""
        with self._lock:
            if worker_id in self._members:
                self._evict(worker_id, reason="left")

    def sync(self, worker_id: str, generation: int) -> dict:
        """Worker acks a proposed generation (after rolling back to the
        anchor). Returns ``{"status": "wait"}`` until the proposal
        commits, then the committed membership view."""
        with self._lock:
            m = self._members.get(worker_id)
            if m is None:
                raise EvictedError(f"{worker_id} is not a member")
            m.last_hb = self._clock()       # syncing proves liveness
            if self.proposal is not None and generation == self.proposal:
                m.synced_gen = generation
                self._try_commit(self._clock())
            if self.proposal is None and generation == self.generation:
                return self._membership_view(worker_id)
            return {"status": "wait",
                    "proposal": self.proposal or self.generation}

    def _membership_view(self, worker_id: str) -> dict:
        m = self._members[worker_id]
        return {"status": "go", "generation": self.generation,
                "rank": m.rank, "world": self.world,
                "anchor": dict(self.anchor), "phase": self.phase,
                "endpoints": {str(o.rank): ["127.0.0.1", o.data_port]
                              for o in self._members.values()
                              if o.rank is not None}}

    def _propose(self, now: float, reason: str, evicted: bool = False):
        """Open (or refresh) a reform: next generation, members must
        re-sync. Fences every in-flight barrier."""
        self.proposal = self.generation + 1
        if evicted and len(self._members) < self.target_world:
            self._grace_deadline = now + self.replacement_grace
        elif len(self._members) >= self.target_world:
            self._grace_deadline = None
        self.events.append({"type": "reform_proposed",
                            "proposal": self.proposal, "reason": reason,
                            "t": now})
        for key, b in self._barriers.items():
            if not b.fenced:
                b.fenced = True
        self._cond.notify_all()

    def _try_commit(self, now: float):
        if self.proposal is None or not self._members:
            return
        if any(m.synced_gen != self.proposal
               for m in self._members.values()):
            return
        full = len(self._members) >= self.target_world
        grace_over = (self._grace_deadline is not None
                      and now >= self._grace_deadline)
        if not full and not grace_over:
            return
        # commit: survivors keep their ranks when the world is full
        # (replacements fill the holes — shard mapping matches an unkilled
        # run, the bitwise-parity soak pins this); a degraded commit
        # compacts ranks by previous order so 0..W-1 stays contiguous
        members = list(self._members.values())
        if full:
            taken = {m.rank for m in members
                     if m.rank is not None and m.rank < self.target_world}
            free = [r for r in range(self.target_world) if r not in taken]
            seen = set()
            for m in sorted(members, key=lambda m: m.joined_at):
                if m.rank is None or m.rank in seen or m.rank >= self.target_world:
                    m.rank = free.pop(0)
                seen.add(m.rank)
        else:
            order = sorted(members,
                           key=lambda m: (m.rank if m.rank is not None
                                          else 1 << 30, m.joined_at))
            for r, m in enumerate(order):
                m.rank = r
        self.generation = self.proposal
        self.world = len(members)
        self.proposal = None
        self._grace_deadline = None
        self._barriers.clear()
        self._reduced.clear()
        if self._evict_t is not None:
            self.last_recovery_wall = now - self._evict_t
            self._c_recover.labels(
                outcome="replaced" if full else "degraded").inc()
            self._evict_t = None
        self.phase = "running"
        self.events.append({"type": "generation_committed",
                            "generation": self.generation,
                            "world": self.world, "t": now,
                            "ranks": {m.worker_id: m.rank
                                      for m in members}})
        self._publish_gauges()
        self._cond.notify_all()

    # ---------------------------------------------------------- lease clock
    def heartbeat(self, worker_id: str, generation: int = 0,
                  step: int = 0) -> dict:
        with self._lock:
            m = self._members.get(worker_id)
            if m is None:
                raise EvictedError(f"{worker_id} is not a member")
            m.last_hb = self._clock()
            if m.state == SUSPECT:
                m.state = LIVE          # a heartbeat heals suspicion
            m.steps_done = max(m.steps_done, int(step))
            self._advance_reduced()
            self._c_hb.inc()
            self._publish_gauges()
            directive = "none"
            if self.proposal is not None and m.synced_gen != self.proposal:
                directive = "rollback"
            elif generation and generation != self.generation:
                directive = "rollback"
            return {"generation": self.generation,
                    "proposal": self.proposal, "directive": directive,
                    "anchor": dict(self.anchor), "phase": self.phase}

    def tick(self, now: Optional[float] = None) -> None:
        """Advance the failure detector: lease ages walk live → suspect →
        evicted, and an expired grace window commits a degraded world."""
        with self._lock:
            now = self._clock() if now is None else now
            for wid in list(self._members):
                m = self._members[wid]
                age = now - m.last_hb
                if age >= self.evict_after:
                    self._evict(wid, reason="lease_expired", now=now)
                elif age >= self.suspect_after and m.state == LIVE:
                    m.state = SUSPECT
                    self.events.append({"type": "suspect",
                                        "worker_id": wid, "t": now})
            self._try_commit(now)
            self._publish_gauges()

    def _evict(self, worker_id: str, reason: str,
               now: Optional[float] = None):
        now = self._clock() if now is None else now
        m = self._members.pop(worker_id)
        self._c_evict.labels(reason=reason).inc()
        if self._evict_t is None:
            self._evict_t = now
        self.events.append({"type": "evicted", "worker_id": worker_id,
                            "rank": m.rank, "reason": reason, "t": now})
        # the dead member may have been the only one yet to post a result;
        # without this re-check the finished survivors would wait in a
        # reform nobody can commit and the job would never reach "done"
        self._maybe_done()
        if self.phase != "done":
            if self._members:
                self._propose(now, reason=f"evict:{worker_id}", evicted=True)
            else:
                self.proposal = self.generation + 1
                self._grace_deadline = None
        self._publish_gauges()
        self._cond.notify_all()

    # ------------------------------------------------------------ allreduce
    def _fence(self, rpc: str, msg: str):
        self._c_fenced.labels(rpc=rpc).inc()
        raise FencedError(msg, proposal=self.proposal,
                          anchor=dict(self.anchor))

    def contribute(self, worker_id: str, generation: int, step: int,
                   rows: int, vec: np.ndarray) -> None:
        """Post one member's pre-scaled ``loss‖grads`` vector for ``step``.
        Idempotent per (generation, step, rank): a retry after an HTTP
        timeout re-registers the same contribution."""
        with self._lock:
            m = self._members.get(worker_id)
            if m is None:
                raise EvictedError(f"{worker_id} is not a member")
            if generation != self.generation or self.proposal is not None:
                self._fence("allreduce",
                            f"stale generation {generation} "
                            f"(current {self.generation}, "
                            f"proposal {self.proposal})")
            key = (generation, step)
            if key in self._reduced:
                return                  # already reduced: reader path serves it
            b = self._barriers.setdefault(key, _Barrier())
            b.contrib[m.rank] = (int(rows), np.asarray(vec, np.float32))
            m.steps_done = max(m.steps_done, step)
            if len(b.contrib) >= self.world:
                total = None
                rows_sum = 0
                for r in sorted(b.contrib):     # rank order: deterministic
                    n, v = b.contrib[r]
                    rows_sum += n
                    total = v.copy() if total is None else total + v
                self._reduced[key] = (total / np.float32(rows_sum))
                while len(self._reduced) > _REDUCED_KEEP:
                    del self._reduced[min(self._reduced)]
                del self._barriers[key]
                self.reduced_steps = max(self.reduced_steps, step + 1)
                self._c_steps.inc()
            self._cond.notify_all()

    def wait_reduced(self, worker_id: str, generation: int, step: int,
                     timeout: float = 60.0) -> np.ndarray:
        """Block until ``step``'s reduction is ready (or the barrier is
        fenced by a membership change). Real-clock timeout: this is the
        HTTP handler's wait, not the failure detector's."""
        t0 = time.monotonic()
        deadline = t0 + timeout
        with self._lock:
            while True:
                key = (generation, step)
                if key in self._reduced:
                    self._h_reduce.observe(time.monotonic() - t0)
                    return self._reduced[key]
                if worker_id not in self._members:
                    raise EvictedError(f"{worker_id} evicted mid-barrier")
                if generation != self.generation or self.proposal is not None:
                    self._fence("allreduce",
                                f"barrier fenced at generation {generation}")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"allreduce step {step} gen {generation}: barrier "
                        f"incomplete after {timeout}s")
                self._cond.wait(timeout=min(remaining, 0.1))

    # ----------------------------------------------------- anchor / results
    def anchor_report(self, worker_id: str, generation: int, step: int,
                      path: Optional[str]) -> dict:
        """Rank 0 reports an atomic checkpoint at ``step`` — the recovery
        anchor every rollback restores."""
        with self._lock:
            if worker_id not in self._members:
                raise EvictedError(f"{worker_id} is not a member")
            if generation != self.generation or self.proposal is not None:
                self._fence("anchor", f"anchor from dead generation "
                                      f"{generation}")
            self.anchor = {"step": int(step), "path": path}
            self.events.append({"type": "anchor", "step": int(step),
                                "path": path, "t": self._clock()})
            return dict(self.anchor)

    def _advance_reduced(self) -> None:
        """On the peer-to-peer data plane the coordinator never sees a
        gradient, so reduced progress is inferred from reported steps: the
        chain is lockstep — a member can only be at step s+1 once step s
        reduced across everyone — so min(steps_done) is the fully-reduced
        floor. Monotone (max) because members report anchor-rolled-back
        steps during reforms. The star path still advances the counter
        directly at each barrier; this floor can never outrun it."""
        if not self._members:
            return
        floor = min(m.steps_done for m in self._members.values())
        if floor > self.reduced_steps:
            self._c_steps.inc(floor - self.reduced_steps)
            self.reduced_steps = floor

    def result(self, worker_id: str, payload: dict) -> None:
        with self._lock:
            self._results[worker_id] = dict(payload)
            m = self._members.get(worker_id)
            if m is not None:
                m.steps_done = max(m.steps_done,
                                   int(payload.get("steps") or 0))
                self._advance_reduced()
            self._maybe_done()

    def _maybe_done(self):
        """Every live member has posted its result → the job is done.
        Checked after results AND after evictions, because either event
        can be the one that completes the condition."""
        live = set(self._members)
        if live and live <= set(self._results):
            self.phase = "done"
            self._cond.notify_all()

    def results(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._results)

    def state(self) -> dict:
        with self._lock:
            return {
                "phase": self.phase, "generation": self.generation,
                "proposal": self.proposal, "world": self.world,
                "target_world": self.target_world,
                "anchor": dict(self.anchor),
                "reduced_steps": self.reduced_steps,
                "last_recovery_wall": self.last_recovery_wall,
                "members": {wid: {"rank": m.rank, "state": m.state,
                                  "synced_gen": m.synced_gen,
                                  "steps_done": m.steps_done}
                            for wid, m in self._members.items()},
                "results": dict(self._results),
                "events": list(self.events),
            }


# --------------------------------------------------------------------------
# HTTP plane
# --------------------------------------------------------------------------

def _mk_handler(coord: ElasticCoordinator):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):       # quiet: the events journal is the log
            pass

        def _json(self, code: int, doc: dict):
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _bytes(self, code: int, body: bytes):
            self.send_response(code)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _read_body(self) -> bytes:
            n = int(self.headers.get("Content-Length", 0) or 0)
            return self.rfile.read(n) if n else b""

        def do_GET(self):
            if self.path.split("?")[0] == "/state":
                self._json(200, coord.state())
            else:
                self._json(404, {"error": "not_found"})

        def do_POST(self):  # noqa: C901 — one dispatch table, kept flat
            path = self.path.split("?")[0]
            try:
                if path == "/allreduce":
                    h = self.headers
                    wid = h.get("X-Worker", "")
                    gen = int(h.get("X-Gen", 0))
                    step = int(h.get("X-Step", 0))
                    rows = int(h.get("X-Rows", 0))
                    vec = np.frombuffer(self._read_body(), dtype=np.float32)
                    coord.contribute(wid, gen, step, rows, vec)
                    out = coord.wait_reduced(wid, gen, step)
                    self._bytes(200, out.astype(np.float32).tobytes())
                    return
                doc = json.loads(self._read_body() or b"{}")
                if path == "/join":
                    self._json(200, coord.join(
                        doc["worker_id"],
                        int(doc.get("data_port", 0) or 0)))
                elif path == "/sync":
                    self._json(200, coord.sync(doc["worker_id"],
                                               int(doc["generation"])))
                elif path == "/heartbeat":
                    self._json(200, coord.heartbeat(
                        doc["worker_id"], int(doc.get("generation", 0)),
                        int(doc.get("step", 0))))
                elif path == "/anchor":
                    self._json(200, coord.anchor_report(
                        doc["worker_id"], int(doc["generation"]),
                        int(doc["step"]), doc.get("path")))
                elif path == "/leave":
                    coord.leave(doc["worker_id"])
                    self._json(200, {"ok": True})
                elif path == "/result":
                    coord.result(doc["worker_id"], doc.get("result", {}))
                    self._json(200, {"ok": True})
                else:
                    self._json(404, {"error": "not_found"})
            except FencedError as e:
                self._json(409, {"error": "stale_generation",
                                 "message": str(e),
                                 "proposal": e.proposal,
                                 "anchor": e.anchor})
            except EvictedError as e:
                self._json(410, {"error": "evicted", "message": str(e)})
            except ClusterFullError as e:
                self._json(409, {"error": "cluster_full",
                                 "message": str(e)})
            except TimeoutError as e:
                self._json(503, {"error": "barrier_timeout",
                                 "message": str(e)})
            except (KeyError, ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": "bad_request",
                                 "message": str(e)})

    return Handler


class CoordinatorServer:
    """The coordinator's HTTP face + its failure-detector clock thread.

    ``tick_interval=None`` disables the background ticker (tests that
    drive ``coord.tick`` with a fake clock run the server purely as
    transport)."""

    def __init__(self, coord: ElasticCoordinator, port: int = 0,
                 tick_interval: Optional[float] = 0.1):
        self.coord = coord
        self.tick_interval = tick_interval
        from http.server import ThreadingHTTPServer
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                          _mk_handler(coord))
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "CoordinatorServer":
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="coord-http", daemon=True)
        t.start()
        self._threads.append(t)
        if self.tick_interval:
            tt = threading.Thread(target=self._tick_loop, name="coord-tick",
                                  daemon=True)
            tt.start()
            self._threads.append(tt)
        return self

    def _tick_loop(self):
        while not self._stop.wait(self.tick_interval):
            try:
                self.coord.tick()
            except Exception:   # noqa: BLE001 — the detector must not die
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
