"""AOT program artifacts: serialize compiled XLA executables next to the
checkpoint so a fresh replica restores them in milliseconds instead of
paying a full retrace.

A cold replica's dominant start-up cost is tracing + XLA-compiling its hot
programs (the bucketed ladder rungs, the decode step, the spec
draft/verify pair, the paged-KV side programs) — 20-120 s per program on
tunneled TPU attachments, seconds even on CPU. The persistent compile
cache (util/compile_cache.py) removes the XLA backend compile but still
pays the full python trace per program; this module removes BOTH by
shipping the serialized executables themselves
(``jax.experimental.serialize_executable``) in a versioned zip artifact
written with the atomic ``model_serializer`` discipline.

Validity model: a serialized executable bakes in argument shapes/dtypes,
donation, and backend-specific generated code. The bundle is therefore
keyed on (backend, jaxlib version, model signature, precision) at the
artifact level — any mismatch rejects the WHOLE bundle — and each program
inside is keyed by a caller-chosen string encoding its rung/shape
(``engine:mln:b8:...``, ``decode:step:S4:...``). The model signature
hashes shapes/dtypes only (weights are runtime arguments), so a newer
checkpoint of the same architecture reuses the artifact unchanged.

Every miss falls back to trace-and-save: callers trace as before, export
the fresh program, and merge it into the artifact. Restores count in
``dl4jtpu_aot_restores_total`` — never in the engines' compile counters —
so the existing compiled-program pins survive, and "zero new compiles
after restore" is directly observable as ``trace_count == 0``.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import time
import zipfile
from typing import Any, Dict, Optional, Tuple

__all__ = ["AotBundle", "open_bundle", "export_compiled", "companion_path",
           "model_signature", "MISS_REASONS"]

FORMAT = "deeplearning4j_tpu/aot-bundle/v1"

#: every reason ``dl4jtpu_aot_misses_total`` can carry — the artifact-level
#: gates first (whole bundle rejected), then per-program misses
MISS_REASONS = ("no_artifact", "corrupt", "format", "backend", "jaxlib",
                "model_sig", "precision", "key")

_metrics = None


def _aot_metrics():
    global _metrics
    if _metrics is None:
        from deeplearning4j_tpu.monitor import get_registry
        reg = get_registry()
        _metrics = {
            "restores": reg.counter(
                "dl4jtpu_aot_restores_total",
                "Compiled programs deserialized from an AOT artifact "
                "instead of being retraced (counted separately from the "
                "engines' compile counters).", ("engine",)),
            "misses": reg.counter(
                "dl4jtpu_aot_misses_total",
                "AOT artifact lookups that fell back to trace-and-save, "
                "by reason (no_artifact/corrupt/format/backend/jaxlib/"
                "model_sig/precision/key).", ("reason",)),
            "seconds": reg.histogram(
                "dl4jtpu_aot_restore_seconds",
                "Wall seconds to deserialize one compiled program from "
                "the artifact.", ("engine",)),
        }
    return _metrics


def note_miss(reason: str) -> None:
    if reason not in MISS_REASONS:
        reason = "corrupt"
    _aot_metrics()["misses"].labels(reason=reason).inc()


def _env_fingerprint() -> Dict[str, str]:
    import jax
    try:
        import jaxlib
        jl = getattr(jaxlib, "__version__", None)
        if jl is None:
            from jaxlib import version as _jlv
            jl = getattr(_jlv, "__version__", "unknown")
    except Exception:
        jl = "unknown"
    return {"backend": jax.default_backend(), "jaxlib": str(jl),
            "jax": jax.__version__}


def model_signature(*trees) -> str:
    """Hash of the shapes/dtypes of the given pytrees (weights are runtime
    arguments to the serialized programs, so VALUES are irrelevant — a
    later checkpoint of the same architecture keeps the same signature,
    while any architectural change rejects the bundle)."""
    from deeplearning4j_tpu.serving.engine import _tree_signature
    sig = [sorted(_tree_signature(t).items()) for t in trees]
    blob = json.dumps(sig, sort_keys=True).encode()
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


def companion_path(checkpoint_path) -> str:
    """The artifact path riding next to a checkpoint: ``model.zip`` →
    ``model.aot.zip`` (rotated and pinned together by CheckpointManager)."""
    p = os.fspath(checkpoint_path)
    return (p[:-len(".zip")] if p.endswith(".zip") else p) + ".aot.zip"


def export_compiled(jitted, args):
    """AOT-compile ``jitted`` (a ``jax.jit`` result or mesh ``Executor.jit``
    wrapper) at the shapes of ``args`` for serialization. Runs under the
    registration guard so the relowered python body does not double-count
    the caller's compile accounting; the persistent compile cache makes
    the XLA half of this relower cheap.

    On the CPU backend the relower runs with the persistent cache
    DISABLED: a CPU executable loaded from the compilation cache cannot
    be re-serialized (``deserialize_and_load`` of such a payload fails
    with ``Symbols not found``), so a cache HIT here would poison the
    artifact. The cache object is a process singleton that ignores
    config changes after first use, so the dir change alone is not
    enough — the singleton is reset around the compile (and re-armed
    after, so the ambient cache keeps working for everything else)."""
    import jax

    from deeplearning4j_tpu.exec.programs import _Registering, _lowerable
    low = _lowerable(jitted)
    if low is None:
        raise TypeError(f"object has no lowerable jit entry: {jitted!r}")
    with _Registering():
        if jax.default_backend() != "cpu":
            return low.lower(*args).compile()
        try:
            from jax._src import compilation_cache as _cc
        except Exception:
            _cc = None
        prev = jax.config.jax_compilation_cache_dir
        try:
            jax.config.update("jax_compilation_cache_dir", None)
            if _cc is not None:
                _cc.reset_cache()
            # a non-empty compiler_options dict (the value IS the
            # default, so the program is unchanged) bypasses the
            # memoized executable of an earlier call at these shapes —
            # that executable may itself have been loaded from the cache
            return low.lower(*args).compile(
                compiler_options={"xla_cpu_enable_fast_math": False})
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            if _cc is not None:
                _cc.reset_cache()


class AotBundle:
    """A set of serialized executables sharing one validity envelope.

    ``programs`` maps caller-chosen key strings to pickled
    ``serialize_executable`` triples. ``save`` merges with any compatible
    bundle already on disk (two engines warming against the same artifact
    union their programs) and writes atomically.
    """

    def __init__(self, model_sig: str, precision: str,
                 env: Optional[Dict[str, str]] = None):
        env = env or _env_fingerprint()
        self.backend = env["backend"]
        self.jaxlib = env["jaxlib"]
        self.jax = env.get("jax", "unknown")
        self.model_sig = str(model_sig)
        self.precision = str(precision)
        self._programs: Dict[str, bytes] = {}

    # ------------------------------------------------------------ programs
    def keys(self):
        return set(self._programs)

    def __contains__(self, key: str) -> bool:
        return key in self._programs

    def __len__(self) -> int:
        return len(self._programs)

    def add_compiled(self, key: str, compiled) -> None:
        """Serialize one compiled executable under ``key`` (replacing any
        previous entry)."""
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = se.serialize(compiled)
        self._programs[str(key)] = pickle.dumps(
            (payload, in_tree, out_tree), protocol=pickle.HIGHEST_PROTOCOL)

    def restore(self, key: str, engine: str = ""):
        """Deserialize-and-load the program under ``key``; None on a key
        miss or an undeserializable entry (both counted, never raised —
        the caller falls back to trace-and-save)."""
        blob = self._programs.get(str(key))
        if blob is None:
            note_miss("key")
            return None
        t0 = time.perf_counter()
        try:
            from jax.experimental import serialize_executable as se
            payload, in_tree, out_tree = pickle.loads(blob)
            compiled = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:
            note_miss("corrupt")
            return None
        m = _aot_metrics()
        m["restores"].labels(engine=engine or "unknown").inc()
        m["seconds"].labels(engine=engine or "unknown").observe(
            time.perf_counter() - t0)
        return compiled

    # ----------------------------------------------------------------- io
    def _meta(self) -> dict:
        return {"format": FORMAT, "backend": self.backend,
                "jaxlib": self.jaxlib, "jax": self.jax,
                "model_sig": self.model_sig, "precision": self.precision,
                "programs": sorted(self._programs)}

    def compatible(self, other: "AotBundle") -> bool:
        return (self.backend == other.backend
                and self.jaxlib == other.jaxlib
                and self.model_sig == other.model_sig
                and self.precision == other.precision)

    def save(self, path) -> str:
        """Atomic merge-save: union with a compatible bundle already at
        ``path`` (an incompatible one is overwritten — it could never be
        restored in this process anyway), then temp + fsync + rename, the
        model_serializer discipline."""
        path = os.fspath(path)
        try:
            prev = AotBundle.load(path)
        except Exception:
            prev = None
        merged = dict(self._programs)
        if prev is not None and self.compatible(prev):
            for k, v in prev._programs.items():
                merged.setdefault(k, v)
        self._programs = merged

        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_STORED) as z:
            z.writestr("meta.json", json.dumps(self._meta(), indent=1))
            for i, key in enumerate(sorted(self._programs)):
                z.writestr(f"programs/{i:04d}.bin", self._programs[key])
        d = os.path.dirname(path) or "."
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
        try:
            with open(tmp, "wb") as f:
                f.write(buf.getvalue())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
        try:
            fd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass
        return path

    @classmethod
    def load(cls, path) -> "AotBundle":
        """Read a bundle from disk (raises on absence/corruption/unknown
        format — ``open_bundle`` is the non-raising, metric-counting
        entry)."""
        with zipfile.ZipFile(os.fspath(path), "r") as z:
            meta = json.loads(z.read("meta.json"))
            if meta.get("format") != FORMAT:
                raise ValueError(
                    f"unknown artifact format {meta.get('format')!r}")
            b = cls(meta["model_sig"], meta["precision"],
                    env={"backend": meta["backend"],
                         "jaxlib": meta["jaxlib"],
                         "jax": meta.get("jax", "unknown")})
            for i, key in enumerate(meta["programs"]):
                b._programs[key] = z.read(f"programs/{i:04d}.bin")
        return b


def open_bundle(path, model_sig: str, precision: str,
                ) -> Tuple[Optional[AotBundle], Optional[str]]:
    """Open + validate an artifact against this process's environment and
    the caller's model. Returns ``(bundle, None)`` when every artifact-level
    gate passes, else ``(None, reason)`` with the miss counted — a stale
    program is NEVER deserialized; the caller falls back to trace-and-save.
    """
    if not path or not os.path.exists(os.fspath(path)):
        note_miss("no_artifact")
        return None, "no_artifact"
    try:
        b = AotBundle.load(path)
    except ValueError:
        note_miss("format")
        return None, "format"
    except Exception:
        note_miss("corrupt")
        return None, "corrupt"
    env = _env_fingerprint()
    reason = None
    if b.backend != env["backend"]:
        reason = "backend"
    elif b.jaxlib != env["jaxlib"]:
        reason = "jaxlib"
    elif b.model_sig != str(model_sig):
        reason = "model_sig"
    elif b.precision != str(precision):
        reason = "precision"
    if reason is not None:
        note_miss(reason)
        return None, reason
    return b, None
