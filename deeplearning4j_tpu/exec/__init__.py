"""The execution core: mesh + executor + kernel routing.

Every compile site in the repo — train step and ``fit_scan`` in both
model containers, the bucketed serving forward, the continuous-batching
decode step — builds its XLA programs through ``Executor.jit`` against
the ONE process mesh (``data``/``model`` axes). See docs/SHARDING.md.
"""

from deeplearning4j_tpu.exec.mesh import (DATA_AXIS, MODEL_AXIS,  # noqa: F401
                                          build_mesh, default_mesh,
                                          set_default_mesh,
                                          host_device_env)
from deeplearning4j_tpu.exec.executor import (Executor,  # noqa: F401
                                              get_executor, set_executor,
                                              param_spec,
                                              PARAMS, STATE, OPT, REPL,
                                              BATCH, STEP_BATCH, SLOTS,
                                              AUX)
from deeplearning4j_tpu.exec.routing import (lstm_fwd_route,  # noqa: F401
                                             lstm_grad_route,
                                             flash_attn_route,
                                             decode_attn_route,
                                             set_route, load_measurements,
                                             load_measurements_file)
from deeplearning4j_tpu.exec.programs import (ProgramRegistry,  # noqa: F401
                                              get_programs, is_registering)

__all__ = [
    "DATA_AXIS", "MODEL_AXIS", "build_mesh", "default_mesh",
    "set_default_mesh", "host_device_env",
    "Executor", "get_executor", "set_executor", "param_spec",
    "PARAMS", "STATE", "OPT", "REPL", "BATCH", "STEP_BATCH", "SLOTS",
    "AUX",
    "lstm_fwd_route", "lstm_grad_route", "flash_attn_route",
    "decode_attn_route", "set_route",
    "load_measurements", "load_measurements_file",
    "ProgramRegistry", "get_programs", "is_registering",
]
