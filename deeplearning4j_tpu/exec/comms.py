"""Peer-to-peer gradient data plane for the elastic cluster.

PR 19's allreduce moved every step's FULL f32 gradient through the
coordinator as a star — each worker uploads D·4 bytes over a fresh HTTP
connection, blocks at the barrier, downloads D·4 bytes; coordinator
bandwidth is 2·N·D·4 per step, fully serialized with compute. This module
demotes the coordinator to CONTROL PLANE ONLY (membership, generations,
fencing) and carries gradient bytes over persistent peer-to-peer loopback
TCP sockets instead (docs/ELASTIC_TRAINING.md "Data plane"):

- **Chunk-pipelined rank-ordered chain.** The flat ``loss‖grads`` vector
  splits into fixed-size buckets (``bucket_mb``). Reduce messages flow
  rank 0 → 1 → … → N-1, each hop adding its OWN bucket to the arriving
  partial sum; rank N-1 divides by the accumulated row count and
  broadcast messages flow back N-1 → … → 0. Because every element still
  accumulates in exact rank order — the same float association as the
  star coordinator's sorted-rank loop — the dense path is BITWISE-equal
  to PR 19's star allreduce and to the single-process reference replay
  (``exec.worker.single_process_reference``). The reduce and broadcast
  loops run on separate threads per member over full-duplex sockets, so
  bucket j+1 is on the wire while bucket j reduces and bucket j-1's mean
  already flows back — DDP/Horovod-style bucketed overlap.
- **Opt-in threshold wire codec** (``codec="threshold"``). Each worker
  compresses its OWN contribution once per step with the Strom-2015
  scheme shared with ``scaleout/training_master.py`` (sign·threshold
  messages, error-feedback residual carry, adaptive threshold via
  ``parallel.compression.adapt_threshold``); the chain then transports
  the EXACT sparse partial sums — per bucket, an int32-index + f32-value
  payload when that beats dense, dense fallback otherwise. The head
  bucket (loss) is always exact. Residuals are per worker and RESET on
  any generation change (``ThresholdCodec.reset``) so a stale
  pre-eviction residual can never leak into the new membership.
- **Elastic by construction.** Sockets are per-generation: every frame
  carries the generation, a stale or torn wire raises ``CommsError``, the
  worker parks for the coordinator's reform verdict and ``configure()``
  rebuilds the chain over the survivors' endpoints from the committed
  membership view.

``tools/comm_bench.py`` microbenches this module standalone.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["ChainComms", "ThresholdCodec", "CommsError",
           "CommsAbortedError", "bucketize", "DEFAULT_BUCKET_MB"]

DEFAULT_BUCKET_MB = 4.0

_MAGIC = 0xD14C
_HELLO, _REDUCE, _BCAST = 1, 2, 3
_DENSE, _SPARSE = 0, 1
# magic u16 | kind u8 | wire u8 | generation i32 | step i32 | bucket i32 |
# rows i64 | payload nbytes u32  (little-endian, 24 bytes)
_HDR = struct.Struct("<HBBiiiqI")

# sockets poll at this granularity so ``should_abort`` (the worker's
# rollback/evicted lease state) interrupts a peer wait promptly
_POLL_S = 0.25


class CommsError(Exception):
    """The peer-to-peer data plane failed: a peer died mid-exchange, a
    socket tore, or a stale generation arrived on the wire. The member
    must wait for the coordinator's reform verdict and rebuild the chain
    (``ElasticWorker._await_reform``)."""


class CommsAbortedError(CommsError):
    """``should_abort()`` fired while blocked on a peer — the lease layer
    already knows about the membership change; stop waiting and resync."""


def bucketize(n: int, bucket_mb: float = DEFAULT_BUCKET_MB,
              head: int = 1) -> List[Tuple[int, int]]:
    """Split an ``n``-element f32 vector into ``[start, stop)`` buckets:
    one ``head``-element bucket (the loss — always dense and exact on the
    wire) followed by fixed-size body buckets of ``bucket_mb`` MB. A model
    smaller than one bucket gets a single ragged body bucket; the last
    body bucket is ragged whenever the body size doesn't divide."""
    if n < head:
        raise ValueError(f"vector of {n} elements cannot carry a "
                         f"{head}-element head bucket")
    per = max(1, int(float(bucket_mb) * 1024 * 1024) // 4)
    out = [(0, head)] if head else []
    for start in range(head, n, per):
        out.append((start, min(n, start + per)))
    return out


# --------------------------------------------------------------------------
# exact per-bucket wire encoding (sparse when it wins, dense fallback)
# --------------------------------------------------------------------------

def encode_bucket(vals: np.ndarray) -> Tuple[int, bytes]:
    """EXACT encoding of one bucket: sparse ``int32 idx ‖ f32 vals`` when
    8·nnz < 4·n, dense f32 bytes otherwise. Lossless either way — the
    lossy part of the threshold codec happens once per worker in
    ``ThresholdCodec.encode``; partial sums stay exact at every hop."""
    vals = np.ascontiguousarray(vals, np.float32)
    nz = np.flatnonzero(vals)
    if nz.size * 8 < vals.size * 4:
        return _SPARSE, (nz.astype(np.int32).tobytes()
                         + vals[nz].tobytes())
    return _DENSE, vals.tobytes()


def decode_bucket(wire: int, payload: bytes, n: int) -> np.ndarray:
    if wire == _DENSE:
        vals = np.frombuffer(payload, np.float32)
        if vals.size != n:
            raise CommsError(f"dense bucket size {vals.size} != {n}")
        return vals
    if len(payload) % 8:
        raise CommsError(f"sparse bucket payload {len(payload)}B not 8-aligned")
    k = len(payload) // 8
    idx = np.frombuffer(payload[:k * 4], np.int32)
    vals = np.frombuffer(payload[k * 4:], np.float32)
    if k and (idx.min() < 0 or idx.max() >= n):
        raise CommsError(f"sparse bucket index out of range for n={n}")
    out = np.zeros(n, np.float32)
    out[idx] = vals
    return out


# --------------------------------------------------------------------------
# threshold codec (worker-local lossy compression with residual carry)
# --------------------------------------------------------------------------

class ThresholdCodec:
    """Strom-2015 threshold compression for one worker's OWN contribution
    — the same semantics as ``parallel.compression.EncodingHandler``
    (residual error-feedback carry, sign·threshold messages, adaptive
    threshold via the shared ``adapt_threshold`` policy), in host numpy so
    the data plane never touches the device. ``encode`` returns a DENSE
    f32 message vector; the wire layer sparsifies it per bucket
    (``encode_bucket``). Bitwise-parity with EncodingHandler's decoded
    message / residual / threshold trajectory is pinned by
    tests/test_comms.py."""

    def __init__(self, n: int, threshold: float = 1e-3,
                 min_threshold: float = 1e-5, threshold_step: float = 1e-5,
                 capacity_fraction: float = 0.1):
        self.n = int(n)
        self.initial_threshold = float(threshold)
        self.threshold = float(threshold)
        self.min_threshold = float(min_threshold)
        self.threshold_step = float(threshold_step)
        self.capacity_fraction = float(capacity_fraction)
        self.residual = np.zeros(self.n, np.float32)
        self.resets = 0
        self.last_count = 0

    @property
    def capacity(self) -> int:
        return max(1, min(self.n, int(self.n * self.capacity_fraction)))

    def encode(self, vec: np.ndarray) -> np.ndarray:
        from deeplearning4j_tpu.parallel.compression import adapt_threshold
        u = np.asarray(vec, np.float32) + self.residual
        cap = self.capacity
        thr = np.float32(self.threshold)
        mag = np.abs(u)
        sel = np.flatnonzero(mag >= thr)
        if sel.size > cap:
            # keep the ``cap`` largest magnitudes — the fixed-capacity
            # top-k the jit encoder uses (ties broken by magnitude order,
            # irrelevant on continuous gradients)
            sel = sel[np.argsort(mag[sel], kind="stable")[::-1][:cap]]
        msg = np.zeros(self.n, np.float32)
        msg[sel] = np.sign(u[sel]) * thr
        self.residual = u - msg
        self.last_count = int(sel.size)
        self.threshold = adapt_threshold(
            self.threshold, self.last_count, cap,
            step=self.threshold_step, min_threshold=self.min_threshold)
        return msg

    def reset(self) -> None:
        """Generation change: drop the error-feedback residual and restart
        the threshold walk. A residual accumulated under the dead
        membership encodes gradients of a trajectory the new generation
        rolled back — letting it leak would silently skew the first
        post-reform steps (fencing, docs/ELASTIC_TRAINING.md)."""
        self.residual[:] = 0.0
        self.threshold = self.initial_threshold
        self.resets += 1
        _metrics().resets.inc()


# --------------------------------------------------------------------------
# metrics
# --------------------------------------------------------------------------

class _Metrics:
    def __init__(self):
        from deeplearning4j_tpu.monitor import get_registry
        reg = get_registry()
        self.bytes = reg.counter(
            "dl4jtpu_cluster_comm_bytes_total",
            "Gradient data-plane bytes on the wire (headers + payload), by "
            "direction and configured codec; the star fallback counts its "
            "HTTP gradient payloads here too.", ("direction", "codec"))
        self.ratio = reg.gauge(
            "dl4jtpu_cluster_compression_ratio",
            "Dense-equivalent payload bytes / actual payload bytes for the "
            "last allreduce through this member (1.0 on the dense codec).")
        self.bucket = reg.histogram(
            "dl4jtpu_cluster_bucket_seconds",
            "Wall seconds one bucket spent at this member's reduce hop "
            "(receive partial + add own + forward).",
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.02, 0.1, 0.5, 2.0))
        self.resets = reg.counter(
            "dl4jtpu_cluster_residual_resets_total",
            "Threshold-codec error-feedback residuals cleared on a "
            "generation change (stale-residual fencing at reform).")


_METRICS: Optional[_Metrics] = None
_METRICS_LOCK = threading.Lock()


def _metrics() -> _Metrics:
    global _METRICS
    if _METRICS is None:
        with _METRICS_LOCK:
            if _METRICS is None:
                _METRICS = _Metrics()
    return _METRICS


def record_star_bytes(sent: int, recv: int) -> None:
    """The star (coordinator HTTP) fallback reports its gradient payload
    bytes under the same metric family so dashboards compare planes."""
    m = _metrics()
    m.bytes.labels(direction="sent", codec="dense").inc(int(sent))
    m.bytes.labels(direction="recv", codec="dense").inc(int(recv))
    m.ratio.set(1.0)


# --------------------------------------------------------------------------
# chain transport
# --------------------------------------------------------------------------

class ChainComms:
    """One member's half of the chunk-pipelined rank-ordered chain.

    Lifecycle: construct once per worker process (opens the data-plane
    listener whose port rides the ``join`` RPC), ``configure()`` on every
    committed generation (tears down the old sockets, dials rank+1, awaits
    rank-1), ``allreduce()`` once per step. Sockets are PER-GENERATION:
    every frame carries the generation and any mismatch — or a torn/closed
    socket, i.e. a SIGKILLed peer — raises ``CommsError``; the worker then
    waits for the coordinator's reform and reconfigures over the
    survivors. ``close()`` on exit."""

    def __init__(self, codec: str = "dense",
                 bucket_mb: float = DEFAULT_BUCKET_MB,
                 codec_opts: Optional[dict] = None,
                 io_timeout: float = 120.0):
        self.codec = codec
        self.bucket_mb = float(bucket_mb)
        self.codec_opts = dict(codec_opts or {})
        self.io_timeout = float(io_timeout)
        self.codec_state: Optional[ThresholdCodec] = None

        self.generation = 0
        self.rank = 0
        self.world = 1
        self._prev: Optional[socket.socket] = None   # from rank-1
        self._next: Optional[socket.socket] = None   # to rank+1
        self._closed = False
        self._byte_lock = threading.Lock()   # reduce + bcast threads both count
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.last: dict = {}        # per-allreduce stats for bench/tools

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(8)
        self.data_port = self._listener.getsockname()[1]
        self._pcond = threading.Condition()
        self._pending: Dict[Tuple[int, int], socket.socket] = {}
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="comms-accept", daemon=True)
        self._accept_thread.start()

    def set_policy(self, codec: str, bucket_mb: float,
                   codec_opts: Optional[dict] = None) -> None:
        """Adopt the job's codec config (known only after ``join`` returns
        the coordinator's config — the listener must exist before that)."""
        self.codec = codec
        self.bucket_mb = float(bucket_mb)
        if codec_opts:
            self.codec_opts = dict(codec_opts)

    # -- listener ----------------------------------------------------------
    def _accept_loop(self):
        while not self._closed:
            try:
                s, _ = self._listener.accept()
            except OSError:
                return
            try:
                s.settimeout(self.io_timeout)
                hdr = self._read_exact(s, _HDR.size)
                magic, kind, _, gen, rank, _, _, _ = _HDR.unpack(hdr)
                if magic != _MAGIC or kind != _HELLO:
                    s.close()
                    continue
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.settimeout(_POLL_S)
            except Exception:   # noqa: BLE001 — a garbage dial, drop it
                s.close()
                continue
            with self._pcond:
                old = self._pending.pop((gen, rank), None)
                if old is not None:
                    old.close()
                self._pending[(gen, rank)] = s
                self._pcond.notify_all()

    @staticmethod
    def _read_exact(s: socket.socket, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise CommsError("peer closed during handshake")
            buf += chunk
        return bytes(buf)

    # -- (re)configuration -------------------------------------------------
    def configure(self, generation: int, rank: int, world: int,
                  endpoints: Dict[int, Tuple[str, int]], *,
                  should_abort: Optional[Callable[[], bool]] = None,
                  timeout: float = 60.0) -> None:
        """Rebuild the chain for a committed generation: close the old
        generation's sockets, dial rank+1's listener, await rank-1's dial.
        ``endpoints`` is the committed membership view's rank → (host,
        port) map. Raises CommsError if the peers never materialize —
        usually a peer died between commit and formation, which the lease
        detector will turn into another reform."""
        self._teardown_peers()
        if int(generation) != self.generation:
            # stale-residual fencing: error feedback accumulated under the
            # dead membership must not leak into the new one
            self.reset_codec()
        self.generation = int(generation)
        self.rank = int(rank)
        self.world = int(world)
        if self.world <= 1:
            return
        deadline = time.monotonic() + timeout
        if self.rank < self.world - 1:
            host, port = endpoints[self.rank + 1]
            self._next = self._dial(host, int(port), deadline, should_abort)
        if self.rank > 0:
            self._prev = self._await_accept(self.generation, self.rank - 1,
                                            deadline, should_abort)
        with self._pcond:     # drop sockets stranded by dead generations
            for key in [k for k in self._pending if k[0] < self.generation]:
                self._pending.pop(key).close()

    def _dial(self, host: str, port: int, deadline: float,
              should_abort) -> socket.socket:
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            if should_abort is not None and should_abort():
                raise CommsAbortedError("aborted dialing next rank")
            try:
                s = socket.create_connection((host, port), timeout=_POLL_S)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.settimeout(_POLL_S)
                s.sendall(_HDR.pack(_MAGIC, _HELLO, 0, self.generation,
                                    self.rank, 0, 0, 0))
                return s
            except OSError as e:    # listener not up yet / race: retry
                last = e
                time.sleep(0.02)
        raise CommsError(f"could not reach rank {self.rank + 1} at "
                         f"{host}:{port} for generation {self.generation}: "
                         f"{last!r}")

    def _await_accept(self, gen: int, rank: int, deadline: float,
                      should_abort) -> socket.socket:
        with self._pcond:
            while True:
                s = self._pending.pop((gen, rank), None)
                if s is not None:
                    return s
                if should_abort is not None and should_abort():
                    raise CommsAbortedError("aborted awaiting prev rank")
                if time.monotonic() >= deadline:
                    raise CommsError(
                        f"rank {rank} never dialed in for generation {gen}")
                self._pcond.wait(timeout=_POLL_S)

    def _teardown_peers(self):
        for s in (self._prev, self._next):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._prev = self._next = None

    def close(self):
        self._closed = True
        self._teardown_peers()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._pcond:
            for s in self._pending.values():
                s.close()
            self._pending.clear()

    def reset_codec(self) -> None:
        if self.codec_state is not None:
            self.codec_state.reset()

    # -- framed I/O --------------------------------------------------------
    def _send(self, sock: socket.socket, kind: int, wire: int, step: int,
              bucket: int, rows: int, payload, should_abort=None) -> None:
        # Sockets run with a short poll timeout so a peer stuck in compute
        # (or dead) can't wedge us: loop the syscall by hand — sendall()
        # leaves the stream in an unknown state after a partial-write
        # timeout. sendmsg gathers header + payload without concatenating
        # them (a bucket-sized copy per hop at dense widths).
        nbytes = memoryview(payload).nbytes
        pending = [memoryview(_HDR.pack(_MAGIC, kind, wire, self.generation,
                                        step, bucket, rows, nbytes)).cast("B"),
                   memoryview(payload).cast("B")]
        deadline = time.monotonic() + self.io_timeout
        while pending:
            if should_abort is not None and should_abort():
                raise CommsAbortedError("aborted while sending to peer")
            if time.monotonic() >= deadline:
                raise CommsError(f"peer send timed out ({self.io_timeout}s)")
            try:
                done = sock.sendmsg(pending)
            except socket.timeout:
                continue
            except OSError as e:
                raise CommsError(f"send to peer failed: {e!r}") from None
            while done:
                if done >= len(pending[0]):
                    done -= len(pending[0])
                    pending.pop(0)
                else:
                    pending[0] = pending[0][done:]
                    done = 0
            pending = [v for v in pending if len(v)]
        n = _HDR.size + nbytes
        with self._byte_lock:
            self.bytes_sent += n
        _metrics().bytes.labels(direction="sent", codec=self.codec).inc(n)

    def _recv_exact(self, sock: socket.socket, n: int,
                    should_abort) -> bytearray:
        # recv_into a preallocated buffer: no chunk-list growth, no final
        # bytes() copy — callers treat the returned bytearray as frozen
        buf = bytearray(n)
        view, got = memoryview(buf), 0
        deadline = time.monotonic() + self.io_timeout
        while got < n:
            if should_abort is not None and should_abort():
                raise CommsAbortedError("aborted waiting on peer bytes")
            if time.monotonic() >= deadline:
                raise CommsError(f"peer read timed out ({self.io_timeout}s)")
            try:
                k = sock.recv_into(view[got:], min(1 << 20, n - got))
            except socket.timeout:
                continue
            except OSError as e:
                raise CommsError(f"recv from peer failed: {e!r}") from None
            if not k:
                raise CommsError("peer closed mid-message (died or reformed)")
            got += k
        return buf

    def _recv_msg(self, sock: socket.socket, kind: int, step: int,
                  bucket: int, should_abort):
        hdr = self._recv_exact(sock, _HDR.size, should_abort)
        magic, k, wire, gen, s, b, rows, nbytes = _HDR.unpack(hdr)
        if magic != _MAGIC or k != kind:
            raise CommsError(f"bad frame magic={magic:#x} kind={k}")
        if gen != self.generation:
            raise CommsError(f"wire generation {gen} != committed "
                             f"{self.generation} (reform in flight)")
        if s != step or b != bucket:
            raise CommsError(f"out-of-order frame step={s} bucket={b} "
                             f"(want step={step} bucket={bucket})")
        payload = self._recv_exact(sock, nbytes, should_abort)
        n = _HDR.size + nbytes
        with self._byte_lock:
            self.bytes_recv += n
        _metrics().bytes.labels(direction="recv", codec=self.codec).inc(n)
        return wire, rows, payload

    # -- the allreduce -----------------------------------------------------
    def allreduce(self, step: int, vec: np.ndarray, rows: int, *,
                  should_abort: Optional[Callable[[], bool]] = None
                  ) -> np.ndarray:
        """Mean-reduce ``vec`` (already pre-scaled by ``rows``) across the
        chain; every rank returns byte-identical output. Row counts
        accumulate through frame headers and rank N-1 performs the single
        ``total / float32(rows_sum)`` division — exactly the star
        coordinator's arithmetic, which is what keeps the dense path
        bitwise-equal to PR 19 and to the single-process reference."""
        t0 = time.perf_counter()
        vec = np.ascontiguousarray(vec, np.float32)
        n = vec.shape[0]
        own = vec
        if self.codec == "threshold" and n > 1:
            if self.codec_state is None or self.codec_state.n != n - 1:
                self.codec_state = ThresholdCodec(n - 1, **self.codec_opts)
            # lossy once, on this worker's own contribution; the head
            # element (loss·rows) stays exact
            own = np.concatenate([vec[:1], self.codec_state.encode(vec[1:])])
        if self.world <= 1:
            out = own / np.float32(rows)
            self._stats(t0, 1, 0, 0, 0, 0)
            return out

        buckets = bucketize(n, self.bucket_mb)
        sparse_wire = self.codec == "threshold"
        mean_q: "queue.Queue" = queue.Queue()
        mean_parts: List[Optional[np.ndarray]] = [None] * len(buckets)
        errors: List[BaseException] = []
        # separate dict keys per thread: reduce and bcast account payload
        # bytes concurrently
        acct = {"r_pay": 0, "r_dense": 0, "b_pay": 0, "b_dense": 0}
        sent0, recv0 = self.bytes_sent, self.bytes_recv

        def abort() -> bool:
            return bool(errors) or (should_abort is not None
                                    and should_abort())

        def out_frame(vals: np.ndarray, side: str):
            if sparse_wire:
                wire, payload = encode_bucket(vals)
            else:
                # zero-copy wire view of the reduced bucket (the array
                # outlives the send: mean_parts / acc hold it)
                wire, payload = _DENSE, memoryview(
                    np.ascontiguousarray(vals, np.float32)).cast("B")
            acct[side + "_pay"] += len(payload)
            acct[side + "_dense"] += vals.size * 4
            return wire, payload

        def reduce_loop():
            for j, (a, b) in enumerate(buckets):
                tb = time.perf_counter()
                mine = own[a:b]
                if self.rank == 0:
                    acc, racc = mine, int(rows)
                else:
                    wire, rin, payload = self._recv_msg(
                        self._prev, _REDUCE, step, j, abort)
                    partial = decode_bucket(wire, payload, b - a)
                    acc = partial + mine        # ranks 0..r-1, then r: exact
                    racc = int(rin) + int(rows)  # rank-order association
                if self.rank < self.world - 1:
                    wire, payload = out_frame(acc, "r")
                    self._send(self._next, _REDUCE, wire, step, j, racc,
                               payload, abort)
                else:
                    mean_q.put((j, acc / np.float32(racc)))
                _metrics().bucket.observe(time.perf_counter() - tb)

        def bcast_loop():
            if self.rank == self.world - 1:
                for _ in buckets:
                    item = None
                    while item is None:
                        if abort():
                            raise CommsAbortedError("aborted at bcast head")
                        try:
                            item = mean_q.get(timeout=_POLL_S)
                        except queue.Empty:
                            continue
                    j, mean = item
                    wire, payload = out_frame(mean, "b")
                    self._send(self._prev, _BCAST, wire, step, j, 0, payload,
                               abort)
                    mean_parts[j] = mean
            else:
                for j, (a, b) in enumerate(buckets):
                    wire, _, payload = self._recv_msg(
                        self._next, _BCAST, step, j, abort)
                    if self.rank > 0:
                        self._send(self._prev, _BCAST, wire, step, j, 0,
                                   payload, abort)
                        acct["b_pay"] += len(payload)
                        acct["b_dense"] += (b - a) * 4
                    mean_parts[j] = decode_bucket(wire, payload, b - a)

        def guarded(fn):
            try:
                fn()
            except BaseException as e:   # noqa: BLE001 — rethrown below
                errors.append(e)

        t = threading.Thread(target=guarded, args=(reduce_loop,),
                             name="comms-reduce", daemon=True)
        t.start()
        guarded(bcast_loop)
        t.join()
        if errors:
            # a real peer failure outranks the abort it cascaded into the
            # other loop — surface the root cause
            for e in errors:
                if not isinstance(e, CommsAbortedError):
                    raise e
            raise errors[0]
        out = np.concatenate(mean_parts)
        self._stats(t0, len(buckets), self.bytes_sent - sent0,
                    self.bytes_recv - recv0,
                    acct["r_pay"] + acct["b_pay"],
                    acct["r_dense"] + acct["b_dense"])
        return out

    def _stats(self, t0: float, nbuckets: int, sent: int, recv: int,
               pay_sent: int, dense_sent: int) -> None:
        ratio = (dense_sent / pay_sent) if pay_sent else 1.0
        _metrics().ratio.set(ratio)
        self.last = {"wall_s": time.perf_counter() - t0,
                     "buckets": nbuckets, "bytes_sent": sent,
                     "bytes_recv": recv, "payload_sent": pay_sent,
                     "dense_equiv_sent": dense_sent,
                     "compression_ratio": ratio}
