"""Elastic N-process training cluster: supervisor + subprocess workers.

``ClusterManager`` is the parent-side control plane for a real
multi-process data-parallel job on one machine (docs/ELASTIC_TRAINING.md):

    mgr = ClusterManager(workdir, workers=4, total_steps=12)
    result = mgr.run()          # spawn, supervise, auto-replace, collect

It runs the ``ElasticCoordinator`` (exec/elastic.py) in-process — the
supervisor reads membership truth off the object directly, no RPC — and
spawns one ``python -m deeplearning4j_tpu.exec.worker`` per seat through
the ``host_device_env`` pattern (each child gets its own virtual-device
view; the parent's jax state is untouched). Supervision is the elastic
story's other half: when the coordinator evicts a seat (lease expired,
partitioned link, graceful leave), the manager spawns a REPLACEMENT
worker into the same job — the job itself never restarts, which is what
the soak's zero-job-restart assertion pins (surviving pids unchanged,
spawn count == N + kills).

Chaos is declarative: ``chaos={1: "die_at_step=5"}`` plants a scripted
self-SIGKILL in worker 1's env (``resilience.faults.WorkerChaos``), and
``partition=[2]`` routes worker 2's coordinator link through a
``BlackholeProxy`` the test can starve — the worker keeps running but its
heartbeats vanish, the partition the lease detector exists for.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.exec.elastic import CoordinatorServer, ElasticCoordinator
from deeplearning4j_tpu.exec.mesh import host_device_env

__all__ = ["WorkerProcess", "ClusterManager"]


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


class WorkerProcess:
    """Parent-side handle for one subprocess worker (the ReplicaProcess
    idiom: port-file handshake, log-to-file, SIGTERM drain, SIGKILL).

    The port-file carries the child's PID once it has JOINED the
    coordinator — the spawn handshake ``wait_joined`` blocks on.
    """

    def __init__(self, workdir: str, coordinator_url: str, worker_id: str,
                 rank: int, devices: int = 1, chaos: Optional[str] = None,
                 env: Optional[dict] = None):
        self.workdir = workdir
        self.coordinator_url = coordinator_url
        self.worker_id = worker_id
        self.rank = rank
        self.devices = devices
        self.chaos = chaos
        self.extra_env = env
        self.proc: Optional[subprocess.Popen] = None
        self.spawned_at: Optional[float] = None
        self._log = os.path.join(workdir, f"{worker_id}.log")
        self._port_file = os.path.join(workdir, f"{worker_id}.port")

    @property
    def pid(self) -> Optional[int]:
        return None if self.proc is None else self.proc.pid

    def start(self) -> "WorkerProcess":
        if os.path.exists(self._port_file):
            os.unlink(self._port_file)
        cmd = [sys.executable, "-m", "deeplearning4j_tpu.exec.worker",
               "--coordinator", self.coordinator_url,
               "--worker-id", self.worker_id,
               "--rank", str(self.rank),
               "--port-file", self._port_file]
        env = host_device_env(self.devices)
        env["PYTHONPATH"] = (_repo_root() + os.pathsep
                             + env.get("PYTHONPATH", ""))
        if self.chaos:
            env["DL4JTPU_WORKER_CHAOS"] = self.chaos
        else:
            env.pop("DL4JTPU_WORKER_CHAOS", None)
        if self.extra_env:
            env.update(self.extra_env)
        # log to a FILE: a full stdout pipe would deadlock a worker nobody
        # reads, and the post-mortem wants the log anyway. The child owns
        # its inherited fd after the spawn, so the parent's handle closes
        # immediately — replacements must not leak descriptors in the
        # supervisor for the life of the run.
        self.spawned_at = time.monotonic()
        with open(self._log, "ab") as logf:
            self.proc = subprocess.Popen(cmd, stdout=logf,
                                         stderr=subprocess.STDOUT, env=env,
                                         cwd=self.workdir)
        return self

    def wait_joined(self, timeout: float = 120.0) -> "WorkerProcess":
        deadline = time.monotonic() + timeout
        while True:
            if os.path.exists(self._port_file):
                with open(self._port_file) as f:
                    if f.read().strip():
                        return self
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"worker {self.worker_id} exited "
                    f"rc={self.proc.returncode} before joining; "
                    f"see {self._log}")
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"worker {self.worker_id} never joined; see {self._log}")
            time.sleep(0.05)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def stop(self, timeout: float = 30.0) -> None:
        """SIGTERM → wait → SIGKILL."""
        if self.proc is None or self.proc.poll() is not None:
            return
        self.proc.send_signal(signal.SIGTERM)
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=10)

    def kill(self) -> None:
        """SIGKILL, no drain — the crash the lease detector must catch."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)

    def log_text(self) -> str:
        try:
            with open(self._log, "r", errors="replace") as f:
                return f.read()
        except OSError:
            return ""


class ClusterManager:
    """Spawn, supervise and auto-replace the worker fleet for one job.

    ``chaos``: {spawn_index: WorkerChaos spec string} — planted only in
    the ORIGINAL worker at that seat, never in its replacement (a scripted
    death must not re-kill the seat forever).
    ``replace``: auto-spawn a replacement when a seat is evicted (up to
    ``max_replacements``); False lets the grace window expire into an N-1
    degraded commit instead.
    ``partition``: spawn these seats with their coordinator link routed
    through a ``BlackholeProxy`` — ``mgr.partition_worker("w2")`` then
    starves the link (heartbeats vanish, the worker process lives), the
    exact failure the lease detector exists for.
    """

    def __init__(self, workdir: str, workers: int = 2, *,
                 devices_per_worker: int = 1, total_steps: int = 8,
                 global_batch: int = 32, model: str = "mlp", seed: int = 42,
                 ckpt_every: int = 4, aot: bool = True,
                 hb_interval: float = 0.25, suspect_after: float = 1.5,
                 evict_after: float = 4.0, replacement_grace: float = 8.0,
                 replace: bool = True, max_replacements: int = 4,
                 chaos: Optional[Dict[int, str]] = None,
                 partition: Optional[List[int]] = None,
                 data_plane: str = "chain", codec: str = "dense",
                 bucket_mb: float = 4.0, threshold: float = 1e-3,
                 min_threshold: float = 1e-5, threshold_step: float = 1e-5,
                 capacity_fraction: float = 0.1):
        self.workdir = os.fspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.workers = int(workers)
        self.devices_per_worker = int(devices_per_worker)
        self.replace = replace
        self.max_replacements = int(max_replacements)
        self.chaos = dict(chaos or {})
        self.ckpt_dir = os.path.join(self.workdir, "ckpt")
        self.coord = ElasticCoordinator(
            workers, total_steps=total_steps, global_batch=global_batch,
            model=model, seed=seed, ckpt_dir=self.ckpt_dir,
            ckpt_every=ckpt_every, aot=aot, hb_interval=hb_interval,
            suspect_after=suspect_after, evict_after=evict_after,
            replacement_grace=replacement_grace, data_plane=data_plane,
            codec=codec, bucket_mb=bucket_mb, threshold=threshold,
            min_threshold=min_threshold, threshold_step=threshold_step,
            capacity_fraction=capacity_fraction)
        self.server = CoordinatorServer(self.coord,
                                        tick_interval=hb_interval / 2)
        self.procs: Dict[str, WorkerProcess] = {}
        self.proxies: Dict[str, object] = {}
        self._partition = set(partition or ())
        self.spawn_count = 0
        self.replacements = 0
        self._events_seen = 0

    @property
    def url(self) -> str:
        return self.server.url

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ClusterManager":
        self.server.start()
        for i in range(self.workers):
            self._spawn(f"w{i}", rank=i, chaos=self.chaos.get(i),
                        proxied=i in self._partition)
        return self

    def _spawn(self, worker_id: str, rank: int,
               chaos: Optional[str] = None,
               proxied: bool = False) -> WorkerProcess:
        url = self.url
        if proxied:
            from deeplearning4j_tpu.resilience.faults import BlackholeProxy
            proxy = BlackholeProxy(self.server.port).start()
            self.proxies[worker_id] = proxy
            url = f"http://127.0.0.1:{proxy.port}"
        wp = WorkerProcess(self.workdir, url, worker_id, rank,
                           devices=self.devices_per_worker, chaos=chaos)
        self.procs[worker_id] = wp.start()
        self.spawn_count += 1
        return wp

    def partition_worker(self, worker_id: str, on: bool = True) -> None:
        """Starve (or heal) a proxied worker's coordinator link. The
        worker must have been spawned with its seat in ``partition``."""
        self.proxies[worker_id].blackhole(on)

    def _supervise_once(self) -> None:
        """Drain new coordinator events; replace evicted seats. The
        replacement id is ``<seat>r<n>`` so logs and spill files name the
        lineage."""
        with self.coord._lock:
            events = self.coord.events[self._events_seen:]
            self._events_seen += len(events)
            done = self.coord.phase == "done"
        for ev in events:
            # a finished job needs no replacement — the eviction that
            # completed it (last non-reporter died) must not spawn one
            if done or ev["type"] != "evicted" or not self.replace:
                continue
            if self.replacements >= self.max_replacements:
                continue
            dead = ev["worker_id"]
            seat = dead.split("r")[0]
            self.replacements += 1
            wid = f"{seat}r{self.replacements}"
            # never inherit the dead worker's chaos: a scripted death
            # would re-kill every replacement at the same step
            self._spawn(wid, rank=ev.get("rank") or 0, chaos=None)

    def run(self, timeout: float = 300.0) -> dict:
        """Start (if needed), supervise to completion, stop, report."""
        if not self.procs:
            self.start()
        deadline = time.monotonic() + timeout
        try:
            while True:
                self._supervise_once()
                state = self.coord.state()
                if state["phase"] == "done":
                    break
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"cluster did not finish in {timeout}s: "
                        f"phase={state['phase']} "
                        f"reduced={state['reduced_steps']} "
                        f"members={list(state['members'])}")
                if (not any(p.alive() for p in self.procs.values())
                        and state["phase"] != "done"):
                    logs = {w: p.log_text()[-2000:]
                            for w, p in self.procs.items()}
                    raise RuntimeError(
                        f"every worker exited before the job finished: "
                        f"{ {w: p.proc.returncode for w, p in self.procs.items() if p.proc} }"
                        f"\n{logs}")
                time.sleep(0.05)
            # drain: workers exit on their own once they observe the done
            # phase — waiting here lets them return rc=0 instead of eating
            # the teardown SIGTERM (the soak asserts survivors' exit codes)
            drain = time.monotonic() + 15.0
            while (any(p.alive() for p in self.procs.values())
                   and time.monotonic() < drain):
                time.sleep(0.05)
            return self.result()
        finally:
            self.stop()

    def result(self) -> dict:
        state = self.coord.state()
        from deeplearning4j_tpu.resilience.checkpoint import latest_checkpoint
        return {
            "results": state["results"],
            "generation": state["generation"],
            "world": state["world"],
            "reduced_steps": state["reduced_steps"],
            "last_recovery_wall": state["last_recovery_wall"],
            "spawns": self.spawn_count,
            "replacements": self.replacements,
            "checkpoint": latest_checkpoint(self.ckpt_dir),
            "events": state["events"],
        }

    def stop(self) -> None:
        for p in self.procs.values():
            try:
                p.stop(timeout=10)
            except Exception:   # noqa: BLE001 — teardown must finish
                try:
                    p.kill()
                except Exception:   # noqa: BLE001
                    pass
        for proxy in self.proxies.values():
            try:
                proxy.stop()
            except Exception:   # noqa: BLE001
                pass
        self.server.stop()

    # -- chaos hooks (the tests' remote control) ---------------------------
    def worker(self, worker_id: str) -> WorkerProcess:
        return self.procs[worker_id]

    def kill_worker(self, worker_id: str) -> None:
        from deeplearning4j_tpu.resilience.faults import kill_worker
        kill_worker(self.procs[worker_id])
