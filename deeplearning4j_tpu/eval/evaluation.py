"""Evaluation metrics.

Parity surface: reference deeplearning4j-nn/.../eval/ — Evaluation.java
(accuracy/precision/recall/F1/confusion matrix), RegressionEvaluation.java
(MSE/MAE/RMSE/R², per-column), EvaluationBinary.java, ROC.java (AUC via
threshold sweep; here exact rank-based AUC).

Accumulation is numpy on host (cheap relative to the jit'd forward); the
heavy part — model inference — runs on TPU.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class Evaluation:
    """Multi-class classification metrics (parity: eval/Evaluation.java)."""

    def __init__(self, num_classes: Optional[int] = None, labels=None):
        self.num_classes = num_classes
        self.label_names = labels
        self.confusion: Optional[np.ndarray] = None

    def _ensure(self, n):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = np.zeros((self.num_classes, self.num_classes),
                                      np.int64)

    def eval(self, labels, predictions, mask=None):
        """labels/predictions: (B, C) one-hot/probs, or (B, T, C) time series
        (flattened with mask)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            B, T, C = labels.shape
            labels = labels.reshape(B * T, C)
            predictions = predictions.reshape(B * T, C)
            if mask is not None:
                m = np.asarray(mask).reshape(B * T) > 0
                labels, predictions = labels[m], predictions[m]
        self._ensure(labels.shape[-1])
        t = labels.argmax(-1)
        p = predictions.argmax(-1)
        np.add.at(self.confusion, (t, p), 1)
        return self

    # ---- metrics ----------------------------------------------------------
    def _tp(self):
        return np.diag(self.confusion).astype(np.float64)

    def accuracy(self):
        tot = self.confusion.sum()
        return float(self._tp().sum() / tot) if tot else 0.0

    def precision(self, cls=None):
        col = self.confusion.sum(axis=0).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(col > 0, self._tp() / col, 0.0)
        return float(per[cls]) if cls is not None else float(
            per[col > 0].mean() if (col > 0).any() else 0.0)

    def recall(self, cls=None):
        row = self.confusion.sum(axis=1).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(row > 0, self._tp() / row, 0.0)
        return float(per[cls]) if cls is not None else float(
            per[row > 0].mean() if (row > 0).any() else 0.0)

    def f1(self, cls=None):
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0

    def false_positive_rate(self, cls):
        fp = self.confusion[:, cls].sum() - self.confusion[cls, cls]
        tn = self.confusion.sum() - self.confusion[cls].sum() - \
            self.confusion[:, cls].sum() + self.confusion[cls, cls]
        return float(fp / (fp + tn)) if (fp + tn) else 0.0

    def matthews_correlation(self, cls):
        c = self.confusion
        tp = c[cls, cls]
        fp = c[:, cls].sum() - tp
        fn = c[cls].sum() - tp
        tn = c.sum() - tp - fp - fn
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return float((tp * tn - fp * fn) / denom) if denom else 0.0

    def stats(self):
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self.num_classes}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "",
            "=========================Confusion Matrix=========================",
            str(self.confusion),
            "==================================================================",
        ]
        return "\n".join(lines)

    def merge(self, other: "Evaluation"):
        if self.confusion is None:
            self.confusion = other.confusion.copy()
            self.num_classes = other.num_classes
        else:
            self.confusion += other.confusion
        return self


class EvaluationBinary:
    """Per-output binary metrics for multi-label nets
    (parity: eval/EvaluationBinary.java)."""

    def __init__(self, threshold=0.5):
        self.threshold = threshold
        self.tp = self.fp = self.tn = self.fn = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels).reshape(-1, np.asarray(labels).shape[-1])
        preds = (np.asarray(predictions).reshape(labels.shape) >= self.threshold)
        lab = labels >= 0.5
        if self.tp is None:
            n = labels.shape[-1]
            self.tp = np.zeros(n, np.int64)
            self.fp = np.zeros(n, np.int64)
            self.tn = np.zeros(n, np.int64)
            self.fn = np.zeros(n, np.int64)
        self.tp += (preds & lab).sum(0)
        self.fp += (preds & ~lab).sum(0)
        self.tn += (~preds & ~lab).sum(0)
        self.fn += (~preds & lab).sum(0)
        return self

    def accuracy(self, i):
        tot = self.tp[i] + self.fp[i] + self.tn[i] + self.fn[i]
        return float((self.tp[i] + self.tn[i]) / tot) if tot else 0.0

    def precision(self, i):
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i] / d) if d else 0.0

    def recall(self, i):
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i] / d) if d else 0.0

    def f1(self, i):
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0


class RegressionEvaluation:
    """Per-column regression metrics (parity: eval/RegressionEvaluation.java)."""

    def __init__(self, column_names=None):
        self.column_names = column_names
        self._n = 0
        self._sum_sq_err = None
        self._sum_abs_err = None
        self._sum_label = None
        self._sum_label_sq = None
        self._sum_pred = None
        self._sum_label_pred = None
        self._sum_pred_sq = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        preds = np.asarray(predictions, np.float64)
        labels = labels.reshape(-1, labels.shape[-1])
        preds = preds.reshape(-1, preds.shape[-1])
        if self._sum_sq_err is None:
            c = labels.shape[-1]
            for a in ("_sum_sq_err", "_sum_abs_err", "_sum_label",
                      "_sum_label_sq", "_sum_pred", "_sum_label_pred",
                      "_sum_pred_sq"):
                setattr(self, a, np.zeros(c))
        err = preds - labels
        self._n += labels.shape[0]
        self._sum_sq_err += (err ** 2).sum(0)
        self._sum_abs_err += np.abs(err).sum(0)
        self._sum_label += labels.sum(0)
        self._sum_label_sq += (labels ** 2).sum(0)
        self._sum_pred += preds.sum(0)
        self._sum_pred_sq += (preds ** 2).sum(0)
        self._sum_label_pred += (labels * preds).sum(0)
        return self

    def mean_squared_error(self, col=None):
        m = self._sum_sq_err / self._n
        return float(m[col]) if col is not None else float(m.mean())

    def mean_absolute_error(self, col=None):
        m = self._sum_abs_err / self._n
        return float(m[col]) if col is not None else float(m.mean())

    def root_mean_squared_error(self, col=None):
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col=None):
        ss_tot = self._sum_label_sq - self._sum_label ** 2 / self._n
        ss_res = self._sum_sq_err
        with np.errstate(divide="ignore", invalid="ignore"):
            r2 = np.where(ss_tot > 0, 1.0 - ss_res / ss_tot, 0.0)
        return float(r2[col]) if col is not None else float(r2.mean())

    def pearson_correlation(self, col=None):
        n = self._n
        cov = self._sum_label_pred - self._sum_label * self._sum_pred / n
        vl = self._sum_label_sq - self._sum_label ** 2 / n
        vp = self._sum_pred_sq - self._sum_pred ** 2 / n
        with np.errstate(divide="ignore", invalid="ignore"):
            r = np.where((vl > 0) & (vp > 0), cov / np.sqrt(vl * vp), 0.0)
        return float(r[col]) if col is not None else float(r.mean())

    def stats(self):
        return (f"MSE: {self.mean_squared_error():.6f}  "
                f"MAE: {self.mean_absolute_error():.6f}  "
                f"RMSE: {self.root_mean_squared_error():.6f}  "
                f"R^2: {self.r_squared():.6f}")


class ROC:
    """Binary ROC / AUC (parity: eval/ROC.java). Exact AUC via rank statistic
    rather than the reference's thresholded approximation."""

    def __init__(self):
        self.scores = []
        self.labels = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        preds = np.asarray(predictions)
        if labels.ndim > 1 and labels.shape[-1] == 2:
            labels = labels[..., 1]
            preds = preds[..., 1]
        self.labels.append(labels.reshape(-1))
        self.scores.append(preds.reshape(-1))
        return self

    def calculate_auc(self):
        y = np.concatenate(self.labels) >= 0.5
        s = np.concatenate(self.scores)
        n_pos, n_neg = int(y.sum()), int((~y).sum())
        if n_pos == 0 or n_neg == 0:
            return 0.5
        order = np.argsort(s, kind="mergesort")
        ranks = np.empty_like(order, dtype=np.float64)
        ranks[order] = np.arange(1, len(s) + 1)
        # average ranks for ties
        s_sorted = s[order]
        i = 0
        while i < len(s_sorted):
            j = i
            while j + 1 < len(s_sorted) and s_sorted[j + 1] == s_sorted[i]:
                j += 1
            if j > i:
                avg = (i + j + 2) / 2.0
                ranks[order[i:j + 1]] = avg
            i = j + 1
        return float((ranks[y].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))

    def roc_curve(self, steps=100):
        y = np.concatenate(self.labels) >= 0.5
        s = np.concatenate(self.scores)
        thresholds = np.linspace(0, 1, steps + 1)
        tpr, fpr = [], []
        for t in thresholds:
            pred = s >= t
            tp = (pred & y).sum()
            fp = (pred & ~y).sum()
            fn = (~pred & y).sum()
            tn = (~pred & ~y).sum()
            tpr.append(tp / max(tp + fn, 1))
            fpr.append(fp / max(fp + tn, 1))
        return np.array(fpr), np.array(tpr), thresholds


class ROCMultiClass:
    """One-vs-all ROC per class (parity: eval/ROCMultiClass.java)."""

    def __init__(self):
        self._rocs = {}

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels).reshape(-1, np.asarray(labels).shape[-1])
        preds = np.asarray(predictions).reshape(labels.shape)
        for c in range(labels.shape[-1]):
            self._rocs.setdefault(c, ROC()).eval(labels[:, c], preds[:, c])
        return self

    def calculate_auc(self, cls):
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self):
        return float(np.mean([r.calculate_auc() for r in self._rocs.values()]))
