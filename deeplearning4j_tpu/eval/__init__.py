from deeplearning4j_tpu.eval.evaluation import (
    Evaluation, RegressionEvaluation, EvaluationBinary, ROC, ROCMultiClass,
)
from deeplearning4j_tpu.eval.calibration import (
    EvaluationCalibration, ReliabilityDiagram, Histogram,
)

__all__ = ["Evaluation", "RegressionEvaluation", "EvaluationBinary", "ROC",
           "ROCMultiClass", "EvaluationCalibration", "ReliabilityDiagram",
           "Histogram"]
