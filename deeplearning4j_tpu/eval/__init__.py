from deeplearning4j_tpu.eval.evaluation import (
    Evaluation, RegressionEvaluation, EvaluationBinary, ROC, ROCMultiClass,
)

__all__ = ["Evaluation", "RegressionEvaluation", "EvaluationBinary", "ROC",
           "ROCMultiClass"]
