"""EvaluationCalibration — classifier calibration analysis.

Parity surface: reference eval/EvaluationCalibration.java:
- per-class reliability diagrams (positive fraction vs mean predicted
  probability per bin, :114-187 / getReliabilityDiagram :307),
- label / predicted-class count distributions (:343/:351),
- residual plots |label - p| overall and per label class (:362/:377),
- probability histograms overall and per label class (:388/:401),
all mask-aware (per-example column mask or per-output mask) and
time-series-capable (rank-3 inputs are flattened with the mask, the
evalTimeSeries path).

Accumulation is vectorized numpy on host, matching the module's convention
(the heavy part — inference — runs on TPU; see evaluation.py docstring).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

DEFAULT_RELIABILITY_DIAG_NUM_BINS = 10
DEFAULT_HISTOGRAM_NUM_BINS = 50


@dataclass
class ReliabilityDiagram:
    """One class's reliability curve (parity: curves/ReliabilityDiagram)."""
    title: str
    mean_predicted_value: np.ndarray    # (bins,) average p in each bin
    fraction_positives: np.ndarray      # (bins,) empirical positive fraction


@dataclass
class Histogram:
    """Fixed-range histogram (parity: curves/Histogram)."""
    title: str
    lower: float
    upper: float
    bin_counts: np.ndarray


class EvaluationCalibration:
    """Parity: eval/EvaluationCalibration.java:41."""

    def __init__(self,
                 reliability_num_bins: int = DEFAULT_RELIABILITY_DIAG_NUM_BINS,
                 histogram_num_bins: int = DEFAULT_HISTOGRAM_NUM_BINS):
        self.reliability_num_bins = reliability_num_bins
        self.histogram_num_bins = histogram_num_bins
        self._n = None          # num classes; arrays allocated on first eval
        self.reset()

    def reset(self):
        self._n = None
        self.rdiag_pos_count = None          # (rbins, C)
        self.rdiag_total_count = None        # (rbins, C)
        self.rdiag_sum_predictions = None    # (rbins, C)
        self.label_counts = None             # (C,)
        self.prediction_counts = None        # (C,)
        self.residual_overall = None         # (hbins,)
        self.residual_by_class = None        # (hbins, C)
        self.prob_overall = None             # (hbins,)
        self.prob_by_class = None            # (hbins, C)
        return self

    def _ensure(self, n):
        if self._n is not None:
            if n != self._n:
                raise ValueError(f"num classes changed: {self._n} -> {n}")
            return
        self._n = n
        rb, hb = self.reliability_num_bins, self.histogram_num_bins
        self.rdiag_pos_count = np.zeros((rb, n))
        self.rdiag_total_count = np.zeros((rb, n))
        self.rdiag_sum_predictions = np.zeros((rb, n))
        self.label_counts = np.zeros(n)
        self.prediction_counts = np.zeros(n)
        self.residual_overall = np.zeros(hb)
        self.residual_by_class = np.zeros((hb, n))
        self.prob_overall = np.zeros(hb)
        self.prob_by_class = np.zeros((hb, n))

    # ------------------------------------------------------------------ eval
    def eval(self, labels, predictions, mask=None):
        """labels/predictions: (B, C) or (B, T, C); mask: per-example (B,) /
        (B, T) for time series, or per-output (same shape as labels)."""
        l = np.asarray(labels, np.float64)
        p = np.asarray(predictions, np.float64)
        if l.ndim == 3:
            B, T, C = l.shape
            l = l.reshape(B * T, C)
            p = p.reshape(B * T, C)
            if mask is not None:
                mask = np.asarray(mask)
                # per-output (B,T,C) masks keep the class axis; per-example
                # (B,T) masks flatten to one weight per timestep
                mask = (mask.reshape(B * T, C) if mask.ndim == 3
                        else mask.reshape(-1))
        self._ensure(l.shape[-1])

        # normalize mask to a per-output (B, C) weight matrix
        if mask is None:
            w = np.ones_like(l)
        else:
            m = np.asarray(mask, np.float64)
            w = (np.broadcast_to(m[:, None], l.shape).copy()
                 if m.ndim == 1 else m)

        rb = self.reliability_num_bins
        # reliability bins: digitize p into rb bins over [0, 1]; the last
        # bin is closed above (p == 1.0 falls in bin rb-1) — reference
        # lte(1.0) edge case
        bins = np.minimum((p * rb).astype(np.int64), rb - 1)
        for j in range(rb):
            in_bin = (bins == j) * w
            self.rdiag_total_count[j] += in_bin.sum(axis=0)
            self.rdiag_pos_count[j] += (l * in_bin).sum(axis=0)
            self.rdiag_sum_predictions[j] += (p * in_bin).sum(axis=0)

        ex_w = (w.max(axis=1) > 0)           # rows with any live output
        self.label_counts += (l * w).sum(axis=0)
        # masked-out columns must not win the argmax for a row's predicted
        # class: exclude them (rows with no live column are dropped by ex_w)
        pred_cls = np.where(w > 0, p, -np.inf).argmax(axis=1)
        np.add.at(self.prediction_counts, pred_cls[ex_w], 1)

        # residuals |l - p| and probability histograms over [0, 1]
        hb = self.histogram_num_bins
        resid = np.abs(l - p)
        rbins = np.minimum((resid * hb).astype(np.int64), hb - 1)
        pbins = np.minimum((p * hb).astype(np.int64), hb - 1)
        live = w > 0
        np.add.at(self.residual_overall, rbins[live], 1)
        np.add.at(self.prob_overall, pbins[live], 1)
        # per-label-class: rows whose label is class c contribute their
        # residual/probability for class c
        lab_cls = l.argmax(axis=1)
        # a row only contributes per-class stats when its true-label column
        # is itself live under the per-output mask
        lab_live = np.take_along_axis(w, lab_cls[:, None], axis=1)[:, 0] > 0
        labeled = (l.max(axis=1) > 0) & ex_w & lab_live
        cls = lab_cls[labeled]
        np.add.at(self.residual_by_class,
                  (rbins[labeled, cls], cls), 1)
        np.add.at(self.prob_by_class, (pbins[labeled, cls], cls), 1)
        return self

    # --------------------------------------------------------------- getters
    def num_classes(self):
        return self._n

    def get_reliability_diagram(self, class_idx: int) -> ReliabilityDiagram:
        """Bins with zero count are dropped (reference :307-339)."""
        total = self.rdiag_total_count[:, class_idx]
        keep = total > 0
        mean_p = self.rdiag_sum_predictions[keep, class_idx] / total[keep]
        frac_pos = self.rdiag_pos_count[keep, class_idx] / total[keep]
        return ReliabilityDiagram(
            f"Reliability Diagram: Class {class_idx}", mean_p, frac_pos)

    def get_label_counts_each_class(self):
        return self.label_counts.astype(np.int64)

    def get_prediction_counts_each_class(self):
        return self.prediction_counts.astype(np.int64)

    def get_residual_plot_all_classes(self) -> Histogram:
        return Histogram("Residual Plot - All Predictions and Classes",
                         0.0, 1.0, self.residual_overall.astype(np.int64))

    def get_residual_plot(self, label_class_idx: int) -> Histogram:
        return Histogram(
            f"Residual Plot - Predictions for Label Class {label_class_idx}",
            0.0, 1.0,
            self.residual_by_class[:, label_class_idx].astype(np.int64))

    def get_probability_histogram_all_classes(self) -> Histogram:
        return Histogram("Network Probabilities Histogram - All Predictions "
                         "and Classes", 0.0, 1.0,
                         self.prob_overall.astype(np.int64))

    def get_probability_histogram(self, label_class_idx: int) -> Histogram:
        return Histogram(
            f"Network Probabilities Histogram - P(class {label_class_idx}) - "
            f"Data Labelled Class {label_class_idx}", 0.0, 1.0,
            self.prob_by_class[:, label_class_idx].astype(np.int64))

    # ------------------------------------------------------- merge/summary
    def merge(self, other: "EvaluationCalibration"):
        if other._n is None:
            return self
        if self._n is None:
            self._ensure(other._n)
        for attr in ("rdiag_pos_count", "rdiag_total_count",
                     "rdiag_sum_predictions", "label_counts",
                     "prediction_counts", "residual_overall",
                     "residual_by_class", "prob_overall", "prob_by_class"):
            getattr(self, attr).__iadd__(getattr(other, attr))
        return self

    def expected_calibration_error(self, class_idx: Optional[int] = None):
        """ECE = sum_bins (n_bin/N) * |acc_bin - conf_bin| — a standard
        summary the reference exposes only graphically."""
        if class_idx is None:
            tot = self.rdiag_total_count.sum(axis=1)
            pos = self.rdiag_pos_count.sum(axis=1)
            sp = self.rdiag_sum_predictions.sum(axis=1)
        else:
            tot = self.rdiag_total_count[:, class_idx]
            pos = self.rdiag_pos_count[:, class_idx]
            sp = self.rdiag_sum_predictions[:, class_idx]
        n = tot.sum()
        if n == 0:
            return 0.0
        keep = tot > 0
        return float(np.sum(tot[keep] / n *
                            np.abs(pos[keep] / tot[keep] - sp[keep] / tot[keep])))

    def stats(self):
        lines = ["===================Evaluation Calibration=================",
                 f" # of classes:  {self._n}",
                 f" Reliability bins: {self.reliability_num_bins}, "
                 f"histogram bins: {self.histogram_num_bins}",
                 f" Label counts:      {self.get_label_counts_each_class()}",
                 f" Prediction counts: {self.get_prediction_counts_each_class()}",
                 f" ECE (micro):       {self.expected_calibration_error():.4f}",
                 "=========================================================="]
        return "\n".join(lines)
