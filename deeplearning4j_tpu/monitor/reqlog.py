"""Wide-event request journal (docs/OBSERVABILITY.md "Request lifecycle").

One bounded, thread-safe ring of structured per-request records — the
"wide event" style of Dapper-lineage request telemetry: instead of a
request smearing its story across N metrics and M log lines, every
request appends ONE terminal record carrying its whole lifecycle
(identity, outcome, phase attribution, token and KV accounting, router
annotations). A p99 regression then links to a concrete, replayable
record instead of a histogram bucket.

Writers call :meth:`RequestLog.append` exactly once per request, at the
terminal outcome — completions AND rejections (shed / deadline /
queue-full), so the journal never under-counts the requests that hurt.
The ring is a ``deque(maxlen=capacity)``: appends are O(1), the oldest
record is dropped first, and the process never grows without bound.

Readers pull ``tail(n)`` (newest last) — served over HTTP as
``GET /requests?n=`` by both the InferenceServer (decode + predict
journals merged) and the Router (its annotation journal), and merged
fleet-wide by ``monitor/collect.py::collect_requests`` /
``tools/tail_requests.py``.

Records are plain dicts (JSON-ready). :func:`new_record` stamps the
common identity fields; writers add their per-source extras:

- ``source="decode"``: ``phases`` {queue, prefill, decode, verify},
  ``tokens_in/out``, ``spec`` {drafted, accepted}, ``kv``
  {peak_blocks, prefix_hit_depth, host_restores}.
- ``source="predict"``: ``phases`` {queue, bucket, pad, device,
  readback}, ``rows``, ``batch``.
- ``source="router"``: ``attempts``, ``attempt_rids``,
  ``hedge_winner``, ``affinity_hit``, ``replica``, ``status``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

__all__ = ["RequestLog", "new_record"]

#: terminal outcomes a record may carry (informational — not enforced,
#: so a new writer can extend the vocabulary without touching this file)
OUTCOMES = ("ok", "eos", "max_new", "shed", "deadline", "error",
            "failed_over", "hedge_win")


def new_record(request_id: Optional[str], source: str, **fields) -> dict:
    """A journal record with the common identity fields stamped.

    ``ts`` is wall-clock epoch seconds at terminal time (so records from
    different processes merge onto one timeline, same anchor discipline
    as the tracer); everything else is the writer's business.
    """
    rec = {"request_id": request_id,
           "source": source,
           "ts": time.time(),
           "trace_id": None,
           "outcome": None,
           "tenant": "default",
           "priority": "normal",
           "wall_seconds": None}
    rec.update(fields)
    return rec


class RequestLog:
    """Bounded, thread-safe ring of terminal request records.

    ``capacity`` bounds memory; when full, the OLDEST record is dropped
    (``total`` keeps counting, so ``dropped = total - len`` is visible
    in :meth:`snapshot` — a scraper can tell the journal wrapped).
    """

    def __init__(self, capacity: int = 512):
        self.capacity = max(int(capacity), 1)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._total = 0

    def append(self, record: dict) -> dict:
        """Append one terminal record (oldest dropped when full)."""
        with self._lock:
            self._total += 1
            self._ring.append(record)
        return record

    def tail(self, n: Optional[int] = None) -> List[dict]:
        """The newest ``n`` records, oldest first (all when ``n`` is
        None; ``n <= 0`` returns [])."""
        with self._lock:
            recs = list(self._ring)
        if n is None:
            return recs
        n = int(n)
        return recs[-n:] if n > 0 else []

    def find(self, request_id: str) -> Optional[dict]:
        """Newest record for ``request_id`` (exact match), or None."""
        with self._lock:
            recs = list(self._ring)
        for rec in reversed(recs):
            if rec.get("request_id") == request_id:
                return rec
        return None

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def total(self) -> int:
        """Records ever appended (dropped ones included)."""
        with self._lock:
            return self._total

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._total - len(self._ring)

    def clear(self) -> "RequestLog":
        with self._lock:
            self._ring.clear()
            self._total = 0
        return self

    def snapshot(self, n: Optional[int] = None) -> dict:
        """JSON-ready document: ring accounting + the newest ``n``
        records (what ``GET /requests?n=`` serves)."""
        with self._lock:
            recs = list(self._ring)
            total = self._total
        dropped = total - len(recs)
        if n is not None:
            n = int(n)
            recs = recs[-n:] if n > 0 else []
        return {"capacity": self.capacity,
                "total": total,
                "dropped": dropped,
                "records": recs}
