"""Unified observability: pull metrics + span tracing, zero external deps.

Two stores, one subsystem:

- ``metrics`` — a process-wide ``MetricsRegistry`` of Counter / Gauge /
  fixed-bucket Histogram families (labels supported) rendered in the
  Prometheus text exposition format. Scraped at ``GET /metrics`` on the
  serving server; read in-process by ``/stats``, the UI StatsListener and
  bench row snapshots — all the same numbers, so surfaces cannot drift.
- ``tracing`` — a ring-buffered span tracer (``with trace.span("step")``)
  exporting Chrome trace-event JSON for Perfetto; spans cover the train
  loop (wait/fetch/h2d/step/callback) and the serving path
  (enqueue/bucket/pad/device/readback).

Fleet additions (docs/OBSERVABILITY.md):

- ``tracing.TraceContext`` — Dapper-style trace identity minted at the
  router, propagated via ``x-trace-context``; tracer timestamps share
  the wall-clock epoch so ``collect.collect_fleet_trace`` can merge
  every process's ring buffer into ONE Perfetto document.
- ``slo.BurnRateSLO`` — multi-window (5 m / 1 h) error-budget burn-rate
  health, wired into router and replica ``/healthz``.
- ``profiling`` — ``POST /admin/profile`` around live traffic and
  ``DL4JTPU_PROFILE=dir`` around ``fit()``.
- ``reqlog`` — the wide-event request journal: one terminal record per
  request (phases, outcome, spec/KV accounting), served at
  ``GET /requests`` and merged fleet-wide by ``collect.collect_requests``
  (docs/OBSERVABILITY.md "Request lifecycle").
- ``flight`` — the training flight recorder: per-layer telemetry
  computed inside the jitted train step, a crash-safe ring of recent
  records (``GET /train/diagnostics``), anomaly detection, Perfetto
  counter tracks (``collect.flight_counter_events``).

Both stores are cheap enough to leave on (see the bench's
``observability`` row); tracing is opt-in via ``trace.enable()`` /
``DL4JTPU_TRACE``. Metric name catalog and usage in
docs/OBSERVABILITY.md (linted by tools/lint_metrics.py).
"""

from deeplearning4j_tpu.monitor.metrics import (
    Counter, Gauge, Histogram, MetricsRegistry, get_registry,
    set_metrics_enabled, DEFAULT_LATENCY_BUCKETS, DEFAULT_STEP_BUCKETS)
from deeplearning4j_tpu.monitor.tracing import (
    Tracer, trace, get_tracer,
    TraceContext, set_context, get_context, trace_context)
from deeplearning4j_tpu.monitor.slo import BurnRateSLO, SLOState
from deeplearning4j_tpu.monitor.collect import (
    collect_fleet_trace, collect_requests, merge_docs,
    flight_counter_events)
from deeplearning4j_tpu.monitor.reqlog import RequestLog, new_record
from deeplearning4j_tpu.monitor.flight import (
    FlightRecorder, AnomalyDetector, STAT_COLS)
from deeplearning4j_tpu.monitor.profiling import (
    start_profile, profile_status, profile_scope)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "set_metrics_enabled",
    "DEFAULT_LATENCY_BUCKETS", "DEFAULT_STEP_BUCKETS",
    "Tracer", "trace", "get_tracer",
    "TraceContext", "set_context", "get_context", "trace_context",
    "BurnRateSLO", "SLOState",
    "collect_fleet_trace", "collect_requests", "merge_docs",
    "flight_counter_events", "RequestLog", "new_record",
    "FlightRecorder", "AnomalyDetector", "STAT_COLS",
    "start_profile", "profile_status", "profile_scope",
]
