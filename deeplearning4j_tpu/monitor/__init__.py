"""Unified observability: pull metrics + span tracing, zero external deps.

Two stores, one subsystem:

- ``metrics`` — a process-wide ``MetricsRegistry`` of Counter / Gauge /
  fixed-bucket Histogram families (labels supported) rendered in the
  Prometheus text exposition format. Scraped at ``GET /metrics`` on the
  serving server; read in-process by ``/stats``, the UI StatsListener and
  bench row snapshots — all the same numbers, so surfaces cannot drift.
- ``tracing`` — a ring-buffered span tracer (``with trace.span("step")``)
  exporting Chrome trace-event JSON for Perfetto; spans cover the train
  loop (wait/fetch/h2d/step/callback) and the serving path
  (enqueue/bucket/pad/device/readback).

Both are cheap enough to leave on (see the bench's
``observability_overhead`` row); tracing is opt-in via
``trace.enable()`` / ``DL4JTPU_TRACE``. Metric name catalog and usage in
docs/OBSERVABILITY.md.
"""

from deeplearning4j_tpu.monitor.metrics import (
    Counter, Gauge, Histogram, MetricsRegistry, get_registry,
    set_metrics_enabled, DEFAULT_LATENCY_BUCKETS, DEFAULT_STEP_BUCKETS)
from deeplearning4j_tpu.monitor.tracing import Tracer, trace, get_tracer

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "get_registry", "set_metrics_enabled",
    "DEFAULT_LATENCY_BUCKETS", "DEFAULT_STEP_BUCKETS",
    "Tracer", "trace", "get_tracer",
]
