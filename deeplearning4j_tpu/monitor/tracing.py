"""Low-overhead span tracing exported as Chrome trace-event JSON.

The per-step timeline half of the observability subsystem (fleet counters
are ``monitor/metrics.py``). Spans follow the Dapper model (Sigelman et
al., 2010): nestable named intervals recorded per thread, serialized as
``B``/``E`` (duration begin/end) events in the Chrome trace-event format
— load the exported file straight into Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing`` and the ``train_step``
spans visually nest their ``wait``/``fetch``/``h2d``/``step``/
``callback`` children; the serving path shows
``enqueue``/``bucket``/``pad``/``device``/``readback``.

Fleet tracing: timestamps are anchored to the unix epoch (wall clock) so
spans recorded by *different processes* — the router, each replica
subprocess — merge onto one timeline. A :class:`TraceContext` minted at
the router rides the ``x-trace-context`` HTTP header into every replica;
while a context is installed (thread-local), every span records its
``trace_id`` so a collected fleet document can be filtered to one
request's path end to end. ``monitor/collect.py`` pulls each process's
ring buffer over ``GET /trace`` and emits the single merged document.

Overhead discipline: tracing is OFF by default; a disabled tracer's
``span()`` returns one shared no-op context manager (no allocation, no
clock read). Enabled, argless spans are cached per name (no per-call
allocation); each span costs two ``perf_counter`` reads and two dict
appends into a bounded ring buffer (old events are dropped, the process
never grows without bound). The bench's ``observability`` row pins the
cost of both states.

Enable via code (``trace.enable()``) or environment::

    DL4JTPU_TRACE=1                 # collect; export manually
    DL4JTPU_TRACE=/tmp/step.json    # collect + auto-export at exit
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Optional

__all__ = [
    "Tracer", "trace", "get_tracer",
    "TraceContext", "set_context", "get_context", "trace_context",
]


# ------------------------------------------------------------- context
class TraceContext:
    """Dapper-style trace identity carried across process boundaries.

    ``trace_id`` names the whole request tree (the router mints it from
    the request id); ``parent`` names the span that caused this process
    to do work (e.g. the router attempt ``req-...#a1``). Serialized as
    the ``x-trace-context`` header: ``trace_id`` or ``trace_id;parent``.
    """

    __slots__ = ("trace_id", "parent")

    def __init__(self, trace_id: str, parent: str = ""):
        self.trace_id = trace_id
        self.parent = parent

    def child(self, parent: str) -> "TraceContext":
        return TraceContext(self.trace_id, parent)

    def to_header(self) -> str:
        return f"{self.trace_id};{self.parent}" if self.parent else self.trace_id

    @classmethod
    def from_header(cls, value) -> Optional["TraceContext"]:
        if not value:
            return None
        value = value.strip()
        if not value:
            return None
        trace_id, _, parent = value.partition(";")
        return cls(trace_id, parent)

    def __repr__(self):  # pragma: no cover - debug aid
        return f"TraceContext({self.trace_id!r}, parent={self.parent!r})"


_CTX = threading.local()


def set_context(ctx: Optional[TraceContext]) -> None:
    """Install ``ctx`` as this thread's current trace context."""
    _CTX.ctx = ctx


def get_context() -> Optional[TraceContext]:
    return getattr(_CTX, "ctx", None)


class _CtxScope:
    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        self._prev = getattr(_CTX, "ctx", None)
        _CTX.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _CTX.ctx = self._prev
        return False


def trace_context(ctx: Optional[TraceContext]) -> _CtxScope:
    """``with trace_context(ctx): ...`` — install for a scope, restoring
    the previous context on exit (re-entrant, per-thread)."""
    return _CtxScope(ctx)


# ---------------------------------------------------------------- spans
class _NullSpan:
    """Shared no-op context manager returned while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tr", "_name", "_args")

    def __init__(self, tr, name, args):
        self._tr = tr
        self._name = name
        self._args = args

    def __enter__(self):
        tr = self._tr
        ev = {"ph": "B", "name": self._name, "pid": tr._pid,
              "tid": threading.get_ident(),
              "ts": (tr._epoch + time.perf_counter()) * 1e6}
        args = self._args
        ctx = getattr(_CTX, "ctx", None)
        if ctx is not None:
            # never mutate self._args: argless spans are cached + shared
            args = dict(args) if args else {}
            args["trace_id"] = ctx.trace_id
            if ctx.parent:
                args["parent"] = ctx.parent
        if args:
            ev["args"] = args
        tr._events.append(ev)
        return self

    def __exit__(self, *exc):
        tr = self._tr
        tr._events.append(
            {"ph": "E", "name": self._name, "pid": tr._pid,
             "tid": threading.get_ident(),
             "ts": (tr._epoch + time.perf_counter()) * 1e6})
        return False


class Tracer:
    """Ring-buffered span recorder.

    ``capacity`` bounds memory: a deque(maxlen) of event dicts — at the
    default 200k events (~100k spans) a steady-state training loop keeps
    the most recent few thousand steps, which is what a stall
    investigation actually looks at.

    Timestamps are wall-clock microseconds (``time.time()`` anchored
    once, advanced by ``perf_counter`` so they stay monotonic within the
    process): every process shares the epoch, which is what lets
    ``monitor/collect.py`` merge ring buffers from N processes onto one
    Perfetto timeline."""

    def __init__(self, capacity: int = 200_000, enabled: bool = False):
        self._capacity = int(capacity)
        self._events = deque(maxlen=self._capacity)
        self._enabled = bool(enabled)
        self._pid = os.getpid()
        # wall-clock anchor: ts = (_epoch + perf_counter()) seconds
        self._epoch = time.time() - time.perf_counter()
        self._process_name = ""
        self._argless = {}

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, on: bool = True) -> "Tracer":
        self._enabled = bool(on)
        return self

    def set_process_name(self, name: str) -> "Tracer":
        """Name this process's track in merged fleet traces (emitted as a
        Chrome ``process_name`` metadata event on export)."""
        self._process_name = str(name)
        return self

    @property
    def process_name(self) -> str:
        return self._process_name

    def clear(self) -> "Tracer":
        # rebind rather than .clear(): a concurrent span/instant append
        # lands harmlessly in the old deque instead of racing the wipe
        self._events = deque(maxlen=self._capacity)
        return self

    def span(self, name: str, **args):
        """``with trace.span("step"): ...`` — nest freely; disabled
        tracing returns a shared no-op (near-zero cost)."""
        if not self._enabled:
            return _NULL_SPAN
        if not args:
            # argless spans (the hot-path kind) are immutable: cache one
            # instance per name instead of allocating per call
            s = self._argless.get(name)
            if s is None:
                s = self._argless[name] = _Span(self, name, None)
            return s
        return _Span(self, name, args)

    def instant(self, name: str, **args):
        """Point-in-time marker (Chrome ``i`` event)."""
        if not self._enabled:
            return
        ev = {"ph": "i", "name": name, "pid": self._pid,
              "tid": threading.get_ident(), "s": "t",
              "ts": (self._epoch + time.perf_counter()) * 1e6}
        ctx = getattr(_CTX, "ctx", None)
        if ctx is not None:
            args = dict(args) if args else {}
            args["trace_id"] = ctx.trace_id
        if args:
            ev["args"] = args
        self._events.append(ev)

    def events(self) -> list:
        return list(self._events)

    def export(self, path: Optional[str] = None) -> dict:
        """The Chrome trace-event document; written to ``path`` as JSON
        when given.

        Events are sorted by timestamp, and ``E`` events whose matching
        ``B`` fell off the ring (a wrap keeps the end of a span whose
        begin was dropped) are removed — an unbalanced ``E`` makes
        Perfetto close the *wrong* enclosing span, mis-nesting the whole
        track. A ``B`` without an ``E`` (span still open) is fine and is
        kept."""
        events = sorted(self._events, key=lambda e: e["ts"])
        kept, depth = [], {}
        for ev in events:
            ph = ev["ph"]
            if ph == "B":
                key = (ev["pid"], ev["tid"])
                depth[key] = depth.get(key, 0) + 1
            elif ph == "E":
                key = (ev["pid"], ev["tid"])
                d = depth.get(key, 0)
                if d <= 0:
                    continue  # orphan E: its B was dropped by the ring
                depth[key] = d - 1
            kept.append(ev)
        meta = []
        if self._process_name:
            meta.append({"ph": "M", "name": "process_name",
                         "pid": self._pid, "tid": 0,
                         "args": {"name": self._process_name}})
        doc = {"traceEvents": meta + kept, "displayTimeUnit": "ms"}
        if path:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc


# ---------------------------------------------------------------- default
# The process-wide tracer every instrumented path records into (the span
# analog of metrics.get_registry()).
trace = Tracer()


def get_tracer() -> Tracer:
    return trace


_env = os.environ.get("DL4JTPU_TRACE", "")
if _env and _env.lower() not in ("0", "false", "off", "no"):
    trace.enable(True)
    if os.sep in _env or _env.endswith(".json"):
        atexit.register(trace.export, _env)
