"""Low-overhead span tracing exported as Chrome trace-event JSON.

The per-step timeline half of the observability subsystem (fleet counters
are ``monitor/metrics.py``). Spans follow the Dapper model (Sigelman et
al., 2010) collapsed to one process: nestable named intervals recorded
per thread, serialized as ``B``/``E`` (duration begin/end) events in the
Chrome trace-event format — load the exported file straight into
Perfetto (https://ui.perfetto.dev) or ``chrome://tracing`` and the
``train_step`` spans visually nest their ``wait``/``fetch``/``h2d``/
``step``/``callback`` children; the serving path shows
``enqueue``/``bucket``/``pad``/``device``/``readback``.

Overhead discipline: tracing is OFF by default; a disabled tracer's
``span()`` returns one shared no-op context manager (no allocation, no
clock read). Enabled, each span costs two ``perf_counter`` reads and two
dict appends into a bounded ring buffer (old events are dropped, the
process never grows without bound). The bench's ``observability_overhead``
row pins the cost of both states.

Enable via code (``trace.enable()``) or environment::

    DL4JTPU_TRACE=1                 # collect; export manually
    DL4JTPU_TRACE=/tmp/step.json    # collect + auto-export at exit
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from typing import Optional

__all__ = ["Tracer", "trace", "get_tracer"]


class _NullSpan:
    """Shared no-op context manager returned while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tr", "_name", "_args")

    def __init__(self, tr, name, args):
        self._tr = tr
        self._name = name
        self._args = args

    def __enter__(self):
        tr = self._tr
        ev = {"ph": "B", "name": self._name, "pid": tr._pid,
              "tid": threading.get_ident(),
              "ts": (time.perf_counter() - tr._t0) * 1e6}
        if self._args:
            ev["args"] = self._args
        tr._events.append(ev)
        return self

    def __exit__(self, *exc):
        tr = self._tr
        tr._events.append(
            {"ph": "E", "name": self._name, "pid": tr._pid,
             "tid": threading.get_ident(),
             "ts": (time.perf_counter() - tr._t0) * 1e6})
        return False


class Tracer:
    """Ring-buffered span recorder.

    ``capacity`` bounds memory: a deque(maxlen) of event dicts — at the
    default 200k events (~100k spans) a steady-state training loop keeps
    the most recent few thousand steps, which is what a stall
    investigation actually looks at."""

    def __init__(self, capacity: int = 200_000, enabled: bool = False):
        self._events = deque(maxlen=int(capacity))
        self._enabled = bool(enabled)
        self._pid = os.getpid()
        self._t0 = time.perf_counter()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, on: bool = True) -> "Tracer":
        self._enabled = bool(on)
        return self

    def clear(self) -> "Tracer":
        self._events.clear()
        return self

    def span(self, name: str, **args):
        """``with trace.span("step"): ...`` — nest freely; disabled
        tracing returns a shared no-op (near-zero cost)."""
        if not self._enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def instant(self, name: str, **args):
        """Point-in-time marker (Chrome ``i`` event)."""
        if not self._enabled:
            return
        ev = {"ph": "i", "name": name, "pid": self._pid,
              "tid": threading.get_ident(), "s": "t",
              "ts": (time.perf_counter() - self._t0) * 1e6}
        if args:
            ev["args"] = args
        self._events.append(ev)

    def events(self) -> list:
        return list(self._events)

    def export(self, path: Optional[str] = None) -> dict:
        """The Chrome trace-event document; written to ``path`` as JSON
        when given. Events are sorted by timestamp so a ring-buffer wrap
        (which may drop a ``B`` while keeping its ``E``) still loads."""
        doc = {"traceEvents": sorted(self._events, key=lambda e: e["ts"]),
               "displayTimeUnit": "ms"}
        if path:
            with open(path, "w") as f:
                json.dump(doc, f)
        return doc


# ---------------------------------------------------------------- default
# The process-wide tracer every instrumented path records into (the span
# analog of metrics.get_registry()).
trace = Tracer()


def get_tracer() -> Tracer:
    return trace


_env = os.environ.get("DL4JTPU_TRACE", "")
if _env and _env.lower() not in ("0", "false", "off", "no"):
    trace.enable(True)
    if os.sep in _env or _env.endswith(".json"):
        atexit.register(trace.export, _env)
